"""Benchmark: registry -> TPU HBM load, TTFT, and serving throughput.

Stands up a local registry, pushes a synthetic llama-shaped bf16 checkpoint,
then measures:

- baseline: the reference's deployment shape — download the blob to a pod
  volume as one sequential stream (modelxdl semantics, pull.go:111-143),
  then read it and device_put tensor-by-tensor;
- modelx-tpu: the loader path — blob-location redirect (file provider for
  the colocated registry, ranged HTTP otherwise) planned from the manifest's
  tensor index, streamed into device memory overlapped with fetches;
- link probe: raw host->device bandwidth of this rig (the tunnel to the TPU
  is the hard ceiling for any loader; report it so the ratio value/link is
  interpretable and a degraded run is visible as a degraded link, not
  mistaken for a code regression);
- ttft_ms: p50 time from "fresh process asks the registry for the model" to
  "first decoded token", warm persistent XLA cache (BASELINE.md north star);
- serving: prefill/decode tokens/s and MFU for the pushed model.

Both timed legs alternate with settle pauses: the TPU tunnel on this rig is
token-bucket shaped (a burst allowance, then a lower sustained rate), so
back-to-back legs would hand whichever ran first an unearned advantage.

Prints ONE JSON line; "value" stays registry->HBM GB/s (the BASELINE
metric), extras carry the rest.
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

import numpy as np

# Per-chip peaks used for MFU / bandwidth-utilization. Public specs:
# v5e 197 bf16 TFLOP/s + 819 GB/s HBM; v5p 459 TFLOP/s + 2765 GB/s;
# v4 275 TFLOP/s + 1228 GB/s. Longest-prefix match wins ("TPU v5p" must not
# fall into the v5e bucket).
PEAK_FLOPS = {"TPU v5p": 459e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
              "TPU v4": 275e12, "cpu": 1e12}
HBM_GBPS = {"TPU v5p": 2765e9, "TPU v5 lite": 819e9, "TPU v5e": 819e9,
            "TPU v4": 1228e9, "cpu": 100e9}


def _chip_spec(table: dict, device_kind: str, default: float) -> float:
    for k, v in table.items():
        if device_kind.startswith(k):
            return v
    return default


def build_checkpoint(path: str, target_bytes: int, hidden: int = 2048,
                     inter: int = 5632, vocab: int = 32000) -> int:
    """Synthetic llama-shaped checkpoint (bf16) of roughly target_bytes."""
    import ml_dtypes

    from modelx_tpu.dl import safetensors as st

    rng = np.random.RandomState(0)
    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": rng.rand(vocab, hidden).astype(ml_dtypes.bfloat16),
        "model.norm.weight": np.ones((hidden,), ml_dtypes.bfloat16),
    }
    layer_bytes = 2 * (4 * hidden * hidden + 3 * hidden * inter + 2 * hidden)
    base = 2 * vocab * hidden
    layers = max(1, (target_bytes - base) // layer_bytes)
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors[p + "self_attn.q_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.k_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.v_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.o_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.gate_proj.weight"] = rng.rand(inter, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.up_proj.weight"] = rng.rand(inter, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.down_proj.weight"] = rng.rand(hidden, inter).astype(ml_dtypes.bfloat16)
        tensors[p + "input_layernorm.weight"] = np.ones((hidden,), ml_dtypes.bfloat16)
        tensors[p + "post_attention_layernorm.weight"] = np.ones((hidden,), ml_dtypes.bfloat16)
    st.write_safetensors(path, tensors)
    return os.path.getsize(path)


def start_registry(workdir: str) -> tuple[subprocess.Popen, str]:
    from modelx_tpu.registry.server import free_port

    port = free_port()
    base = f"http://127.0.0.1:{port}"
    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)),
               JAX_PLATFORMS="cpu")
    srv = subprocess.Popen(
        [sys.executable, "-m", "modelx_tpu.cli", "serve",
         "--listen", f"127.0.0.1:{port}",
         "--data", os.path.join(workdir, "registry")],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    import requests

    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except Exception:
            time.sleep(0.2)
    return srv, base


def push_checkpoint(base: str, repo: str, ckpt: str):
    from modelx_tpu.client.client import Client
    from modelx_tpu.client.helper import descriptor_for_file
    from modelx_tpu.client.push import _annotate_safetensors
    from modelx_tpu.types import Manifest

    client = Client(base, quiet=True)
    desc = descriptor_for_file(ckpt, "model.safetensors", "application/vnd.modelx.model.file.v1")
    _annotate_safetensors(ckpt, desc)
    with open(ckpt, "rb") as f:
        client.remote.upload_blob_content(repo, desc, f)
    client.remote.put_manifest(repo, "v1", Manifest(blobs=[desc]))
    return client, desc


def probe_link_gbps(device, nbytes: int = 16 << 20, reps: int = 3) -> float:
    """Median raw host->device bandwidth for random (incompressible) bytes."""
    import jax

    a = np.random.randint(0, 256, nbytes, dtype=np.uint8)
    x = jax.device_put(a, device)
    x.block_until_ready()
    del x
    ts = []
    for _ in range(reps):
        t0 = time.monotonic()
        x = jax.device_put(a, device)
        x.block_until_ready()
        ts.append(time.monotonic() - t0)
        del x
    return nbytes / statistics.median(ts) / 1e9


def run_ours(client, repo: str, desc, mesh, size: int) -> tuple[float, str]:
    """The loader path through the blob-location seam. Returns (seconds,
    source-class name actually used — proves which engine ran)."""
    from modelx_tpu.dl.initializer import _blob_source
    from modelx_tpu.dl.loader import load_safetensors
    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.sharding import LLAMA_RULES

    t0 = time.monotonic()
    source = _blob_source(client, repo, desc)
    tensors = data_offset = None
    from modelx_tpu.types import AnnotationTensorIndex

    if AnnotationTensorIndex in desc.annotations:
        tensors, data_offset = st.parse_index_annotation(desc.annotations[AnnotationTensorIndex])
    try:
        loaded, stats = load_safetensors(
            source, mesh, LLAMA_RULES, tensors=tensors, data_offset=data_offset
        )
    finally:
        if hasattr(source, "close"):
            source.close()
    seconds = time.monotonic() - t0
    del loaded
    return seconds, type(source).__name__


def run_baseline(base: str, repo: str, desc, workdir: str, devices) -> float:
    """Reference deployment shape: one sequential download to a volume file,
    then read + per-tensor device_put (cmd/modelxdl semantics)."""
    import jax
    import requests

    from modelx_tpu.dl import safetensors as st

    url = f"{base}/{repo}/blobs/{desc.digest}"
    t0 = time.monotonic()
    vol = os.path.join(workdir, "volume.safetensors")
    with requests.get(url, stream=True) as r, open(vol, "wb") as f:
        for chunk in r.iter_content(chunk_size=1024 * 1024):
            f.write(chunk)
    arrays = []
    with open(vol, "rb") as f:
        infos, off = st.read_header(f)
        for name, info in infos.items():
            f.seek(off + info.start)
            raw = f.read(info.nbytes)
            arr = np.frombuffer(raw, info.np_dtype()).reshape(info.shape)
            arrays.append(jax.device_put(arr, devices[0]))
    jax.block_until_ready(arrays)
    seconds = time.monotonic() - t0
    del arrays
    os.unlink(vol)
    return seconds


def measure_ttft(base: str, repo: str, workdir: str, runs: int = 5) -> dict:
    """p50 registry->first-token (BASELINE north star), warm persistent XLA
    cache. Each run starts from a cleared in-process jit cache
    (``jax.clear_caches``): the deploy being modeled is a fresh sidecar that
    ships a pre-warmed persistent compile cache but must re-trace and fetch
    weights. The TPU on this rig is single-tenant, so a subprocess-per-run
    harness can't hold the device while the bench does.

    The flow is the product's overlap: the manifest's tensor-index
    annotation fully describes the architecture, so the prefill program
    AOT-compiles on a side thread while the loader streams weight bytes —
    the first token pays max(load, compile), not the sum. First decoded
    token == argmax of the prefill logits' last position (greedy); the
    decode-with-cache program compiles off the TTFT clock."""
    import threading

    import jax

    from modelx_tpu.client.client import Client
    from modelx_tpu.dl import families as fam
    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.initializer import load_to_mesh
    from modelx_tpu.dl.loader import fuse_expert_tensors
    from modelx_tpu.dl.serve import enable_compile_cache
    from modelx_tpu.parallel.mesh import make_mesh
    from modelx_tpu.types import AnnotationTensorIndex

    cache_dir = os.path.join(workdir, "xla-cache")
    enable_compile_cache(cache_dir)
    samples, load_ms, token_ms = [], [], []
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    for i in range(runs + 1):  # run 0 warms the persistent cache, unscored
        jax.clear_caches()
        t0 = time.monotonic()
        client = Client(base, quiet=True)
        manifest = client.get_manifest(repo, "v1")
        # architecture from the manifest alone -> compile while bytes stream
        infos: dict = {}
        for blob in manifest.blobs:
            if AnnotationTensorIndex in blob.annotations:
                parsed, _off = st.parse_index_annotation(blob.annotations[AnnotationTensorIndex])
                infos.update(parsed)
        mesh = make_mesh("dp=1")
        family = fam.detect(list(infos))
        infos = fuse_expert_tensors(infos, family.rules)
        cfg = family.infer_config(fam.abstract_params(infos))
        sds = fam.abstract_params(infos, family.rules, mesh)
        compiled: dict = {}

        def _compile(family=family, cfg=cfg, sds=sds, mesh=mesh, out=compiled):
            try:
                out["fwd"] = fam.precompile_forward(
                    family, cfg, sds, prompt.shape, mesh=mesh, mode="argmax_last"
                )
            except BaseException as e:  # re-raised on the measuring thread
                out["error"] = e

        th = threading.Thread(target=_compile, daemon=True)
        th.start()
        out = load_to_mesh(client, repo, manifest, mesh_spec="dp=1")
        params = out["arrays"]
        t1 = time.monotonic()
        th.join()
        if "error" in compiled:
            raise RuntimeError("ttft precompile failed") from compiled["error"]
        first = compiled["fwd"](params, jax.numpy.asarray(prompt))
        np.asarray(first)
        t2 = time.monotonic()
        del params, out, first, compiled
        if i > 0:
            samples.append((t2 - t0) * 1e3)
            load_ms.append((t1 - t0) * 1e3)
            token_ms.append((t2 - t1) * 1e3)
    if not samples:
        return {}
    return {
        "ttft_ms": round(statistics.median(samples), 1),
        "ttft_ms_runs": [round(s, 1) for s in samples],
        "ttft_load_ms": round(statistics.median(load_ms), 1),
        "ttft_compile_token_ms": round(statistics.median(token_ms), 1),
    }


# stdlib-only puller (no jax import: interpreter startup must not drown the
# transfer on a small-core host) — http.client + readinto into one reused
# buffer, the same zero-copy discipline the loader's HTTPSource uses. The
# stream is consumed, counted, and discarded: in the deployment being
# modeled each tenant lands bytes on its own pod volume (or straight in
# HBM), so N tenants funneling ~2 GB through THIS rig's one shared disk
# would measure the kernel's dirty-page writeback throttle, not the
# registry's data plane. Byte count goes to stdout for verification.
_PULL_SNIPPET = r"""
import sys, time, http.client, urllib.parse
url = sys.argv[1]
u = urllib.parse.urlsplit(url)
t0 = time.monotonic()
conn = http.client.HTTPConnection(u.hostname, u.port, timeout=300)
conn.request("GET", u.path)
resp = conn.getresponse()
assert resp.status == 200, resp.status
buf = bytearray(16 << 20)
view = memoryview(buf)
n = 0
while True:
    got = resp.readinto(view)
    if not got:
        break
    n += got
print(time.monotonic() - t0, n)
"""


def measure_multitenant(base: str, repo: str, desc, size: int,
                        clients: int = 4) -> dict:
    """BASELINE config #5: N tenants pulling concurrently from one registry.
    Each tenant is its own process (the pod shape), streaming through the
    server's direct GET — this stresses the registry data plane itself;
    colocated tenants would take the file redirect and not touch it at all.
    Pass = aggregate GB/s with N clients >= 1 client."""
    url = f"{base}/{repo}/blobs/{desc.digest}"

    # -S + clean env: this image's sitecustomize imports accelerator
    # machinery into every interpreter, which would bill multi-second
    # startup to the transfer
    env = {"PATH": os.environ.get("PATH", "")}

    def run_n(n: int) -> float:
        procs = []
        t0 = time.monotonic()
        for i in range(n):
            procs.append(subprocess.Popen(
                [sys.executable, "-S", "-c", _PULL_SNIPPET, url],
                stdout=subprocess.PIPE, text=True, env=env))
        outs = []
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"multitenant puller {i} exited {p.returncode}")
            outs.append(out)
        wall = time.monotonic() - t0
        for i, out in enumerate(outs):
            got = int(out.split()[1])
            if got != size:  # a partial transfer must not inflate the GB/s
                raise RuntimeError(f"multitenant puller {i}: {got} of {size} bytes")
        return wall

    run_n(1)  # warm page cache + interpreter startup path
    single = run_n(1)
    multi = run_n(clients)
    return {
        "mt_clients": clients,
        "mt_single_gbps": round(size / single / 1e9, 3),
        "mt_aggregate_gbps": round(clients * size / multi / 1e9, 3),
        # context for the aggregate number: the server's data plane is kernel
        # sendfile (no Python byte-shuffling), so N clients scale with CPU
        # cores — on a 1-core host the tenants' own read loops contend for
        # the same core and aggregate can sit below single-client
        "mt_host_cores": os.cpu_count(),
    }


# Colocated tenant: ask the registry for the blob's location (control
# plane), then pread the advertised file directly (data plane) — the
# load-separation deployment shape. Stdlib-only like _PULL_SNIPPET.
_REDIRECT_PULL_SNIPPET = r"""
import json, sys, time, os, http.client, urllib.parse
url = sys.argv[1]  # .../{repo}/blobs/{digest}/locations/download
u = urllib.parse.urlsplit(url)
t0 = time.monotonic()
conn = http.client.HTTPConnection(u.hostname, u.port, timeout=60)
conn.request("GET", u.path)
resp = conn.getresponse()
assert resp.status == 200, resp.status
loc = json.loads(resp.read())
assert loc["provider"] == "file", loc
path = loc["properties"]["path"]
fd = os.open(path, os.O_RDONLY)
buf = bytearray(16 << 20)
view = memoryview(buf)
n = 0
while True:
    got = os.preadv(fd, [view], n)
    if got <= 0:
        break
    n += got
os.close(fd)
print(time.monotonic() - t0, n)
"""


def measure_redirect_multitenant(base: str, repo: str, desc, size: int,
                                 clients: int = 4) -> dict:
    """Load separation, measured (docs/api.md:32-42 is the reference's core
    architectural claim): colocated tenants fetch the blob LOCATION from the
    server (tiny control-plane JSON) and read the bytes straight from the
    store's filesystem — the bulk data plane never crosses the registry
    process, so N tenants scale with storage bandwidth, not server CPU."""
    url = f"{base}/{repo}/blobs/{desc.digest}/locations/download"
    env = {"PATH": os.environ.get("PATH", "")}

    def run_n(n: int) -> float:
        t0 = time.monotonic()
        procs = [subprocess.Popen(
            [sys.executable, "-S", "-c", _REDIRECT_PULL_SNIPPET, url],
            stdout=subprocess.PIPE, text=True, env=env) for _ in range(n)]
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"redirect puller {i} exited {p.returncode}")
            got = int(out.split()[1])
            if got != size:
                raise RuntimeError(f"redirect puller {i}: {got} of {size} bytes")
        return time.monotonic() - t0

    run_n(1)
    single = run_n(1)
    multi = run_n(clients)
    return {
        "mt_redirect_single_gbps": round(size / single / 1e9, 3),
        "mt_redirect_aggregate_gbps": round(clients * size / multi / 1e9, 3),
    }


def measure_serving(params: dict, mesh, device_kind: str, decode_only: bool = False,
                    weight_bytes_per_param: int = 2) -> dict:
    """Prefill + cached-decode throughput and MFU for the loaded model."""
    import jax
    import jax.numpy as jnp

    from modelx_tpu.dl import families as fam

    family = fam.detect(list(params))
    cfg = family.infer_config(params)
    # the forward spans the whole mesh: utilization is against ALL its chips
    peak = _chip_spec(PEAK_FLOPS, device_kind, 1e12) * mesh.devices.size

    h, layers, inter, vocab = (cfg.hidden_size, cfg.num_layers,
                               cfg.intermediate_size, cfg.vocab_size)
    # dense matmul params touched per token: attention + mlp + lm_head
    # (embedding lookup is a gather, not a matmul)
    p_matmul = layers * (4 * h * h + 3 * h * inter) + vocab * h

    out: dict = {}
    rng = np.random.RandomState(7)

    # Timing discipline for a tunneled device: every rep uses DISTINCT
    # inputs (the relay memoizes repeat executions) and forces a small
    # result fetch. Per-call latency includes the host<->device round trip;
    # steady-state throughput pipelines N dispatches and fetches once, the
    # shape a serving batcher actually drives.
    def fetch(x):
        return float(jnp.reshape(x, (-1,))[0])

    # -- prefill ------------------------------------------------------------
    B, S = 8, 512
    toks = [jnp.asarray(rng.randint(1, vocab, (B, S)), jnp.int32) for _ in range(10)]
    if not decode_only:
        fwd = jax.jit(lambda p, t: family.forward(p, t, cfg, mesh=mesh))
        fetch(fwd(params, toks[9]))  # compile
        lat = []
        for i in range(3):
            t0 = time.monotonic()
            fetch(fwd(params, toks[i]))
            lat.append(time.monotonic() - t0)
        t0 = time.monotonic()
        outs = [fwd(params, t) for t in toks[:8]]
        fetch(outs[-1])
        pipe_dt = (time.monotonic() - t0) / 8
        dt = statistics.median(lat)
        # attention score+value matmuls: 2 * 2 * h per (query, key<=query) pair
        flops = 2 * p_matmul * B * S + layers * 4 * h * B * S * S / 2
        out["prefill_latency_ms"] = round(dt * 1e3, 1)
        out["prefill_tokens_per_s"] = round(B * S / pipe_dt, 1)
        out["prefill_mfu"] = round(flops / pipe_dt / peak, 4)

    # -- cached decode ------------------------------------------------------
    # one jit call decodes N tokens via lax.scan. Per-step cost comes from
    # the slope between two generation lengths — a single-length timing
    # would bill the fixed host<->device round trip (tens of ms on a
    # tunneled rig) to the decode loop and understate throughput ~3x.
    prompts = [t[:, :128] for t in toks]
    lens = (16, 144)  # wide spread: slope noise shrinks with the step gap
    call_dt = {}
    for new in lens:
        gen = jax.jit(
            lambda p, t, n=new: family.generate(p, t, cfg, mesh=mesh, max_new_tokens=n)
        )
        fetch(gen(params, prompts[9]))  # compile
        lat = []
        for i in range(4):
            t0 = time.monotonic()
            fetch(gen(params, prompts[i]))
            lat.append(time.monotonic() - t0)
        call_dt[new] = statistics.median(lat)
    slope = (call_dt[lens[1]] - call_dt[lens[0]]) / (lens[1] - lens[0])
    if slope <= 0:
        # noise won: a longer generation measured faster than a shorter one.
        # Flag it instead of publishing a nonsense throughput.
        out["decode_slope_invalid"] = True
        out["decode_call_seconds"] = {str(k): round(v, 4) for k, v in call_dt.items()}
    else:
        out["decode_tokens_per_s"] = round(B / slope, 1)
        out["decode_call_overhead_ms"] = round((call_dt[lens[0]] - lens[0] * slope) * 1e3, 1)
        # decode is HBM-bound: every step re-reads the weights; utilization
        # against the mesh's aggregate memory bandwidth is the roofline
        hbm_bw = _chip_spec(HBM_GBPS, device_kind, 1e12) * mesh.devices.size
        out["decode_model_bandwidth_util"] = round(
            weight_bytes_per_param * p_matmul / slope / hbm_bw, 4
        )
    out["serving_batch"] = B
    return out


def main() -> None:
    import jax

    from modelx_tpu import native
    from modelx_tpu.dl.loader import load_safetensors
    from modelx_tpu.dl.sharding import LLAMA_RULES
    from modelx_tpu.dl.initializer import _blob_source
    from modelx_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    device_kind = getattr(devices[0], "device_kind", str(devices[0]))
    workdir = tempfile.mkdtemp(prefix="modelx-bench-")
    settle_s = float(os.environ.get("BENCH_SETTLE_S", 8.0))
    srv = None
    try:
        ckpt = os.path.join(workdir, "model.safetensors")
        target = int(os.environ.get("BENCH_BYTES", 512 * 1024 * 1024))
        size = build_checkpoint(ckpt, target)
        srv, base = start_registry(workdir)
        client, desc = push_checkpoint(base, "library/bench", ckpt)

        # small model for TTFT (BASELINE #3 scaled to the rig: the 500 ms
        # budget was set for a multi-chip pod; this rig is one tunneled chip)
        ttft_ckpt = os.path.join(workdir, "ttft.safetensors")
        build_checkpoint(ttft_ckpt, 48 * 1024 * 1024, hidden=512, inter=1408, vocab=8192)
        push_checkpoint(base, "library/ttft", ttft_ckpt)

        mesh = make_mesh(f"dp={len(devices)}")

        # warm up the device transfer path so neither leg pays setup costs
        link_gbps = probe_link_gbps(devices[0])

        # TTFT first: a fresh deploy is not preceded by gigabytes of bench
        # traffic, and the tunnel's burst bucket must not bill earlier legs
        # to the deploy-latency number
        ttft = measure_ttft(base, "library/ttft", workdir)

        # alternate legs with settle pauses (token-bucket tunnel; see module
        # docstring), baseline first = any leftover burst credit goes to the
        # reference's shape, not ours
        baseline_ts, ours_ts, engine_src = [], [], ""
        for _ in range(3):  # best-of-3: the tunnel throttles unpredictably
            time.sleep(settle_s)
            baseline_ts.append(run_baseline(base, "library/bench", desc, workdir, devices))
            time.sleep(settle_s)
            s, engine_src = run_ours(client, "library/bench", desc, mesh, size)
            ours_ts.append(s)
        ours_s, baseline_s = min(ours_ts), min(baseline_ts)

        multitenant = measure_multitenant(base, "library/bench", desc, size)
        multitenant.update(
            measure_redirect_multitenant(base, "library/bench", desc, size)
        )

        # serving: load once more (cheap assert it still works), reuse arrays
        source = _blob_source(client, "library/bench", desc)
        try:
            loaded, _stats = load_safetensors(source, mesh, LLAMA_RULES)
        finally:
            if hasattr(source, "close"):
                source.close()
        serving = measure_serving(loaded, mesh, device_kind)
        del loaded

        # int8 weight-only serving: per-step weight reads halve, so decode
        # (HBM-bound) speeds up — the quantize flag the serve sidecar ships
        source = _blob_source(client, "library/bench", desc)
        try:
            loaded_q, _stats = load_safetensors(source, mesh, LLAMA_RULES, quantize="int8")
        finally:
            if hasattr(source, "close"):
                source.close()
        q = measure_serving(
            loaded_q, mesh, device_kind, decode_only=True,
            weight_bytes_per_param=1,  # int8 matmul weights (embed stays bf16)
        )
        serving.update({
            "int8_decode_tokens_per_s": q.get("decode_tokens_per_s"),
            "int8_decode_speedup": (
                round(q["decode_tokens_per_s"] / serving["decode_tokens_per_s"], 2)
                if q.get("decode_tokens_per_s") and serving.get("decode_tokens_per_s")
                else None
            ),
        })
        del loaded_q

        ours_gbps = size / ours_s / 1e9
        baseline_gbps = size / baseline_s / 1e9

        print(json.dumps({
            "metric": "registry_to_hbm_gbps",
            "value": round(ours_gbps, 3),
            "unit": "GB/s",
            "vs_baseline": round(ours_gbps / baseline_gbps, 3),
            "baseline_gbps": round(baseline_gbps, 3),
            "bytes": size,
            "seconds": round(ours_s, 3),
            "baseline_seconds": round(baseline_s, 3),
            "seconds_runs": [round(t, 3) for t in ours_ts],
            "baseline_seconds_runs": [round(t, 3) for t in baseline_ts],
            "link_gbps": round(link_gbps, 3),
            "link_utilization": round(ours_gbps / link_gbps, 3) if link_gbps else None,
            "engine": {"native": native.available(), "source": engine_src},
            **ttft,
            **multitenant,
            **serving,
            "device": str(devices[0]),
            "device_kind": device_kind,
            "n_devices": len(devices),
        }))
    finally:
        if srv is not None:
            srv.terminate()  # before rmtree: never delete a live server's data
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
