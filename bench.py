"""Benchmark: registry -> TPU HBM load throughput (the BASELINE metric).

Stands up a local registry, pushes a synthetic llama-shaped bf16 checkpoint,
then measures:

- baseline: the reference's deployment shape — download the blob to a pod
  volume as one sequential stream (modelxdl semantics), then read it and
  device_put tensor-by-tensor;
- modelx-tpu: the loader path — parallel ranged HTTP reads planned from the
  manifest's tensor index, streamed straight into device memory.

Prints ONE JSON line: {"metric", "value" (GB/s into HBM), "unit",
"vs_baseline" (speedup over the sequential path), ...extras}.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np


def build_checkpoint(path: str, target_bytes: int) -> int:
    """Synthetic llama-shaped checkpoint (bf16) of roughly target_bytes."""
    import ml_dtypes

    from modelx_tpu.dl import safetensors as st

    rng = np.random.RandomState(0)
    hidden, inter, vocab = 2048, 5632, 32000
    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": rng.rand(vocab, hidden).astype(ml_dtypes.bfloat16),
        "model.norm.weight": np.ones((hidden,), ml_dtypes.bfloat16),
    }
    layer_bytes = 2 * (4 * hidden * hidden + 3 * hidden * inter + 2 * hidden)
    base = 2 * vocab * hidden
    layers = max(1, (target_bytes - base) // layer_bytes)
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors[p + "self_attn.q_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.k_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.v_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.o_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.gate_proj.weight"] = rng.rand(inter, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.up_proj.weight"] = rng.rand(inter, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.down_proj.weight"] = rng.rand(hidden, inter).astype(ml_dtypes.bfloat16)
        tensors[p + "input_layernorm.weight"] = np.ones((hidden,), ml_dtypes.bfloat16)
        tensors[p + "post_attention_layernorm.weight"] = np.ones((hidden,), ml_dtypes.bfloat16)
    st.write_safetensors(path, tensors)
    return os.path.getsize(path)


def main() -> None:
    import jax

    from modelx_tpu.client.client import Client
    from modelx_tpu.client.helper import descriptor_for_file
    from modelx_tpu.client.push import _annotate_safetensors
    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.loader import HTTPSource, LocalFileSource, load_safetensors
    from modelx_tpu.dl.sharding import LLAMA_RULES
    from modelx_tpu.parallel.mesh import make_mesh
    from modelx_tpu.registry.server import free_port
    from modelx_tpu.types import Manifest

    devices = jax.devices()
    workdir = tempfile.mkdtemp(prefix="modelx-bench-")
    try:
        # -- build + push ------------------------------------------------------
        ckpt = os.path.join(workdir, "model.safetensors")
        target = int(os.environ.get("BENCH_BYTES", 512 * 1024 * 1024))
        size = build_checkpoint(ckpt, target)

        import subprocess

        port = free_port()
        base = f"http://127.0.0.1:{port}"
        env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.abspath(__file__)), JAX_PLATFORMS="cpu")
        srv = subprocess.Popen(
            [sys.executable, "-m", "modelx_tpu.cli", "serve",
             "--listen", f"127.0.0.1:{port}",
             "--data", os.path.join(workdir, "registry")],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        import requests as _rq

        for _ in range(50):
            try:
                _rq.get(base + "/healthz", timeout=1)
                break
            except Exception:
                time.sleep(0.2)
        client = Client(base, quiet=True)

        desc = descriptor_for_file(ckpt, "model.safetensors", "application/vnd.modelx.model.file.v1")
        _annotate_safetensors(ckpt, desc)
        with open(ckpt, "rb") as f:
            client.remote.upload_blob_content("library/bench", desc, f)
        client.remote.put_manifest("library/bench", "v1", Manifest(blobs=[desc]))

        url = f"{base}/library/bench/blobs/{desc.digest}"
        mesh = make_mesh(f"dp={len(devices)}")
        tensors, data_offset = st.read_header_from_file(ckpt)

        # warm up the device transfer path so neither side pays setup costs
        warm = jax.device_put(np.zeros(8 << 20, np.uint8), devices[0])
        warm.block_until_ready()
        del warm

        # -- modelx-tpu loader: ranged parallel -> HBM ------------------------
        t0 = time.monotonic()
        loaded, stats = load_safetensors(
            HTTPSource(url, total=size), mesh, LLAMA_RULES,
            tensors=tensors, data_offset=data_offset,
        )
        ours_s = time.monotonic() - t0
        del loaded

        # -- baseline: sequential download to volume, then load ---------------
        t0 = time.monotonic()
        vol = os.path.join(workdir, "volume.safetensors")
        import requests

        with requests.get(url, stream=True) as r, open(vol, "wb") as f:
            for chunk in r.iter_content(chunk_size=1024 * 1024):
                f.write(chunk)
        arrays = []
        with open(vol, "rb") as f:
            infos, off = st.read_header(f)
            for name, info in infos.items():
                f.seek(off + info.start)
                raw = f.read(info.nbytes)
                arr = np.frombuffer(raw, info.np_dtype()).reshape(info.shape)
                arrays.append(jax.device_put(arr, devices[0]))
        jax.block_until_ready(arrays)
        baseline_s = time.monotonic() - t0
        del arrays

        ours_gbps = size / ours_s / 1e9
        baseline_gbps = size / baseline_s / 1e9
        srv.terminate()

        print(
            json.dumps(
                {
                    "metric": "registry_to_hbm_gbps",
                    "value": round(ours_gbps, 3),
                    "unit": "GB/s",
                    "vs_baseline": round(ours_gbps / baseline_gbps, 3),
                    "baseline_gbps": round(baseline_gbps, 3),
                    "bytes": size,
                    "seconds": round(ours_s, 3),
                    "baseline_seconds": round(baseline_s, 3),
                    "device": str(devices[0]),
                    "n_devices": len(devices),
                }
            )
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
