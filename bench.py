"""Benchmark: registry -> TPU HBM load, TTFT, and serving throughput.

Stands up a local registry, pushes a synthetic llama-shaped bf16 checkpoint,
then measures:

- baseline: the reference's deployment shape — download the blob to a pod
  volume as one sequential stream (modelxdl semantics, pull.go:111-143),
  then read it and device_put tensor-by-tensor;
- modelx-tpu: the loader path — blob-location redirect (file provider for
  the colocated registry, ranged HTTP otherwise) planned from the manifest's
  tensor index, streamed into device memory overlapped with fetches;
- link probe: raw host->device bandwidth of this rig (the tunnel to the TPU
  is the hard ceiling for any loader; report it so the ratio value/link is
  interpretable and a degraded run is visible as a degraded link, not
  mistaken for a code regression);
- ttft_ms: p50 time from "fresh process asks the registry for the model" to
  "first decoded token", warm persistent XLA cache (BASELINE.md north star);
- serving: prefill/decode tokens/s and MFU for the pushed model;
- mixed prefill/decode: admit a long prompt into a saturated continuous
  decode batch and report inter-token latency p99 with chunked prefill on
  vs the monolithic-admission baseline (``itl_p99_ms_mixed``,
  ``itl_p99_ms_mixed_baseline``, ``admission_stall_ms_max``).

Leg isolation (BENCH_r04 post-mortem): every TIMED leg runs in its own
FRESH subprocess (``python bench.py --leg <kind> ...``). Measured on this
rig, the TPU tunnel's throttle state is per-process and sticky — one
process's link can sit collapsed 15-20x below another's — so in-process
best-of-3 loops can record a number that says nothing about the code.
Each child also probes the raw link AFTER its load (same process, still
pre-first-execution), so every leg carries its own ceiling context. A
collapsed-leg guard then rechecks the verdict: if the best loader leg
still lost 4x to the baseline AND sat under 10% of the measured link, that
leg reruns once more in another fresh process, and the JSON records which
legs were retried (``legs_retried``).

Legs alternate with settle pauses: beyond the per-process state, the
tunnel is token-bucket shaped (a burst allowance, then a lower sustained
rate), so back-to-back legs would hand whichever ran first an unearned
advantage; baseline-first ordering gives leftover credit to the
reference's shape, not ours.

Prints ONE JSON line; "value" stays registry->HBM GB/s (the BASELINE
metric), extras carry the rest.
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

# Per-chip peaks used for MFU / bandwidth-utilization. Public specs:
# v5e 197 bf16 TFLOP/s + 819 GB/s HBM; v5p 459 TFLOP/s + 2765 GB/s;
# v4 275 TFLOP/s + 1228 GB/s. Longest-prefix match wins ("TPU v5p" must not
# fall into the v5e bucket).
PEAK_FLOPS = {"TPU v5p": 459e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
              "TPU v4": 275e12, "cpu": 1e12}
HBM_GBPS = {"TPU v5p": 2765e9, "TPU v5 lite": 819e9, "TPU v5e": 819e9,
            "TPU v4": 1228e9, "cpu": 100e9}


def _chip_spec(table: dict, device_kind: str, default: float) -> float:
    for k, v in table.items():
        if device_kind.startswith(k):
            return v
    return default


def build_checkpoint(path: str, target_bytes: int, hidden: int = 2048,
                     inter: int = 5632, vocab: int = 32000,
                     seed: int = 0) -> int:
    """Synthetic llama-shaped checkpoint (bf16) of roughly target_bytes.
    ``seed`` varies the weight bytes so legs that must distinguish
    models by CONTENT (the tier store keys on manifest digests) get
    genuinely different checkpoints, not byte-identical ones."""
    import ml_dtypes

    from modelx_tpu.dl import safetensors as st

    rng = np.random.RandomState(seed)
    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": rng.rand(vocab, hidden).astype(ml_dtypes.bfloat16),
        "model.norm.weight": np.ones((hidden,), ml_dtypes.bfloat16),
    }
    layer_bytes = 2 * (4 * hidden * hidden + 3 * hidden * inter + 2 * hidden)
    base = 2 * vocab * hidden
    layers = max(1, (target_bytes - base) // layer_bytes)
    for i in range(layers):
        p = f"model.layers.{i}."
        tensors[p + "self_attn.q_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.k_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.v_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "self_attn.o_proj.weight"] = rng.rand(hidden, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.gate_proj.weight"] = rng.rand(inter, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.up_proj.weight"] = rng.rand(inter, hidden).astype(ml_dtypes.bfloat16)
        tensors[p + "mlp.down_proj.weight"] = rng.rand(hidden, inter).astype(ml_dtypes.bfloat16)
        tensors[p + "input_layernorm.weight"] = np.ones((hidden,), ml_dtypes.bfloat16)
        tensors[p + "post_attention_layernorm.weight"] = np.ones((hidden,), ml_dtypes.bfloat16)
    st.write_safetensors(path, tensors)
    return os.path.getsize(path)


def start_registry(workdir: str) -> tuple[subprocess.Popen, str]:
    from modelx_tpu.registry.server import free_port

    port = free_port()
    base = f"http://127.0.0.1:{port}"
    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)),
               JAX_PLATFORMS="cpu")
    srv = subprocess.Popen(
        [sys.executable, "-m", "modelx_tpu.cli", "serve",
         "--listen", f"127.0.0.1:{port}",
         "--data", os.path.join(workdir, "registry")],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    import requests

    for _ in range(50):
        try:
            requests.get(base + "/healthz", timeout=1)
            break
        except Exception:
            time.sleep(0.2)
    return srv, base


def push_checkpoint(base: str, repo: str, ckpt: str):
    from modelx_tpu.client.client import Client
    from modelx_tpu.client.helper import descriptor_for_file
    from modelx_tpu.client.push import _annotate_safetensors
    from modelx_tpu.types import Manifest

    client = Client(base, quiet=True)
    desc = descriptor_for_file(ckpt, "model.safetensors", "application/vnd.modelx.model.file.v1")
    _annotate_safetensors(ckpt, desc)
    with open(ckpt, "rb") as f:
        client.remote.upload_blob_content(repo, desc, f)
    client.remote.put_manifest(repo, "v1", Manifest(blobs=[desc]))
    return client, desc


def probe_link_gbps(device, nbytes: int = 16 << 20, reps: int = 3) -> float:
    """Median raw host->device bandwidth for random (incompressible) bytes."""
    import jax

    a = np.random.randint(0, 256, nbytes, dtype=np.uint8)
    x = jax.device_put(a, device)
    x.block_until_ready()
    del x
    ts = []
    for _ in range(reps):
        t0 = time.monotonic()
        x = jax.device_put(a, device)
        x.block_until_ready()
        ts.append(time.monotonic() - t0)
        del x
    return nbytes / statistics.median(ts) / 1e9


def run_ours(client, repo: str, desc, mesh, size: int,
             quantize: str | None = None, cache=None,
             prefer_local: bool | None = None) -> tuple[float, str, object]:
    """The loader path through the blob-location seam. Returns (seconds,
    source-class name actually used — proves which engine ran, LoadStats
    for the fetch/device decomposition). ``cache`` routes the load through
    the local blob-cache tier; ``prefer_local=False`` skips the colocated
    file redirect so the leg models a remote pod (the cache legs' shape)."""
    from modelx_tpu.dl.initializer import _blob_source
    from modelx_tpu.dl.loader import load_safetensors
    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.sharding import LLAMA_RULES

    t0 = time.monotonic()
    source = _blob_source(client, repo, desc, cache=cache, prefer_local=prefer_local)
    tensors = data_offset = None
    from modelx_tpu.types import AnnotationTensorIndex

    if AnnotationTensorIndex in desc.annotations:
        tensors, data_offset = st.parse_index_annotation(desc.annotations[AnnotationTensorIndex])
    try:
        loaded, stats = load_safetensors(
            source, mesh, LLAMA_RULES, tensors=tensors, data_offset=data_offset,
            quantize=quantize,
        )
    finally:
        if hasattr(source, "close"):
            source.close()
    seconds = time.monotonic() - t0
    del loaded
    return seconds, type(source).__name__, stats


def run_baseline(base: str, repo: str, desc, workdir: str, devices) -> float:
    """Reference deployment shape: one sequential download to a volume file,
    then read + per-tensor device_put (cmd/modelxdl semantics)."""
    import jax
    import requests

    from modelx_tpu.dl import safetensors as st

    url = f"{base}/{repo}/blobs/{desc.digest}"
    t0 = time.monotonic()
    vol = os.path.join(workdir, "volume.safetensors")
    with requests.get(url, stream=True) as r, open(vol, "wb") as f:
        for chunk in r.iter_content(chunk_size=1024 * 1024):
            f.write(chunk)
    arrays = []
    with open(vol, "rb") as f:
        infos, off = st.read_header(f)
        for name, info in infos.items():
            f.seek(off + info.start)
            raw = f.read(info.nbytes)
            arr = np.frombuffer(raw, info.np_dtype()).reshape(info.shape)
            arrays.append(jax.device_put(arr, devices[0]))
    jax.block_until_ready(arrays)
    seconds = time.monotonic() - t0
    del arrays
    os.unlink(vol)
    return seconds


def measure_ttft(base: str, repo: str, workdir: str, runs: int = 5,
                 int8_runs: int = 2, settle_s: float = 4.0,
                 blob_cache_dir: str = "", child_timeout_s: float = 900.0) -> dict:
    """p50 registry->first-token (BASELINE north star), subprocess-per-run.

    Each run is a FRESH process (``python -m modelx_tpu.dl.ttft``) with the
    warm persistent caches a pre-baked sidecar image ships (XLA compile
    cache + serialized-export cache): measured on this rig, the tunnel relay
    collapses a process's host->device bandwidth ~15x after its first
    program execution, so same-process repeat runs (the r3 harness) measured
    the collapsed link, not deploy latency. The caller must NOT have
    initialized the TPU backend yet — the child processes own the device
    while this runs.

    Reported decomposition (medians over scored runs): plan (manifest +
    family detect), load (registry->HBM, overlapped with the AOT compile),
    compile_join (leftover compile after load), first_exec. ``first_exec``
    is dominated by a flat per-process relay program-setup cost on this rig
    (~1.7-3.7 s even for an 8-element add — measured); on directly-attached
    TPUs it is a normal dispatch, so ``ttft_weights_ready_ms`` (the
    registry+loader leg this framework owns) is reported alongside the
    headline."""
    cache_dir = os.path.join(workdir, "xla-cache")
    env = _device_child_env()  # children use the real device
    if blob_cache_dir:
        # blob-cache (warm-restart) variant: the children share one local
        # blob cache and skip the colocated file redirect, so run 0 pays
        # the network (and fills the cache) while every scored run models a
        # warm pod restart — zero network reads for the weights
        env = dict(env, MODELX_BLOB_CACHE_DIR=blob_cache_dir,
                   MODELX_DL_NO_LOCAL_REDIRECT="1")

    def run_once(quantize: str = "") -> dict:
        cmd = [sys.executable, "-m", "modelx_tpu.dl.ttft", base, repo, cache_dir]
        if quantize:
            cmd.append(quantize)
        p = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=max(60.0, child_timeout_s))
        if p.returncode != 0:
            raise RuntimeError(f"ttft run failed: {p.stderr[-2000:]}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    records = []
    for i in range(runs + 1):  # run 0 warms the persistent caches, unscored
        # settle between children: the link's burst bucket is GLOBAL, and
        # back-to-back fresh processes progressively drain it — without the
        # pause, later runs measure the drained sustained rate and the
        # median drifts up with run count rather than converging
        time.sleep(settle_s)
        rec = run_once()
        if i > 0:
            records.append(rec)
    if not records:
        return {}

    def med(key: str) -> float:
        return round(statistics.median(r[key] for r in records), 1)

    out = {
        "ttft_ms": med("ttft_ms"),
        "ttft_ms_runs": [round(r["ttft_ms"], 1) for r in records],
        "ttft_plan_ms": med("plan_ms"),
        "ttft_load_ms": med("load_ms"),
        "ttft_compile_join_ms": med("compile_join_ms"),
        "ttft_first_exec_ms": med("first_exec_ms"),
        "ttft_weights_ready_ms": med("weights_ready_ms"),
        # best-of alongside the medians: the relay's program-setup tax and
        # link state swing 5-10x BETWEEN bench invocations (measured: the
        # same code captured first_exec 133 ms and 1688 ms an hour apart),
        # so the best run is the capability number, the median the
        # that-capture number, and ttft_ms_runs the full evidence
        "ttft_ms_best": round(min(r["ttft_ms"] for r in records), 1),
        "ttft_weights_ready_best_ms": round(
            min(r["weights_ready_ms"] for r in records), 1
        ),
    }
    if int8_runs > 0:
        q_records = []
        for _ in range(int8_runs + 1):
            time.sleep(settle_s)
            q_records.append(run_once("int8"))
        q_records = q_records[1:]
        out["ttft_int8_ms"] = round(
            statistics.median(r["ttft_ms"] for r in q_records), 1
        )
        out["ttft_int8_weights_ready_ms"] = round(
            statistics.median(r["weights_ready_ms"] for r in q_records), 1
        )
    return out


def measure_program_store(base: str, repo: str, workdir: str,
                          settle_s: float = 4.0,
                          child_timeout_s: float = 600.0,
                          env: dict | None = None) -> dict:
    """Compiled-program registry leg (ISSUE 11): pod 1 boots with an EMPTY
    compile cache, pays the full trace+lower+compile, and publishes its
    AOT surface to the model version as a program bundle; pod 2 boots in
    another fresh process with its own empty cache, pulls the bundle
    on-the-clock, and its compile leg becomes deserialize + XLA-cache
    hit. Both are real ``dl/ttft.py`` children — the same measurement the
    headline TTFT legs use — differing ONLY in whether the registry holds
    programs when they boot.

    Reported: cold vs bundle-warm ``compile_thread_ms`` (the acceptance
    ratio: warm <= 0.5x cold), the matching ``ttft_ms``/``first_exec_ms``
    pairs, and the publish/install counts proving bytes actually moved
    through the registry rather than a shared local cache dir."""
    env = dict(env if env is not None else _device_child_env())

    def run_child(cache_dir: str, publish: bool) -> dict:
        os.makedirs(cache_dir, exist_ok=True)
        cmd = [sys.executable, "-m", "modelx_tpu.dl.ttft", base, repo,
               cache_dir]
        if publish:
            # argv is positional: empty quantize / blob_cache_dir slots
            cmd += ["", "", "publish"]
        p = subprocess.run(cmd, capture_output=True, text=True, env=env,
                           timeout=max(60.0, child_timeout_s))
        if p.returncode != 0:
            raise RuntimeError(
                f"program-store ttft child failed: {p.stderr[-2000:]}"
            )
        return json.loads(p.stdout.strip().splitlines()[-1])

    root = os.path.join(workdir, "program-store")
    time.sleep(settle_s)
    cold = run_child(os.path.join(root, "cold-cache"), publish=True)
    time.sleep(settle_s)
    warm = run_child(os.path.join(root, "warm-cache"), publish=False)
    ratio = (
        round(warm["compile_thread_ms"] / cold["compile_thread_ms"], 3)
        if cold["compile_thread_ms"] else None
    )
    return {
        "programs_published": cold["programs_published"],
        "programs_installed": warm["programs_installed"],
        "program_cold_compile_ms": cold["compile_thread_ms"],
        "program_warm_compile_ms": warm["compile_thread_ms"],
        "program_warm_compile_ratio": ratio,
        "program_cold_first_exec_ms": cold["first_exec_ms"],
        "program_warm_first_exec_ms": warm["first_exec_ms"],
        "program_cold_ttft_ms": cold["ttft_ms"],
        "program_warm_ttft_ms": warm["ttft_ms"],
    }


def measure_kv_store(model_dir: str, base: str, repo: str = "library/kv",
                     dtype: str = "bfloat16", prompt_len: int = 192,
                     suffix_len: int = 16, new_tokens: int = 8,
                     max_seq_len: int = 512) -> dict:
    """Content-addressed prefix-KV registry leg (ISSUE 20): pod 1 serves a
    hot shared system prompt H until its prefix KV crosses the publish
    threshold, builds the bundle and attaches it to the model version; a
    SECOND fresh pod (its own ModelServer, empty prefix cache) pulls the
    bundle from the registry at load and answers H + a new suffix from
    the INSTALLED entry — its TTFT drops from a full prefill to a
    suffix prefill (``kv_warm_ttft_ratio``, pass < 0.6).

    Compile isolation: both scored streams run against programs the
    DECOY prompts B / B+S' / D already compiled on pod 2 (same padded
    shapes, different tokens), so the ratio prices prefill compute, not
    trace+compile. ``kv_hits_installed`` >= 1 is asserted — a warm number
    that never touched the installed entry would be a vacuous pass."""
    from modelx_tpu.client.client import Client
    from modelx_tpu.dl import kv_store
    from modelx_tpu.dl.serve import ModelServer

    ckpt = os.path.join(model_dir, "model.safetensors")
    client, _desc = push_checkpoint(base, repo, ckpt)
    ref = f"{base}/{repo}@v1"

    def pod() -> ModelServer:
        srv = ModelServer(model_dir, dtype=dtype, max_seq_len=max_seq_len,
                          prefix_cache_size=8)
        srv.load()
        return srv

    def stream(srv, ids) -> float | None:
        """Drain one stream fully; returns ms-to-first-piece (TTFT)."""
        toks = np.asarray([ids], np.int32)
        t0 = time.monotonic()
        first_ms = None
        for _piece in srv.generate_stream(toks, max_new_tokens=new_tokens,
                                          chunk_size=8):
            if first_ms is None:
                first_ms = (time.monotonic() - t0) * 1e3
        return first_ms

    pod1 = pod()
    rng = np.random.RandomState(31)
    vocab = int(pod1.cfg.vocab_size)

    def prompt(n: int) -> list[int]:
        return rng.randint(1, vocab, n).astype(np.int32).tolist()

    hot = prompt(prompt_len)  # the shared system prompt
    # turn 1 stores H; two follow-up turns extending H push its hit count
    # to the publish threshold (an identical re-send is NOT a hit — the
    # cache serves strict prefixes, like real multi-turn traffic)
    stream(pod1, hot)
    stream(pod1, hot + prompt(suffix_len))
    stream(pod1, hot + prompt(suffix_len))
    model_key = kv_store.model_key_for_ref(ref)
    published = 0
    for key, entry in pod1._prefix_cache.take_publishable(2):
        data = kv_store.build_bundle(list(key), entry, model_key=model_key,
                                     mesh=pod1.mesh)
        if data is not None:
            kv_store.publish_bundle(ref, data)
            published += 1
    if published < 1:
        raise RuntimeError("kv leg: pod 1 published no bundle "
                           f"(cache stats {pod1._prefix_cache.stats()})")
    del pod1

    # pod 2: fresh server + empty prefix cache; the registry is the only
    # channel the hot prefix can arrive through
    pod2 = pod()
    _fwd, init = pod2.family.decode_fns(pod2.cfg, mesh=pod2.mesh)
    inst = kv_store.pull_and_install(
        client, repo, client.get_manifest(repo, "v1"), init,
        pod2._prefix_cache, mesh=pod2.mesh, model_key=model_key)
    if inst["installed"] < 1:
        raise RuntimeError(f"kv leg: pod 2 installed nothing: {inst}")

    # decoy prewarm: D compiles the full-prefill program at the scored
    # total length, B then B+S' compile the suffix-prefill (hit) pair at
    # the scored shapes — different tokens, so nothing leaks into the
    # scored prompts' cache keys
    stream(pod2, prompt(prompt_len + suffix_len))            # D: cold shape
    decoy = prompt(prompt_len)
    stream(pod2, decoy)                                      # B: stores B
    stream(pod2, decoy + prompt(suffix_len))                 # B+S': hit shape

    warm_ms = stream(pod2, hot + prompt(suffix_len))
    hits_installed = pod2._prefix_cache.stats()["hits_installed"]
    if hits_installed < 1:
        raise RuntimeError(
            "kv leg: the scored warm stream missed the installed entry "
            f"(cache stats {pod2._prefix_cache.stats()})")
    cold_ms = stream(pod2, prompt(prompt_len + suffix_len))
    return {
        "kv_published": published,
        "kv_installed": inst["installed"],
        "kv_install_skipped": inst["skipped"],
        "kv_hits_installed": hits_installed,
        "kv_warm_ttft_ms": round(warm_ms, 1),
        "kv_cold_ttft_ms": round(cold_ms, 1),
        "kv_warm_ttft_ratio": round(warm_ms / cold_ms, 3) if cold_ms else None,
    }


def cache_split_summary(size: int, cold_rec: dict, warm_rec: dict) -> dict:
    """The multi-tier cache's cold/warm split from two blob-cache legs
    (leg_main kinds "cold"/"warm"). ``warm_hit`` is the zero-network-reads
    verdict: the warm leg's source must be the cache's LocalFileSource.
    ``cold_overlap_seconds``/``cold_staging_allocs`` surface the cold
    pipeline's fetch-vs-device_put overlap and staging-pool reuse."""
    cold_gbps = size / max(cold_rec["seconds"], 1e-9) / 1e9
    warm_gbps = size / max(warm_rec["seconds"], 1e-9) / 1e9
    return {
        "registry_to_hbm_cold_cached_gbps": round(cold_gbps, 3),
        "registry_to_hbm_warm_gbps": round(warm_gbps, 3),
        "warm_seconds": round(warm_rec["seconds"], 3),
        "warm_vs_cold": round(warm_gbps / max(cold_gbps, 1e-9), 3),
        "warm_hit": bool(warm_rec.get("cache_state") == "warm"),
        "cold_overlap_seconds": cold_rec.get("overlap_seconds"),
        "cold_staging_allocs": cold_rec.get("staging_allocs"),
        "cold_fetch_growths": cold_rec.get("fetch_growths"),
    }


def ttft_warm_fields(warm_ttft: dict) -> dict:
    """Key mapping for the warm-restart TTFT variant (measure_ttft with a
    shared blob cache): the bench JSON carries them under ttft_warm_*."""
    return {
        "ttft_warm_ms": warm_ttft.get("ttft_ms"),
        "ttft_warm_weights_ready_ms": warm_ttft.get("ttft_weights_ready_ms"),
    }


# stdlib-only puller (no jax import: interpreter startup must not drown the
# transfer on a small-core host) — http.client + readinto into one reused
# buffer, the same zero-copy discipline the loader's HTTPSource uses. The
# stream is consumed, counted, and discarded: in the deployment being
# modeled each tenant lands bytes on its own pod volume (or straight in
# HBM), so N tenants funneling ~2 GB through THIS rig's one shared disk
# would measure the kernel's dirty-page writeback throttle, not the
# registry's data plane. Byte count goes to stdout for verification.
_PULL_SNIPPET = r"""
import sys, time, http.client, urllib.parse
url = sys.argv[1]
u = urllib.parse.urlsplit(url)
t0 = time.monotonic()
conn = http.client.HTTPConnection(u.hostname, u.port, timeout=300)
conn.request("GET", u.path)
resp = conn.getresponse()
assert resp.status == 200, resp.status
buf = bytearray(16 << 20)
view = memoryview(buf)
n = 0
while True:
    got = resp.readinto(view)
    if not got:
        break
    n += got
print(time.monotonic() - t0, n)
"""


def measure_multitenant(base: str, repo: str, desc, size: int,
                        clients: int = 4) -> dict:
    """BASELINE config #5: N tenants pulling concurrently from one registry.
    Each tenant is its own process (the pod shape), streaming through the
    server's direct GET — this stresses the registry data plane itself;
    colocated tenants would take the file redirect and not touch it at all.
    Pass = aggregate GB/s with N clients >= 1 client."""
    url = f"{base}/{repo}/blobs/{desc.digest}"

    # -S + clean env: this image's sitecustomize imports accelerator
    # machinery into every interpreter, which would bill multi-second
    # startup to the transfer
    env = {"PATH": os.environ.get("PATH", "")}

    def run_n(n: int) -> float:
        procs = []
        t0 = time.monotonic()
        for i in range(n):
            procs.append(subprocess.Popen(
                [sys.executable, "-S", "-c", _PULL_SNIPPET, url],
                stdout=subprocess.PIPE, text=True, env=env))
        outs = []
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"multitenant puller {i} exited {p.returncode}")
            outs.append(out)
        wall = time.monotonic() - t0
        for i, out in enumerate(outs):
            got = int(out.split()[1])
            if got != size:  # a partial transfer must not inflate the GB/s
                raise RuntimeError(f"multitenant puller {i}: {got} of {size} bytes")
        return wall

    run_n(1)  # warm page cache + interpreter startup path
    single = run_n(1)
    multi = run_n(clients)
    return {
        "mt_clients": clients,
        "mt_single_gbps": round(size / single / 1e9, 3),
        "mt_aggregate_gbps": round(clients * size / multi / 1e9, 3),
        # context for the aggregate number: the server's data plane is kernel
        # sendfile (no Python byte-shuffling), so N clients scale with CPU
        # cores — on a 1-core host the tenants' own read loops contend for
        # the same core and aggregate can sit below single-client
        "mt_host_cores": os.cpu_count(),
    }


# Colocated tenant: ask the registry for the blob's location (control
# plane), then pread the advertised file directly (data plane) — the
# load-separation deployment shape. Stdlib-only like _PULL_SNIPPET.
_REDIRECT_PULL_SNIPPET = r"""
import json, sys, time, os, http.client, urllib.parse
url = sys.argv[1]  # .../{repo}/blobs/{digest}/locations/download
u = urllib.parse.urlsplit(url)
t0 = time.monotonic()
conn = http.client.HTTPConnection(u.hostname, u.port, timeout=60)
conn.request("GET", u.path)
resp = conn.getresponse()
assert resp.status == 200, resp.status
loc = json.loads(resp.read())
assert loc["provider"] == "file", loc
path = loc["properties"]["path"]
fd = os.open(path, os.O_RDONLY)
buf = bytearray(16 << 20)
view = memoryview(buf)
n = 0
while True:
    got = os.preadv(fd, [view], n)
    if got <= 0:
        break
    n += got
os.close(fd)
print(time.monotonic() - t0, n)
"""


def measure_redirect_multitenant(base: str, repo: str, desc, size: int,
                                 clients: int = 4) -> dict:
    """Load separation, measured (docs/api.md:32-42 is the reference's core
    architectural claim): colocated tenants fetch the blob LOCATION from the
    server (tiny control-plane JSON) and read the bytes straight from the
    store's filesystem — the bulk data plane never crosses the registry
    process, so N tenants scale with storage bandwidth, not server CPU."""
    url = f"{base}/{repo}/blobs/{desc.digest}/locations/download"
    env = {"PATH": os.environ.get("PATH", "")}

    def run_n(n: int) -> float:
        t0 = time.monotonic()
        procs = [subprocess.Popen(
            [sys.executable, "-S", "-c", _REDIRECT_PULL_SNIPPET, url],
            stdout=subprocess.PIPE, text=True, env=env) for _ in range(n)]
        for i, p in enumerate(procs):
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(f"redirect puller {i} exited {p.returncode}")
            got = int(out.split()[1])
            if got != size:
                raise RuntimeError(f"redirect puller {i}: {got} of {size} bytes")
        return time.monotonic() - t0

    run_n(1)
    single = run_n(1)
    multi = run_n(clients)
    return {
        "mt_redirect_single_gbps": round(size / single / 1e9, 3),
        "mt_redirect_aggregate_gbps": round(clients * size / multi / 1e9, 3),
    }


def measure_serving(params: dict, mesh, device_kind: str, decode_only: bool = False,
                    weight_bytes_per_param: int = 2) -> dict:
    """Prefill + cached-decode throughput and MFU for the loaded model."""
    import jax
    import jax.numpy as jnp

    from modelx_tpu.dl import families as fam

    family = fam.detect(list(params))
    cfg = family.infer_config(params)
    # the forward spans the whole mesh: utilization is against ALL its chips
    peak = _chip_spec(PEAK_FLOPS, device_kind, 1e12) * mesh.devices.size

    h, layers, inter, vocab = (cfg.hidden_size, cfg.num_layers,
                               cfg.intermediate_size, cfg.vocab_size)
    # dense matmul params touched per token: attention + mlp + lm_head
    # (embedding lookup is a gather, not a matmul)
    p_matmul = layers * (4 * h * h + 3 * h * inter) + vocab * h

    out: dict = {}
    rng = np.random.RandomState(7)

    # Timing discipline for a tunneled device: every rep uses DISTINCT
    # inputs (the relay memoizes repeat executions) and forces a small
    # result fetch. Per-call latency includes the host<->device round trip;
    # steady-state throughput pipelines N dispatches and fetches once, the
    # shape a serving batcher actually drives.
    def fetch(x):
        return float(jnp.reshape(x, (-1,))[0])

    # -- prefill ------------------------------------------------------------
    B, S = 8, 512
    toks = [jnp.asarray(rng.randint(1, vocab, (B, S)), jnp.int32) for _ in range(10)]
    if not decode_only:
        fwd = jax.jit(lambda p, t: family.forward(p, t, cfg, mesh=mesh))
        fetch(fwd(params, toks[9]))  # compile
        lat = []
        for i in range(3):
            t0 = time.monotonic()
            fetch(fwd(params, toks[i]))
            lat.append(time.monotonic() - t0)
        t0 = time.monotonic()
        outs = [fwd(params, t) for t in toks[:8]]
        fetch(outs[-1])
        pipe_dt = (time.monotonic() - t0) / 8
        dt = statistics.median(lat)
        # attention score+value matmuls: 2 * 2 * h per (query, key<=query) pair
        flops = 2 * p_matmul * B * S + layers * 4 * h * B * S * S / 2
        out["prefill_latency_ms"] = round(dt * 1e3, 1)
        out["prefill_tokens_per_s"] = round(B * S / pipe_dt, 1)
        out["prefill_mfu"] = round(flops / pipe_dt / peak, 4)

    # -- cached decode ------------------------------------------------------
    # one jit call decodes N tokens via lax.scan. Per-step cost comes from
    # the slope between two generation lengths — a single-length timing
    # would bill the fixed host<->device round trip (tens of ms on a
    # tunneled rig) to the decode loop and understate throughput ~3x.
    prompts = [t[:, :128] for t in toks]
    lens = (16, 144)  # wide spread: slope noise shrinks with the step gap
    call_dt = {}
    for new in lens:
        gen = jax.jit(
            lambda p, t, n=new: family.generate(p, t, cfg, mesh=mesh, max_new_tokens=n)
        )
        fetch(gen(params, prompts[9]))  # compile
        lat = []
        for i in range(4):
            t0 = time.monotonic()
            fetch(gen(params, prompts[i]))
            lat.append(time.monotonic() - t0)
        call_dt[new] = statistics.median(lat)
    slope = (call_dt[lens[1]] - call_dt[lens[0]]) / (lens[1] - lens[0])
    if slope <= 0:
        # noise won: a longer generation measured faster than a shorter one.
        # Flag it instead of publishing a nonsense throughput.
        out["decode_slope_invalid"] = True
        out["decode_call_seconds"] = {str(k): round(v, 4) for k, v in call_dt.items()}
    else:
        out["decode_tokens_per_s"] = round(B / slope, 1)
        out["decode_call_overhead_ms"] = round((call_dt[lens[0]] - lens[0] * slope) * 1e3, 1)
        # decode is HBM-bound: every step re-reads the weights; utilization
        # against the mesh's aggregate memory bandwidth is the roofline
        hbm_bw = _chip_spec(HBM_GBPS, device_kind, 1e12) * mesh.devices.size
        out["decode_model_bandwidth_util"] = round(
            weight_bytes_per_param * p_matmul / slope / hbm_bw, 4
        )
    out["serving_batch"] = B
    return out


def _engine_shim(params: dict, mesh, max_seq_len: int):
    """ContinuousBatcher's ModelServer surface over already-loaded arrays
    (family/config re-detected from the parameter names). Every serving
    leg builds one; keeping the attribute set in ONE place means a new
    required server attribute cannot silently miss a leg."""
    from modelx_tpu.dl import families as fam

    family = fam.detect(list(params))

    class _Shim:
        pass

    shim = _Shim()
    shim.family, shim.cfg, shim.mesh = family, family.infer_config(params), mesh
    shim.max_seq_len, shim.params = max_seq_len, params
    shim.stats = {"tokens_generated": 0}
    return shim


def measure_continuous(params: dict, mesh, decode_tps: float | None) -> dict:
    """In-flight batching under load: 8 concurrent clients, each submitting
    independent generate requests against one running engine. The dial that
    matters on this rig: every chunk dispatch pays the tunnel's ~65 ms
    round-trip (decode_call_overhead_ms), so the engine runs a LARGE chunk
    here (128) to amortize it — on a directly-attached TPU the default 8-16
    serves the same aggregate at finer flush granularity. Target
    (VERDICT r3): aggregate tokens/s >= 0.8x the batch-8 slope-derived
    decode throughput."""
    import threading as _t
    from concurrent.futures import ThreadPoolExecutor

    from modelx_tpu.dl.continuous import ContinuousBatcher

    import jax
    import jax.numpy as jnp

    shim = _engine_shim(params, mesh, 1024)
    cfg = shim.cfg
    chunk = 128
    clients, new_tokens = 8, 256
    # burst_window_ms 5: the 8 barrier-released clients contend on the GIL
    # while submitting, so give co-arrivals a real window — admitting the
    # whole burst as one batch keeps every row at the same decode depth
    # (stragglers that miss a 128-step chunk boundary cost a whole extra
    # chunk of misaligned decode)
    cb = ContinuousBatcher(shim, max_slots=8, chunk_size=chunk, max_len=1024,
                           burst_window_ms=5.0)
    try:
        rng = np.random.RandomState(11)
        prompts = [
            rng.randint(1, cfg.vocab_size, (1, 128)).astype(np.int32)
            for _ in range(clients + 1)
        ]
        # warm generates: the first compiles single-admit+chunk, the
        # two-row one compiles the size-invariant BATCHED admit program
        # (one compile per prompt bucket — burst size doesn't retrace), the
        # last absorbs a measured one-time second-call cost on the tunnel
        cb.generate(prompts[-1], max_new_tokens=8)
        cb.generate(np.concatenate([prompts[-1], prompts[-1]]), max_new_tokens=8)
        cb.generate(prompts[-1], max_new_tokens=8)
        start = _t.Barrier(clients)

        def client(i: int) -> int:
            start.wait()  # all clients hit the running engine together
            out = cb.generate(prompts[i], max_new_tokens=new_tokens)
            return out.shape[1] - prompts[i].shape[1]

        t0 = time.monotonic()
        with ThreadPoolExecutor(clients) as pool:
            totals = list(pool.map(client, range(clients)))
        dt = time.monotonic() - t0
        agg = sum(totals) / dt

        # in-engine speculation (a lone greedy row swaps chunks for n-gram
        # verify steps): feed a self-repeating continuation and report
        # device-steps/token — the whole value proposition is < 1.0.
        # NB steps/token is the device-efficiency signal; the tokens/s
        # alongside it is round-trip-bound on a tunneled rig (each verify
        # is a synchronous dispatch, ~65 ms here vs ~1 ms direct-attached)
        spec_cb = ContinuousBatcher(shim, max_slots=2, chunk_size=8,
                                    max_len=1024, speculative_k=6)
        try:
            seed_prompt = prompts[-1][:, :32]
            warm = spec_cb.generate(seed_prompt, max_new_tokens=8)
            rep = np.concatenate([warm, warm[:, -24:]], axis=1)
            spec_cb.generate(rep, max_new_tokens=8)  # compile the verify
            steps0 = spec_cb.stats.get("spec_steps", 0)
            chunks0 = spec_cb.stats["chunks"]
            acc0 = spec_cb.stats.get("spec_accepted", 0)
            n_spec = 96
            t0 = time.monotonic()
            spec_cb.generate(rep, max_new_tokens=n_spec)
            spec_dt = time.monotonic() - t0
            dev_steps = (
                spec_cb.stats.get("spec_steps", 0) - steps0
                + (spec_cb.stats["chunks"] - chunks0) * spec_cb.chunk_size
            )
            spec_out = {
                "continuous_spec_tokens": n_spec,
                "continuous_spec_device_steps": dev_steps,
                "continuous_spec_steps_per_token": round(dev_steps / n_spec, 3),
                "continuous_spec_tokens_per_s": round(n_spec / spec_dt, 1),
                "continuous_spec_accepted": (
                    spec_cb.stats.get("spec_accepted", 0) - acc0
                ),
            }
        finally:
            spec_cb.close()

        # what the same clients got BEFORE in-flight batching: sequential
        # single-row decodes through the one generation worker (streams and
        # mid-decode arrivals bypassed the window batcher entirely in r3)
        gen1 = jax.jit(
            lambda p, t: shim.family.generate(
                p, t, cfg, mesh=mesh, max_new_tokens=new_tokens
            )
        )
        np.asarray(gen1(params, jnp.asarray(prompts[-1])))  # compile
        t0 = time.monotonic()
        for i in range(clients):
            np.asarray(gen1(params, jnp.asarray(prompts[i])))
        seq_dt = time.monotonic() - t0
        seq_agg = clients * new_tokens / seq_dt
        return {
            "continuous_clients": clients,
            "continuous_chunk_size": chunk,
            "continuous_new_tokens": new_tokens,
            "continuous_agg_tokens_per_s": round(agg, 1),
            # vs the slope-derived batch-8 decode rate: that denominator
            # excludes ALL dispatch round-trips, which cost ~65-80 ms per
            # call on this rig's tunnel — the admissions+chunks schedule
            # bounds this ratio well below what a directly-attached TPU
            # would show; the sequential ratio below is the deploy-shaped
            # comparison
            "continuous_vs_batch_decode": (
                round(agg / decode_tps, 3) if decode_tps else None
            ),
            "continuous_sequential_tokens_per_s": round(seq_agg, 1),
            "continuous_vs_sequential": round(agg / seq_agg, 3),
            "continuous_chunks": cb.stats["chunks"],
            **spec_out,
        }
    finally:
        cb.close()


def sharded_child_main(ckpt_dir: str) -> int:
    """``bench.py --sharded-child``: the forced-host multi-device half of
    ``measure_sharded_serving``, in a FRESH process so
    ``--xla_force_host_platform_device_count=8`` is set before jax
    initializes (the parent's backend is already up with its own device
    count). Boots the same checkpoint twice — a dp=1 single-device server
    and a dp=2,tp=2 four-device server — runs the continuous engine under
    concurrent clients on each, and prints one JSON line of aggregate
    rates plus the dp=1 engine-vs-legacy byte-equality verdict."""
    import threading as _t
    from concurrent.futures import ThreadPoolExecutor

    from modelx_tpu.dl.continuous import ContinuousBatcher
    from modelx_tpu.dl.serve import ModelServer

    clients, new_tokens = 4, 64
    rng = np.random.RandomState(7)
    out: dict = {}
    for tag, spec in (("dp1", "dp=1"), ("mesh", "dp=2,tp=2")):
        srv = ModelServer(ckpt_dir, mesh_spec=spec, dtype="float32",
                          max_seq_len=256)
        srv.load()
        cb = ContinuousBatcher(srv, max_slots=4, chunk_size=16, max_len=256)
        try:
            prompts = [
                rng.randint(1, srv.cfg.vocab_size, (1, 32)).astype(np.int32)
                for _ in range(clients)
            ]
            # warm: single + batched admission programs, then one repeat
            cb.generate(prompts[0], max_new_tokens=8)
            cb.generate(np.concatenate([prompts[0], prompts[0]]),
                        max_new_tokens=8)
            cb.generate(prompts[0], max_new_tokens=8)
            if tag == "dp1":
                # the byte-equality acceptance: the mesh-aware engine on a
                # single-device mesh must reproduce the legacy serving
                # path's tokens exactly (greedy AND sampled)
                toks = prompts[0][:, :16]
                greedy_eq = np.array_equal(
                    cb.generate(toks, max_new_tokens=12),
                    srv.generate(toks, max_new_tokens=12))
                sampled_eq = np.array_equal(
                    cb.generate(toks, max_new_tokens=12, temperature=0.8,
                                top_k=12, seed=7),
                    srv.generate(toks, max_new_tokens=12, temperature=0.8,
                                 top_k=12, seed=7))
                out["sharded_dp1_byte_equal"] = bool(greedy_eq and sampled_eq)
            start = _t.Barrier(clients)

            def client(i: int) -> int:
                start.wait()
                got = cb.generate(prompts[i], max_new_tokens=new_tokens)
                return got.shape[1] - prompts[i].shape[1]

            t0 = time.monotonic()
            with ThreadPoolExecutor(clients) as pool:
                totals = list(pool.map(client, range(clients)))
            dt = time.monotonic() - t0
            snap = cb.snapshot()
            out[f"{tag}_tokens_per_s"] = round(sum(totals) / dt, 1)
            out[f"{tag}_mesh"] = snap["mesh"]
            out[f"{tag}_devices"] = snap["mesh_devices"]
        finally:
            cb.close()
    print(json.dumps(out))
    return 0


def measure_sharded_serving(ckpt_dir: str, env=None,
                            timeout_s: float = 900.0) -> dict:
    """Tensor-parallel continuous decode on a real (forced-host) multi-
    device mesh — the ISSUE 16 acceptance leg. A child process pins
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` BEFORE jax
    imports, serves one checkpoint on dp=1 and on dp=2,tp=2, and this
    parent reports the aggregate rates, the per-device throughput ratio
    (tp devices all work on every token, so the mesh aggregate IS the
    per-device rate; pass >= 0.7x the single-device baseline), and the
    dp=1 byte-equality verdict."""
    child_env = dict(env or os.environ)
    child_env["JAX_PLATFORMS"] = "cpu"
    flags = child_env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        child_env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--sharded-child",
         ckpt_dir],
        capture_output=True, text=True, env=child_env, timeout=timeout_s)
    if p.returncode != 0:
        raise RuntimeError(f"sharded child failed: {p.stderr[-2000:]}")
    child = json.loads(p.stdout.strip().splitlines()[-1])
    dp1 = child.get("dp1_tokens_per_s") or 0.0
    mesh_tps = child.get("mesh_tokens_per_s") or 0.0
    return {
        "sharded_mesh": child.get("mesh_mesh"),
        "sharded_devices": child.get("mesh_devices"),
        "sharded_tokens_per_s": mesh_tps,
        "sharded_dp1_tokens_per_s": dp1,
        "sharded_per_device_ratio": (
            round(mesh_tps / dp1, 3) if dp1 else None
        ),
        "sharded_dp1_byte_equal": child.get("sharded_dp1_byte_equal"),
    }


def _sampling_microbench(rows: int, vocab: int, reps: int = 40) -> dict:
    """Per-step sampling cost at the engine's [rows, vocab] logits shape:
    the fused top-k prefix path (``sampling_ms_*``) vs the same filters
    forced through the full-vocab sort (``sampling_sort_ms_p50``,
    ``k_cap=None``) — the direct price ISSUE 17's tentpole removes from
    every sampled decode step."""
    import jax
    import jax.numpy as jnp

    from modelx_tpu.ops import sampling as sampling_ops

    key = jax.random.PRNGKey(0)
    temp = jnp.full((rows,), 0.8, jnp.float32)
    tk = jnp.full((rows,), 40, jnp.int32)
    tp = jnp.full((rows,), 0.95, jnp.float32)
    seeds = jnp.arange(rows, dtype=jnp.int32)

    def _fused(lg, step):
        return sampling_ops.sample(lg, key, temp, tk, tp,
                                   seeds=seeds, step=step)

    def _sorted(lg, step):
        filt = sampling_ops.scale_and_filter_reference(
            lg, temp, tk, tp, k_cap=None)
        steps = jnp.broadcast_to(jnp.asarray(step, jnp.int32), (rows,))
        keys = jax.vmap(lambda s, st: jax.random.fold_in(
            jax.random.fold_in(key, s), st))(seeds, steps)
        return jax.vmap(jax.random.categorical)(keys, filt)

    fused = jax.jit(_fused)
    sortp = jax.jit(_sorted)
    logits = [
        jax.random.normal(jax.random.fold_in(key, i), (rows, vocab),
                          jnp.float32) * 3.0
        for i in range(4)
    ]

    def timed(fn) -> list[float]:
        jax.block_until_ready(fn(logits[0], 0))  # compile outside the clock
        ms = []
        for i in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(logits[i % len(logits)], i))
            ms.append((time.perf_counter() - t0) * 1e3)
        return ms

    f_ms = np.asarray(timed(fused))
    s_ms = np.asarray(timed(sortp))
    return {
        "sampling_ms_p50": round(float(np.percentile(f_ms, 50)), 4),
        "sampling_ms_p99": round(float(np.percentile(f_ms, 99)), 4),
        "sampling_sort_ms_p50": round(float(np.percentile(s_ms, 50)), 4),
    }


def measure_decode_pipelined(params, mesh, decode_tps: float | None, *,
                             clients: int = 8, chunk: int = 16,
                             new_tokens: int = 192, prompt_len: int = 64,
                             max_len: int = 512) -> dict:
    """Pipelined-dispatch leg (ISSUE 7): identical 8-client decode traffic
    against two engines — SERIAL boundaries (pipeline_depth=1,
    dispatch_depth=1: dispatch, blocking sync, plan, repeat — the r05
    shape whose ~66 ms/chunk host overhead halved throughput) vs
    DISPATCH-AHEAD (pipeline_depth=2, dispatch_depth auto: depth-D
    programs + async token readback + boundary-prep overlap).

    ``decode_call_overhead_ms_{serial,pipelined}`` is the per-chunk
    boundary overhead: (wall - tokens/decode_tps) / chunk_equivalents —
    the slope-derived batch decode rate prices the pure device time, what
    is left is dispatch + host work per chunk. A depth-D program spreads
    one dispatch across D chunks, so the pipelined number should drop
    ~Dx (acceptance: >= 3x on the bench rig). ``dispatches_serial`` /
    ``dispatches_pipelined`` carry the structural evidence (fewer device
    calls for the same tokens) independent of timing noise.

    ISSUE 17 adds a SAMPLED leg: the same dispatch-ahead engine under a
    mixed client population (every other client samples at temperature
    0.8 / top_k 40 / top_p 0.95 — cuts that resolve inside the fused
    sampler's K_CAP prefix). Before the fused path, sampled rows paid a
    full-vocab sort per token; ``sampled_vs_greedy_decode_ratio`` is the
    acceptance signal (>= 0.9: sampling within 10% of greedy), with
    ``sampling_ms_p50/p99`` (fused) vs ``sampling_sort_ms_p50`` (forced
    sort path) microbenched at the engine's [clients, vocab] shape, and
    ``pad_fraction`` read off the engine's dispatch accounting."""
    import threading as _t
    from concurrent.futures import ThreadPoolExecutor

    from modelx_tpu.dl.continuous import ContinuousBatcher

    shim = _engine_shim(params, mesh, max_len)
    cfg = shim.cfg
    rng = np.random.RandomState(17)
    prompts = [
        rng.randint(1, cfg.vocab_size, (1, prompt_len)).astype(np.int32)
        for _ in range(clients + 1)
    ]
    # the sampled leg's non-greedy client kwargs: cuts inside K_CAP, a
    # per-client seed so streams are independent
    samp_kw = {"temperature": 0.8, "top_k": 40, "top_p": 0.95}

    def run(pipeline_depth: int, dispatch_depth: int,
            sampled: bool = False) -> dict:
        cb = ContinuousBatcher(shim, max_slots=clients, chunk_size=chunk,
                               max_len=max_len, burst_window_ms=5.0,
                               pipeline_depth=pipeline_depth,
                               dispatch_depth=dispatch_depth)
        try:
            # warm every compiled shape the measured phase uses, so no
            # program compiles inside the timed run: the single admit,
            # EVERY pow2 burst-admit width (the barrier start below can
            # land any subset of clients in one admission group, and
            # groups pad to pow2), the per-chunk program, and (auto
            # depth) EVERY power-of-two depth rung. A lone decode's first
            # pipeline_depth dispatches stay depth-1 (first token still
            # owed), then the deep pick sees rem = budget - depth*chunk —
            # budget (pipe_depth + d) * chunk puts rung d exactly there.
            cb.generate(prompts[-1], max_new_tokens=8)
            w = 1
            while w < clients:
                w *= 2
                cb.generate(np.concatenate([prompts[-1]] * min(w, clients)),
                            max_new_tokens=8)
            d = 2
            while d <= (dispatch_depth or cb.AUTO_DISPATCH_DEPTH):
                cb.generate(prompts[-1],
                            max_new_tokens=(pipeline_depth + d) * chunk)
                if sampled:
                    # the filtered chunk-program variant compiles per
                    # depth rung too — warm it so the measured phase's
                    # mixed batches never compile
                    cb.generate(prompts[-1], seed=9,
                                max_new_tokens=(pipeline_depth + d) * chunk,
                                **samp_kw)
                d *= 2
            if sampled:
                cb.generate(prompts[-1], max_new_tokens=8, seed=9, **samp_kw)
            # the warmup's compiles landed in the boundary histogram and
            # the max/peak counters: reset so the reported observability
            # numbers describe the MEASURED phase only
            cb._boundary_host_ms.clear()
            cb.stats["host_syncs_per_boundary"] = 0
            cb.stats["tokens_in_flight_peak"] = 0
            cb.stats["dispatch_depth_max"] = 1
            cb.stats["sync_lag_chunks_max"] = 0
            d0, c0 = cb.stats["dispatches"], cb.stats["chunks"]
            start = _t.Barrier(clients)

            def client(i: int) -> int:
                start.wait()
                kw = dict(seed=100 + i, **samp_kw) if sampled and i % 2 else {}
                out = cb.generate(prompts[i], max_new_tokens=new_tokens, **kw)
                return out.shape[1] - prompts[i].shape[1]

            t0 = time.monotonic()
            with ThreadPoolExecutor(clients) as pool:
                totals = list(pool.map(client, range(clients)))
            wall = time.monotonic() - t0
            return {"wall": wall, "tokens": sum(totals),
                    "dispatches": cb.stats["dispatches"] - d0,
                    "chunks": cb.stats["chunks"] - c0,
                    "snap": cb.snapshot()}
        finally:
            cb.close()

    serial = run(1, 1)
    pipe = run(2, 0)
    samp = run(2, 0, sampled=True)

    def overhead_ms(rec: dict) -> float | None:
        if not decode_tps:
            return None
        device_s = rec["tokens"] / decode_tps
        return round(
            max(0.0, (rec["wall"] - device_s) / max(rec["chunks"], 1) * 1e3), 3
        )

    o_serial, o_pipe = overhead_ms(serial), overhead_ms(pipe)
    agg_pipe = pipe["tokens"] / pipe["wall"]
    agg_samp = samp["tokens"] / samp["wall"]
    out = {
        "pipelined_clients": clients,
        "pipelined_chunk_size": chunk,
        "pipelined_new_tokens": new_tokens,
        "dispatches_serial": serial["dispatches"],
        "dispatches_pipelined": pipe["dispatches"],
        "pipelined_dispatch_depth_max": pipe["snap"].get("dispatch_depth_max"),
        "decode_call_overhead_ms_serial": o_serial,
        "decode_call_overhead_ms_pipelined": o_pipe,
        "serial_agg_tokens_per_s": round(serial["tokens"] / serial["wall"], 1),
        "pipelined_agg_tokens_per_s": round(agg_pipe, 1),
        "continuous_vs_batch_decode_pipelined": (
            round(agg_pipe / decode_tps, 3) if decode_tps else None
        ),
        "boundary_host_ms_p50_serial": serial["snap"].get("boundary_host_ms_p50"),
        "boundary_host_ms_p50_pipelined": pipe["snap"].get("boundary_host_ms_p50"),
        "boundary_host_ms_p99_pipelined": pipe["snap"].get("boundary_host_ms_p99"),
        "pipelined_tokens_in_flight_peak": pipe["snap"].get("tokens_in_flight_peak"),
        "pipelined_host_syncs_per_boundary": pipe["snap"].get("host_syncs_per_boundary"),
        "pipelined_sync_lag_chunks_max": pipe["snap"].get("sync_lag_chunks_max"),
        # sampled leg (ISSUE 17): mixed greedy/sampled clients through the
        # fused on-device sampler — the ratio to the all-greedy run is the
        # acceptance signal (sampled rows used to pay a full-vocab sort)
        "sampled_agg_tokens_per_s": round(agg_samp, 1),
        "continuous_vs_batch_decode_sampled": (
            round(agg_samp / decode_tps, 3) if decode_tps else None
        ),
        "sampled_vs_greedy_decode_ratio": (
            round(agg_samp / agg_pipe, 3) if agg_pipe else None
        ),
        # padding tax, read off the engine's dispatch accounting (the
        # sampled run's snapshot — identical traffic shape to pipe)
        "pad_fraction": samp["snap"].get("pad_fraction"),
        "pages_swept_fraction": samp["snap"].get("pages_swept_fraction"),
    }
    out.update(_sampling_microbench(clients, int(cfg.vocab_size)))
    if o_serial is not None and o_pipe is not None:
        # o_pipe can legitimately clamp to 0.0 (pipelined wall under the
        # device-time estimate — the best possible outcome); floor + cap
        # so the >=3x acceptance evidence is present rather than silently
        # omitted exactly when the win is total
        out["decode_overhead_reduction"] = min(
            round(o_serial / max(o_pipe, 1e-3), 2), 999.0
        )
    return out


def measure_mixed_prefill(params, mesh, *, slots: int = 8, chunk: int = 32,
                          prefill_chunk: int = 128, decode_prompt: int = 128,
                          decode_new: int = 256, long_prompt: int = 704,
                          long_new: int = 64, max_len: int = 1024) -> dict:
    """Admission jitter under load (the chunked-prefill acceptance leg):
    saturate ``slots - 1`` decode rows, then admit a long prompt into the
    running batch and measure each decoding client's inter-token latency.
    Two scenarios on identical traffic: chunked prefill ON (pieces
    interleave with decode chunks) vs OFF (today's monolithic admission
    prefill, the baseline whose stall scales with prompt length).

    Reported: ``itl_p99_ms_mixed`` / ``itl_p99_ms_mixed_baseline`` (p99
    per-token gap over the admission window, chunked vs monolithic),
    ``itl_p99_ms_idle`` (the same engine's p99 with no admission in
    flight — the ≤ 2x acceptance denominator), and
    ``admission_stall_ms_max`` (the engine's own max decode-boundary gap,
    from its stats — no internals poking)."""
    from modelx_tpu.dl.continuous import ContinuousBatcher

    shim = _engine_shim(params, mesh, max_len)
    cfg = shim.cfg
    rng = np.random.RandomState(23)
    n_dec = max(1, slots - 1)
    dec_prompts = [
        rng.randint(1, cfg.vocab_size, decode_prompt).astype(np.int32).tolist()
        for _ in range(n_dec)
    ]
    long_ids = rng.randint(1, cfg.vocab_size, long_prompt).astype(np.int32).tolist()

    def scenario(pc_tokens: int) -> dict:
        cb = ContinuousBatcher(shim, max_slots=slots, chunk_size=chunk,
                               max_len=max_len, burst_window_ms=5.0,
                               prefill_chunk=pc_tokens)
        try:
            # warm every compiled shape the measured phase touches (the
            # n_dec-row burst admit, chunk, the long prompt's piece
            # buckets / monolithic bucket) so the ITL numbers aren't
            # compile stalls
            cb.generate(np.asarray(dec_prompts, np.int32), max_new_tokens=8)
            cb.generate(np.asarray([long_ids], np.int32), max_new_tokens=8)
            cb.stats["stall_ms_max"] = 0.0
            cb.stats["chunks"] = 0
            cb.stats["prefill_pieces"] = 0  # warm-up pieces aren't the leg's

            arrivals: list[list[tuple[float, int]]] = [[] for _ in range(n_dec)]

            def client(i: int, ticket) -> None:
                while True:
                    item = ticket.out.get()
                    if not isinstance(item, np.ndarray):
                        if isinstance(item, BaseException):
                            raise item
                        return
                    arrivals[i].append((time.monotonic(), int(item.size)))

            from concurrent.futures import ThreadPoolExecutor

            tickets = cb.submit_many([
                (ids, decode_new, {}) for ids in dec_prompts
            ])
            # executor, not bare threads: a broken engine must fail the
            # leg loudly (futures re-raise), not silently truncate the
            # arrival records the p99s are computed from
            pool = ThreadPoolExecutor(n_dec)
            futs = [pool.submit(client, i, t) for i, t in enumerate(tickets)]
            # let the batch reach steady-state boundary cadence first (the
            # pre-admission gaps ARE the idle-ITL baseline — a couple of
            # boundaries' worth of clustered warm-in arrivals would make
            # it degenerate), then admit into the running batch
            deadline = time.monotonic() + 120
            while cb.stats["chunks"] < 6 and time.monotonic() < deadline:
                time.sleep(0.002)
            t_admit = time.monotonic()
            long_ticket = cb.submit(long_ids, long_new, {})
            long_first = None
            long_toks = 0
            while True:
                item = long_ticket.out.get()
                if not isinstance(item, np.ndarray):
                    if isinstance(item, BaseException):
                        raise item
                    break
                if long_first is None:
                    long_first = time.monotonic()
                long_toks += int(item.size)
            for fut in futs:
                fut.result(timeout=300)
            pool.shutdown()

            idle, mixed = [], []
            window_end = long_first if long_first is not None else time.monotonic()
            for rec in arrivals:
                for gi, ((t0, _n0), (t1, n1)) in enumerate(zip(rec, rec[1:])):
                    per_tok = (t1 - t0) * 1e3 / max(1, n1)
                    # a gap OVERLAPPING the admission window is admission
                    # jitter; the idle baseline is STRICTLY pre-admission
                    # gaps (post-window gaps come from the now-larger
                    # batch and would flatter the <=2x acceptance ratio),
                    # minus each client's first two warm-in gaps, whose
                    # clustered burst-admission deliveries aren't cadence
                    if t1 >= t_admit and t0 <= window_end:
                        mixed.append(per_tok)
                    elif t1 < t_admit and gi >= 2:
                        idle.append(per_tok)
            out = {
                "stall_ms_max": cb.stats["stall_ms_max"],
                "prefill_pieces": cb.stats["prefill_pieces"],
                "long_tokens": long_toks,
                "ttft_long_ms": round((long_first - t_admit) * 1e3, 1)
                if long_first else None,
            }
            for key, samples in (("itl_p99_ms_idle", idle), ("itl_p99_ms_mixed", mixed)):
                out[key] = round(float(np.percentile(samples, 99)), 3) if samples else None
            return out
        finally:
            cb.close()

    chunked = scenario(prefill_chunk)
    mono = scenario(0)
    out = {
        "mixed_slots": slots,
        "mixed_chunk_size": chunk,
        "mixed_prefill_chunk": prefill_chunk,
        "mixed_long_prompt": long_prompt,
        "itl_p99_ms_mixed": chunked["itl_p99_ms_mixed"],
        "itl_p99_ms_idle": chunked["itl_p99_ms_idle"],
        "itl_p99_ms_mixed_baseline": mono["itl_p99_ms_mixed"],
        "admission_stall_ms_max": chunked["stall_ms_max"],
        "admission_stall_ms_max_baseline": mono["stall_ms_max"],
        "mixed_prefill_pieces": chunked["prefill_pieces"],
        "mixed_ttft_long_ms": chunked["ttft_long_ms"],
        "mixed_ttft_long_ms_baseline": mono["ttft_long_ms"],
    }
    if (chunked["itl_p99_ms_mixed"] and chunked["itl_p99_ms_idle"]
            and chunked["itl_p99_ms_idle"] > 0.05):
        # the acceptance dial: admission must raise ITL p99 by <= 2x idle
        # (guarded against a degenerate near-zero idle capture)
        out["mixed_jitter_ratio"] = round(
            chunked["itl_p99_ms_mixed"] / chunked["itl_p99_ms_idle"], 3
        )
    if chunked["itl_p99_ms_mixed"] and mono["itl_p99_ms_mixed"]:
        out["mixed_vs_monolithic"] = round(
            mono["itl_p99_ms_mixed"] / chunked["itl_p99_ms_mixed"], 3
        )
    return out


def measure_overload(params, mesh, *, slots: int = 2, chunk: int = 8,
                     queue_depth: int = 4, clients: int = 16,
                     prompt: int = 16, new_tokens: int = 32,
                     max_len: int = 256) -> dict:
    """Overload + self-healing leg (ISSUE 3 acceptance): saturate a
    bounded-admission engine and count the sheds, expire a queued request
    past its deadline, then crash the engine's dispatch with a
    deterministic FaultPlan and time the supervisor's recovery.

    Reported: ``shed_429_count`` (submits rejected at --max-queue-depth),
    ``deadline_504_count`` (requests expired at a chunk boundary),
    ``recovery_ms`` (injected crash -> first successful generate on the
    restarted engine), and ``overload_engine_restarts``."""
    from concurrent.futures import ThreadPoolExecutor

    from modelx_tpu.dl.continuous import ContinuousBatcher
    from modelx_tpu.dl.serving_errors import (
        DeadlineExceededError, EngineBrokenError, QueueFullError,
    )
    from modelx_tpu.testing import faults

    shim = _engine_shim(params, mesh, max_len)
    cfg = shim.cfg
    rng = np.random.RandomState(31)
    prompts = [
        rng.randint(1, cfg.vocab_size, (1, prompt)).astype(np.int32)
        for _ in range(clients)
    ]
    cb = ContinuousBatcher(shim, max_slots=slots, chunk_size=chunk,
                           max_len=max_len, max_queue_depth=queue_depth,
                           restart_backoff_s=0.05)
    try:
        cb.generate(prompts[0], max_new_tokens=8)  # warm the compiled set

        # -- shed leg: saturating concurrent traffic against the bound ----
        shed = ok = 0
        lock = threading.Lock()

        def client(i: int) -> None:
            nonlocal shed, ok
            try:
                cb.generate(prompts[i], max_new_tokens=new_tokens)
                with lock:
                    ok += 1
            except QueueFullError:
                with lock:
                    shed += 1

        with ThreadPoolExecutor(clients) as pool:
            list(pool.map(client, range(clients)))

        # -- deadline leg: a queued request expired at the boundary -------
        deadline_504 = 0
        blocker = cb.submit(prompts[0][0].tolist(), 64, {})
        blocker.out.get(timeout=60)  # admitted: the slot array is busy
        fillers = [
            cb.submit(prompts[1 + i % (clients - 1)][0].tolist(), 64, {})
            for i in range(slots - 1)
        ]
        waiter = cb.submit(prompts[2][0].tolist(), 8, {})
        waiter.deadline = 0.0  # already past: expires at the next boundary
        item = waiter.out.get(timeout=60)
        if isinstance(item, DeadlineExceededError):
            deadline_504 += 1
        blocker.cancel()
        for f in fillers:
            f.cancel()

        # -- crash/recovery leg: injected dispatch fault ------------------
        plan = faults.FaultPlan(seed=7)
        plan.add("engine.dispatch", errors_at=[0],
                 error=RuntimeError("bench-injected crash"))
        cb._chunk = faults.wrap_dispatch(cb._chunk, plan)
        t0 = time.monotonic()
        try:
            cb.generate(prompts[3], max_new_tokens=8)
        except EngineBrokenError:
            pass
        recovery_ms = None
        give_up = time.monotonic() + 60
        while time.monotonic() < give_up:
            try:
                cb.generate(prompts[3], max_new_tokens=8)
                recovery_ms = round((time.monotonic() - t0) * 1e3, 1)
                break
            except EngineBrokenError:
                time.sleep(0.01)
        snap = cb.snapshot()
        return {
            "overload_clients": clients,
            "overload_queue_depth": queue_depth,
            "shed_429_count": shed,
            "overload_served": ok,
            "deadline_504_count": deadline_504,
            "recovery_ms": recovery_ms,
            "overload_engine_restarts": snap["engine_restarts"],
        }
    finally:
        cb.close()


def measure_model_swap(base: str, workdir: str, *, target_bytes: int = 16 << 20,
                       hidden: int = 512, inter: int = 1408, vocab: int = 8192,
                       prompt_len: int = 8, new_tokens: int = 4) -> dict:
    """Model lifecycle swap leg (ISSUE 5): with live traffic to a third
    model C, unload A and load B through the pool — cold (empty blob
    cache, bytes come from the registry) vs blob-cache-warm (B's blobs
    already on the node from the cold swap, zero network reads).

    Reported: ``ttft_swap_cold_ms`` / ``ttft_swap_warm_ms`` (DELETE of the
    old model -> first token out of the newly loaded one),
    ``swap_traffic_errors`` (C requests that failed during either swap —
    the uninterrupted-traffic contract, must be 0), and the pull path's
    ``swap_cache_hits``."""
    import threading as _threading

    from modelx_tpu.dl.blob_cache import BlobCache
    from modelx_tpu.dl.serve import ModelServer, ServerSet

    root = os.path.join(workdir, "swap")
    dirs: dict[str, str] = {}
    for name in ("a", "b", "c"):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        build_checkpoint(os.path.join(d, "model.safetensors"), target_bytes,
                         hidden=hidden, inter=inter, vocab=vocab)
        push_checkpoint(base, f"library/swap-{name}",
                        os.path.join(d, "model.safetensors"))
        dirs[name] = d
    cache = BlobCache(os.path.join(root, "blobcache"))
    servers = {n: ModelServer(dirs[n], name=n) for n in ("a", "c")}
    sset = ServerSet(servers, default="c", allow_admin_load=True,
                     staging_root=os.path.join(root, "staging"))
    sset.pool.blob_cache = cache
    sset.load_all()

    stop = _threading.Event()
    counts = {"served": 0, "errors": 0}
    rng = np.random.RandomState(5)
    prompt = rng.randint(1, vocab, (1, prompt_len)).astype(np.int32)

    def traffic() -> None:
        while not stop.is_set():
            try:
                sset.servers["c"].generate(prompt, max_new_tokens=new_tokens)
                counts["served"] += 1
            except Exception:
                counts["errors"] += 1

    t = _threading.Thread(target=traffic, daemon=True)
    t.start()

    def swap(old: str, new: str) -> float:
        t0 = time.monotonic()
        sset.pool.request_unload(old, wait=True)
        sset.pool.request_load(new, ref=f"{base}/library/swap-{new}@v1",
                               wait=True)
        state = sset.pool.states()[new]
        if state["state"] != "READY":
            raise RuntimeError(f"swap load of {new} landed {state}")
        sset.servers[new].generate(prompt, max_new_tokens=1)  # first token
        return (time.monotonic() - t0) * 1e3

    try:
        cold_ms = swap("a", "b")       # empty cache: bytes from the registry
        warm_ms = swap("b", "b")       # B's blobs admitted by the cold pull
    finally:
        stop.set()
        t.join(timeout=30)
    return {
        "ttft_swap_cold_ms": round(cold_ms, 1),
        "ttft_swap_warm_ms": round(warm_ms, 1),
        "swap_traffic_served": counts["served"],
        "swap_traffic_errors": counts["errors"],
        "swap_cache_hits": cache.stats["hits"],
    }


def measure_tier_swap(base: str, workdir: str, *, target_bytes: int = 16 << 20,
                      hidden: int = 512, inter: int = 1408, vocab: int = 8192,
                      prompt_len: int = 8, new_tokens: int = 4) -> dict:
    """Tiered-state swap leg (ISSUE 18): with live traffic to a third
    model C, swap model B in three ways — cold (empty blob cache: registry
    pull + safetensors parse + placement), host-tier promotion (B's
    params demoted to host RAM at unload, re-load is device_put only),
    and disk-tier promotion (host entry spooled to the decoded-tensor
    spool first, re-load is np.load + device_put).

    Reported: ``ttft_swap_cold_ms`` / ``ttft_swap_host_ms`` /
    ``ttft_swap_disk_ms`` (each DELETE old -> first token out of the new
    load), ``tier_traffic_errors`` (C requests failed during any swap —
    the uninterrupted-traffic contract, must be 0), and the tier store's
    hit/spill counters. The ServerlessLLM-style bar: host promotion
    beats the cold path by at least 2x."""
    import threading as _threading

    from modelx_tpu.dl.blob_cache import BlobCache
    from modelx_tpu.dl.serve import ModelServer, ServerSet

    root = os.path.join(workdir, "tierswap")
    dirs: dict[str, str] = {}
    for i, name in enumerate(("a", "b", "c")):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        # distinct seeds: the tier key is CONTENT identity (manifest
        # digests), so byte-identical checkpoints would turn the cold leg
        # into a cross-model tier hit and understate ttft_swap_cold_ms
        build_checkpoint(os.path.join(d, "model.safetensors"), target_bytes,
                         hidden=hidden, inter=inter, vocab=vocab, seed=i + 1)
        push_checkpoint(base, f"library/tier-{name}",
                        os.path.join(d, "model.safetensors"))
        dirs[name] = d
    cache = BlobCache(os.path.join(root, "blobcache"))
    servers = {n: ModelServer(dirs[n], name=n) for n in ("a", "c")}
    sset = ServerSet(servers, default="c", allow_admin_load=True,
                     staging_root=os.path.join(root, "staging"),
                     host_state_budget_bytes=1 << 30,
                     disk_state_budget_bytes=1 << 30,
                     state_spool_dir=os.path.join(root, "spool"))
    sset.pool.blob_cache = cache
    sset.load_all()

    stop = _threading.Event()
    counts = {"served": 0, "errors": 0}
    rng = np.random.RandomState(7)
    prompt = rng.randint(1, vocab, (1, prompt_len)).astype(np.int32)

    def traffic() -> None:
        while not stop.is_set():
            try:
                sset.servers["c"].generate(prompt, max_new_tokens=new_tokens)
                counts["served"] += 1
            except Exception:
                counts["errors"] += 1

    t = _threading.Thread(target=traffic, daemon=True)
    t.start()

    def swap(old: str, new: str) -> float:
        t0 = time.monotonic()
        sset.pool.request_unload(old, wait=True)
        sset.pool.request_load(new, ref=f"{base}/library/tier-{new}@v1",
                               wait=True)
        state = sset.pool.states()[new]
        if state["state"] != "READY":
            raise RuntimeError(f"tier swap load of {new} landed {state}")
        sset.servers[new].generate(prompt, max_new_tokens=1)  # first token
        return (time.monotonic() - t0) * 1e3

    tiers = sset.pool.tiers
    try:
        cold_ms = swap("a", "b")     # B never demoted: full pull + parse
        host_ms = swap("b", "b")     # unload demotes to host; load promotes
        # keep-on-promote left B's entry in the host tier; spool it so the
        # next promotion reads the disk tier
        spilled = tiers.spill_host()
        disk_ms = swap("b", "b")
    finally:
        stop.set()
        t.join(timeout=30)
    snap = tiers.snapshot()
    return {
        "ttft_swap_cold_ms": round(cold_ms, 1),
        "ttft_swap_host_ms": round(host_ms, 1),
        "ttft_swap_disk_ms": round(disk_ms, 1),
        "tier_traffic_served": counts["served"],
        "tier_traffic_errors": counts["errors"],
        "tier_host_hits": snap["host"]["hits"],
        "tier_disk_hits": snap["disk"]["hits"],
        "tier_spills": snap["spills"],
        "tier_host_spilled": spilled,
    }


def measure_registry_outage(workdir: str, *, target_bytes: int = 16 << 20,
                            hidden: int = 512, inter: int = 1408,
                            vocab: int = 8192, prompt_len: int = 8,
                            new_tokens: int = 4, clients: int = 4) -> dict:
    """Registry-outage leg (ISSUE 19): kill the registry under live
    traffic and swap a model in OFFLINE from the pinned manifest + blob
    cache, then restart the registry and watch the publish outbox drain.

    Runs against its OWN in-process registry (the shared bench registry
    is a subprocess the leg could not brown out), killed mid-leg by
    :class:`RegistryKillSwitch` and restarted on the same port over the
    same store. Reported: ``outage_dropped_requests`` (data-path failures
    on model A across the whole outage — the acceptance bar is 0),
    ``swap_offline_ttft_ms`` (admin load of B with the registry dead ->
    first token), ``outage_swap_source`` (must be ``cache``: the ladder,
    not a lucky re-pull), and the outbox drain counters after restart."""
    import threading as _threading

    from modelx_tpu.dl import manifest_cache, program_store
    from modelx_tpu.dl.blob_cache import BlobCache
    from modelx_tpu.dl.serve import ModelServer, ServerSet
    from modelx_tpu.registry.fs import MemoryFSProvider
    from modelx_tpu.registry.server import Options, RegistryServer, free_port
    from modelx_tpu.registry.store_fs import FSRegistryStore
    from modelx_tpu.testing.faults import RegistryKillSwitch

    root = os.path.join(workdir, "outage")
    port = free_port()
    store = FSRegistryStore(MemoryFSProvider())
    srv = RegistryServer(Options(listen=f"127.0.0.1:{port}"), store=store)
    base = srv.serve_background()

    dirs: dict[str, str] = {}
    for i, name in enumerate(("a", "b")):
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        build_checkpoint(os.path.join(d, "model.safetensors"), target_bytes,
                         hidden=hidden, inter=inter, vocab=vocab, seed=i + 1)
        push_checkpoint(base, f"library/outage-{name}",
                        os.path.join(d, "model.safetensors"))
        dirs[name] = d

    # a real (tiny) program bundle for the outbox: publish parses bundle
    # meta before it ever talks to the registry, so the payload must be
    # wire-true even though its contents are fabricated
    aot_dir = os.path.join(root, "aot-cache")
    os.makedirs(aot_dir, exist_ok=True)
    with open(os.path.join(aot_dir, "aot-" + "ab" * 8 + ".bin"), "wb") as f:
        f.write(b"export-one")
    bundle = program_store.build_bundle(aot_dir)

    manifest_cache.configure_default(os.path.join(root, "manifest-cache"))
    manifest_cache.health().reset()
    sset = ServerSet({"a": ModelServer(dirs["a"], name="a")}, default="a",
                     allow_admin_load=True,
                     staging_root=os.path.join(root, "staging"))
    sset.pool.blob_cache = BlobCache(os.path.join(root, "blobcache"))
    sset.pool.attach_outbox(os.path.join(root, "outbox"), backoff_s=0.2)
    sset.load_all()
    switch = RegistryKillSwitch(srv)

    stop = _threading.Event()
    counts = {"served": 0, "errors": 0}
    rng = np.random.RandomState(7)
    prompt = rng.randint(1, vocab, (1, prompt_len)).astype(np.int32)
    bref = f"{base}/library/outage-b@v1"

    def traffic() -> None:
        while not stop.is_set():
            try:
                sset.servers["a"].generate(prompt, max_new_tokens=new_tokens)
                counts["served"] += 1
            except Exception:
                counts["errors"] += 1

    srv2 = None
    threads: list = []
    try:
        # warm the ladder: pull B through the caches once, then drop it
        sset.pool.request_load("b", ref=bref, wait=True)
        if sset.pool.states()["b"]["state"] != "READY":
            raise RuntimeError("outage warm pull of b failed")
        sset.pool.request_unload("b", wait=True)

        threads = [_threading.Thread(target=traffic, daemon=True)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30.0
        while counts["served"] < clients and time.monotonic() < deadline:
            time.sleep(0.02)
        if counts["served"] < clients:
            raise RuntimeError("outage traffic never established")

        # kill the control plane mid-traffic; a publish lands in the
        # spool and fails against the dead registry
        switch.kill()
        if not sset.pool.outbox.enqueue("programs", bref, bundle):
            raise RuntimeError("outbox refused the outage-era publish")
        sset.pool.outbox_drainer.kick()

        # offline swap-in: admin load of B with the registry dead
        t0 = time.monotonic()
        sset.pool.request_load("b", ref=bref, wait=True)
        state = sset.pool.states()["b"]
        if state["state"] != "READY":
            raise RuntimeError(f"offline swap of b landed {state}")
        sset.servers["b"].generate(prompt, max_new_tokens=1)  # first token
        swap_ms = (time.monotonic() - t0) * 1e3
        swap_source = state.get("load_source", "")
        cp_during = manifest_cache.health().state

        # restart the registry (same port, same store); the outbox drains
        srv2 = RegistryServer(Options(listen=f"127.0.0.1:{port}"),
                              store=store)
        srv2.serve_background()
        sset.pool.outbox_drainer.kick()
        deadline = time.monotonic() + 60.0
        while sset.pool.outbox.depth() and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        switch.kill()
        sset.pool.stop_outbox()
        if srv2 is not None:
            srv2.shutdown()
        # the leg marched the process-wide control-plane health through
        # offline; don't leak that state into later in-process legs
        manifest_cache.health().reset()
        with manifest_cache._default_lock:
            manifest_cache._default = None
            manifest_cache._default_configured = False
    return {
        "outage_dropped_requests": counts["errors"],
        "outage_traffic_served": counts["served"],
        "swap_offline_ttft_ms": round(swap_ms, 1),
        "outage_swap_source": swap_source,
        "outage_control_plane_state": cp_during,
        "outbox_depth_after_restart": sset.pool.outbox.depth(),
        "outbox_drained_total": sset.pool.outbox.stats["drained_total"],
        "outbox_publish_failures": sset.pool.outbox.stats[
            "publish_failures_total"],
    }


def measure_fleet(model_dir: str, *, pods: int = 3, clients: int = 4,
                  requests_per_client: int = 5, conversations: int = 6,
                  turns: int = 8, new_tokens: int = 8,
                  max_seq_len: int = 256) -> dict:
    """Fleet front-door leg (ISSUE 8): N in-process pods behind the
    router vs ONE pod addressed directly, identical client traffic.

    The pods are HTTP fronts around ONE loaded model (this host has one
    accelerator, so compute does not multiply with pod count);
    ``fleet_throughput_scaling`` therefore reads as the ROUTER TAX on this
    rig — ~1.0 means the front door's placement + proxy layer costs
    nothing observable at this load; a real fleet's scaling multiplies
    device counts on top. Also driven: repeated-prefix conversations for
    ``sticky_hit_ratio`` and a seeded pod kill under traffic for
    ``failover_recovery_ms`` (kill -> first successful routed response)
    with ``fleet_dropped_requests`` asserting the zero-drop contract."""
    import requests as _requests

    from modelx_tpu.dl.serve import ModelServer, ServerSet, serve
    from modelx_tpu.registry.server import free_port
    from modelx_tpu.router.registry import PodRegistry
    from modelx_tpu.router.server import FleetRouter, route_serve
    from modelx_tpu.testing.faults import PodKillSwitch

    server = ModelServer(model_dir, name="default", max_seq_len=max_seq_len)
    server.load()
    vocab = int(getattr(server.cfg, "vocab_size", 0) or 256)

    pod_set = []
    for _ in range(pods):
        sset = ServerSet({"default": server})
        sset.pool.mark_ready("default")
        httpd = serve(sset, listen=f"127.0.0.1:{free_port()}")
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        pod_set.append({"httpd": httpd, "url": url,
                        "kill": PodKillSwitch(httpd)})
    registry = PodRegistry([p["url"] for p in pod_set], poll_interval_s=0.5)
    router = FleetRouter(registry, request_timeout_s=60.0)
    router.start()
    rhttpd = route_serve(router, listen=f"127.0.0.1:{free_port()}")
    rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

    rng = np.random.RandomState(11)
    prompts = [rng.randint(1, vocab, (8,)).tolist()
               for _ in range(clients)]

    def drive(base_url: str) -> tuple[int, int, float]:
        """clients x requests_per_client generates; (ok, errors, seconds)."""
        counts = {"ok": 0, "err": 0}
        lock = threading.Lock()

        def client(prompt) -> None:
            sess = _requests.Session()
            for _ in range(requests_per_client):
                try:
                    r = sess.post(base_url + "/v1/generate",
                                  json={"tokens": [prompt],
                                        "max_new_tokens": new_tokens},
                                  timeout=60)
                    ok = r.status_code == 200
                except _requests.RequestException:
                    ok = False
                with lock:
                    counts["ok" if ok else "err"] += 1

        threads = [threading.Thread(target=client, args=(p,), daemon=True)
                   for p in prompts]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return counts["ok"], counts["err"], time.monotonic() - t0

    out: dict = {"fleet_pods": pods}
    try:
        # warm every compiled shape once so both legs measure serving, not
        # compilation (the same prompt shapes repeat throughout)
        drive(pod_set[0]["url"])
        ok_d, err_d, dt_d = drive(pod_set[0]["url"])
        ok_r, err_r, dt_r = drive(rbase)
        tps_direct = ok_d * new_tokens / max(dt_d, 1e-9)
        tps_routed = ok_r * new_tokens / max(dt_r, 1e-9)
        out["fleet_tokens_per_s_direct"] = round(tps_direct, 1)
        out["fleet_tokens_per_s_routed"] = round(tps_routed, 1)
        out["fleet_throughput_scaling"] = (
            round(tps_routed / tps_direct, 3) if tps_direct > 0 else None
        )
        out["fleet_traffic_errors"] = err_d + err_r

        # repeated-prefix conversations -> sticky hit ratio
        convs = [rng.randint(1, vocab, (8,)).tolist()
                 for _ in range(conversations)]
        before = router.sticky.stats()
        sess = _requests.Session()
        for _turn in range(turns):
            for conv in convs:
                sess.post(rbase + "/v1/generate",
                          json={"tokens": [conv],
                                "max_new_tokens": new_tokens}, timeout=60)
        after = router.sticky.stats()
        hits = after["sticky_hits"] - before["sticky_hits"]
        misses = after["sticky_misses"] - before["sticky_misses"]
        out["sticky_hit_ratio"] = (
            round(hits / (hits + misses), 4) if hits + misses else None
        )

        # fair-share storm (ISSUE 9): two clients — one 10x hotter —
        # saturate the SAME pods through a second, admission-enabled
        # router (fair_share + bounded backlog + retry budget on; the
        # main router above keeps observe-only defaults, which is itself
        # the no-behavior-change leg). Reported: Jain index of per-client
        # goodput (1.0 = equal shares; FIFO would give the hot client
        # ~10x), sheds by priority class, and retry amplification
        # (upstream attempts per logical request; ~1.0 = no retry storm).
        from modelx_tpu.router.admission import (
            AdmissionController,
            RetryBudget,
            jain_index,
        )

        fair_registry = PodRegistry([p["url"] for p in pod_set],
                                    poll_interval_s=0.5)
        fair_router = FleetRouter(
            fair_registry, request_timeout_s=30.0,
            admission=AdmissionController(fair_share=2, max_backlog=8),
            retry_budget=RetryBudget(ratio=0.2),
        )
        fair_router.start()
        fhttpd = route_serve(fair_router, listen=f"127.0.0.1:{free_port()}")
        fbase = f"http://127.0.0.1:{fhttpd.server_address[1]}"
        try:
            storm_prompt = rng.randint(1, vocab, (8,)).tolist()
            goodput = {"hot": 0, "cold": 0}
            storm_lock = threading.Lock()
            stop_at = time.monotonic() + 5.0

            def storm_client(name: str) -> None:
                # /v1/forward traffic, like the sticky drill: admission
                # semantics are identical for every proxied verb, and the
                # single-forward service time packs enough completions
                # into the window for the Jain index to mean something
                sess = _requests.Session()
                while time.monotonic() < stop_at:
                    try:
                        r = sess.post(
                            fbase + "/v1/forward",
                            json={"tokens": [storm_prompt]},
                            headers={"X-ModelX-Client": name},
                            timeout=30)
                        ok = r.status_code == 200
                    except _requests.RequestException:
                        ok = False
                    if ok:
                        # goodput counts only completions INSIDE the
                        # window: the backlogged (hot) client's queued
                        # waiters all drain after stop_at, and counting
                        # that tail would credit the monopolist with the
                        # very backlog fairness denied it
                        if time.monotonic() <= stop_at:
                            with storm_lock:
                                goodput[name] += 1
                    else:
                        # back off briefly on a shed: a zero-sleep 429
                        # spin across 20 threads would burn the one-CPU
                        # rig's cycles against the very router being
                        # measured (real clients honor Retry-After)
                        time.sleep(0.05)

            # 10x rate asymmetry by connection count: 20 hot vs 2 cold.
            # The cold client needs >= 2 connections to OCCUPY its fair
            # slot share — a single closed-loop connection waits a full
            # service time between its own grants and can never reach
            # 50% goodput no matter how fair the scheduler is
            storm_threads = [
                threading.Thread(target=storm_client, args=("hot",),
                                 daemon=True)
                for _ in range(20)
            ] + [
                threading.Thread(target=storm_client, args=("cold",),
                                 daemon=True)
                for _ in range(2)
            ]
            for t in storm_threads:
                t.start()
            for t in storm_threads:
                t.join()
            out["fair_share_jain_index"] = jain_index(
                [goodput["hot"], goodput["cold"]])
            out["fair_share_goodput"] = dict(goodput)
            adm = fair_router.admission.snapshot()
            out["shed_429_count_by_class"] = dict(adm["shed_by_class"])
            fm = fair_router.metrics.snapshot()
            dispatched = fm["requests_total"] - fm["admission_shed_total"]
            out["retry_amplification"] = (
                round(fm["upstream_attempts_total"] / dispatched, 3)
                if dispatched > 0 else None
            )
        finally:
            fhttpd.shutdown()
            fair_router.close()

        # pod-kill drill: kill the pod that owns a conversation, then time
        # kill -> first successful response for that same conversation
        target = convs[0]
        routes = router.metrics.snapshot()["routes"]
        victim = max(pod_set, key=lambda p: routes.get(p["url"], 0))
        dropped = 0
        victim["kill"].kill()
        t0 = time.monotonic()
        recovery_ms = None
        for _ in range(20):
            try:
                r = sess.post(rbase + "/v1/generate",
                              json={"tokens": [target],
                                    "max_new_tokens": new_tokens},
                              timeout=60)
                if r.status_code == 200:
                    recovery_ms = (time.monotonic() - t0) * 1e3
                    break
                dropped += 1
            except _requests.RequestException:
                dropped += 1
        out["failover_recovery_ms"] = (
            round(recovery_ms, 1) if recovery_ms is not None else None
        )
        out["fleet_dropped_requests"] = dropped
        snap = router.metrics.snapshot()
        out["fleet_failovers"] = snap["failovers_total"]
    finally:
        rhttpd.shutdown()
        router.close()
        for p in pod_set:
            p["httpd"].shutdown()
    return out


def measure_continuation(model_dir: str, *, pods: int = 2, clients: int = 8,
                         new_tokens: int = 16,
                         max_seq_len: int = 128) -> dict:
    """Stream-continuation drill (ISSUE 12): a seeded mid-stream pod kill
    behind the router under ``clients`` concurrent seeded SAMPLED streams
    (identical prompt+seed, so prefix stickiness pins them ALL to the
    dying pod). The router must resume every committed stream on a
    surviving pod token-exactly — ``tokens_lost`` asserts the zero-loss
    contract against an uninterrupted reference stream — and the only
    client-visible cost is one stall, ``continuation_gap_ms`` (last
    pre-kill line -> first post-resume line, read as the max inter-line
    arrival gap across clients; the kill is armed at a line boundary so
    other gaps are per-token decode intervals)."""
    import requests as _requests

    from modelx_tpu.dl.serve import ModelServer, ServerSet, serve
    from modelx_tpu.registry.server import free_port
    from modelx_tpu.router.registry import PodRegistry
    from modelx_tpu.router.server import FleetRouter, route_serve
    from modelx_tpu.testing.faults import PodKillSwitch

    server = ModelServer(model_dir, name="default", max_seq_len=max_seq_len)
    server.load()
    vocab = int(getattr(server.cfg, "vocab_size", 0) or 256)

    rng = np.random.RandomState(23)
    prompt = rng.randint(1, vocab, (6,)).tolist()
    body = {"tokens": [prompt], "max_new_tokens": new_tokens, "stream": True,
            "temperature": 0.9, "top_k": 8, "top_p": 0.95, "seed": 1234}

    # continuous-engine pods around the ONE loaded model: the resume
    # contract needs per-step sample streams (chunked single-row NDJSON)
    pod_set = []
    for _ in range(pods):
        sset = ServerSet({"default": server}, continuous_batch=True,
                         max_slots=2, stream_chunk_size=4)
        sset.pool.mark_ready("default")
        httpd = serve(sset, listen=f"127.0.0.1:{free_port()}")
        pod_set.append({"sset": sset, "httpd": httpd,
                        "url": f"http://127.0.0.1:{httpd.server_address[1]}",
                        "kill": PodKillSwitch(httpd, sset=sset)})

    def read_lines(resp) -> tuple[list, list]:
        """NDJSON payloads + per-line arrival stamps (chunk_size=1 so a
        line's stamp is its flush time, not a buffer boundary)."""
        payloads, stamps = [], []
        for raw in resp.iter_lines(chunk_size=1):
            if raw:
                stamps.append(time.monotonic())
                payloads.append(json.loads(raw))
        return payloads, stamps

    out: dict = {}
    router = None
    rhttpd = None
    try:
        # reference: an uninterrupted direct stream (also warms the
        # compiled shapes, so the routed leg's gap is not a compile)
        r = _requests.post(pod_set[0]["url"] + "/v1/generate", json=body,
                           stream=True, timeout=120)
        if r.status_code != 200:
            raise RuntimeError(f"reference stream failed: {r.text[:200]}")
        ref, _ = read_lines(r)
        ref_ids = [p["tokens"][0][0] for p in ref if "tokens" in p]
        if len(ref_ids) != new_tokens or not ref[-1].get("done"):
            raise RuntimeError(f"malformed reference stream: {ref}")

        registry = PodRegistry([p["url"] for p in pod_set],
                               poll_interval_s=0.5)
        router = FleetRouter(registry, request_timeout_s=60.0)
        router.start()
        rhttpd = route_serve(router, listen=f"127.0.0.1:{free_port()}")
        rbase = f"http://127.0.0.1:{rhttpd.server_address[1]}"

        # arm EVERY pod (placement is the router's call): at piece 2 of
        # the first stream served, the serving pod hard-dies at a line
        # boundary — listener closed, live connections severed
        fired = threading.Event()
        for p in pod_set:
            orig = p["sset"].stream_source

            def src(server_, tokens, n, samp, stop_token_ids=None,
                    _orig=orig, _pod=p, **kw):
                gen = _orig(server_, tokens, n, samp,
                            stop_token_ids=stop_token_ids, **kw)

                def run():
                    for i, piece in enumerate(gen):
                        if i == 2 and not fired.is_set():
                            fired.set()
                            time.sleep(0.3)  # router drains pieces 0-1
                            _pod["kill"].kill()
                            raise RuntimeError("pod dies")
                        yield piece

                return run()

            p["sset"].stream_source = src

        results: list = [None] * clients
        errors: list = []

        def client(i: int) -> None:
            try:
                r_ = _requests.post(rbase + "/v1/generate", json=body,
                                    stream=True, timeout=120)
                if r_.status_code != 200:
                    raise RuntimeError(f"status {r_.status_code}")
                results[i] = read_lines(r_)
            except Exception as e:  # surfaced below — the drill must fail
                errors.append(f"client {i}: {e!r}")

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError("; ".join(errors[:3]))
        if not fired.is_set():
            raise RuntimeError("seeded kill never fired")

        # zero-loss contract, per client: reference tokens NOT reproduced
        # in order (a wrong token loses the whole tail — the stream
        # diverged), summed across the fleet of streams
        lost = 0
        worst_gap = None
        for got, stamps in results:
            got_ids = [p_["tokens"][0][0] for p_ in got if "tokens" in p_]
            prefix = 0
            for a, b in zip(got_ids, ref_ids):
                if a != b:
                    break
                prefix += 1
            lost += len(ref_ids) - prefix
            for a, b in zip(stamps, stamps[1:]):
                if worst_gap is None or b - a > worst_gap:
                    worst_gap = b - a
        out["continuation_clients"] = clients
        out["tokens_lost"] = lost
        snap = router.metrics.snapshot()
        out["streams_continued"] = snap["streams_continued_total"]
        out["streams_severed"] = snap["severed_streams_total"]
        out["continuation_gap_ms"] = (
            round(worst_gap * 1e3, 1) if worst_gap is not None else None
        )
    finally:
        if rhttpd is not None:
            rhttpd.shutdown()
        if router is not None:
            router.close()
        for p in pod_set:
            p["httpd"].shutdown()
            for cb in p["sset"].cbatchers.values():
                cb.close()
                cb.release_device_state()
    return out


def measure_latency_breakdown(model_dir: str, *, requests_n: int = 8,
                              new_tokens: int = 8,
                              max_seq_len: int = 128) -> dict:
    """Per-request latency breakdown micro-leg (ISSUE 13): fire
    ``requests_n`` non-streaming requests at one continuous-batching pod
    and read the ``X-ModelX-Timing-*`` headers back. Two checks ride it:
    the phase spans must ACCOUNT for the request (the engine-reported
    ``total_ms`` covers >= 90% of the client-observed wall time — a
    breakdown that loses a tenth of the latency is lying), and the
    TTFT split (``ttft_queue_ms_*`` = admission wait vs
    ``ttft_compute_ms_*`` = prefill-to-first-token) is the capacity
    signal: queue-dominated TTFT means add pods, compute-dominated
    means the model/batching is the floor."""
    import requests as _requests

    from modelx_tpu.dl.serve import ModelServer, ServerSet, serve
    from modelx_tpu.dl.serving_errors import TIMING_HEADER_PREFIX
    from modelx_tpu.registry.server import free_port

    server = ModelServer(model_dir, name="default", max_seq_len=max_seq_len)
    server.load()
    vocab = int(getattr(server.cfg, "vocab_size", 0) or 256)
    sset = ServerSet({"default": server}, continuous_batch=True,
                     max_slots=2, stream_chunk_size=4)
    sset.pool.mark_ready("default")
    httpd = serve(sset, listen=f"127.0.0.1:{free_port()}")
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def hdr_ms(resp, key: str) -> float:
        name = TIMING_HEADER_PREFIX + "-".join(
            p.capitalize() for p in key.split("_"))
        return float(resp.headers.get(name, 0) or 0)

    rng = np.random.RandomState(31)
    queue_ms, compute_ms, coverage = [], [], []
    try:
        for i in range(requests_n):
            prompt = rng.randint(1, vocab, (6,)).tolist()
            t0 = time.monotonic()
            r = _requests.post(base + "/v1/generate",
                               json={"tokens": [prompt],
                                     "max_new_tokens": new_tokens},
                               timeout=120)
            wall_ms = (time.monotonic() - t0) * 1e3
            if r.status_code != 200:
                raise RuntimeError(f"request {i}: {r.text[:200]}")
            q, ttft = hdr_ms(r, "queue_ms"), hdr_ms(r, "ttft_ms")
            total = hdr_ms(r, "total_ms")
            if not total or not ttft:
                raise RuntimeError(
                    f"request {i}: timing headers missing: "
                    f"{dict(r.headers)}")
            queue_ms.append(q)
            compute_ms.append(max(0.0, ttft - q))
            coverage.append(total / wall_ms if wall_ms else 0.0)
    finally:
        httpd.shutdown()
        for cb in sset.cbatchers.values():
            cb.close()
            cb.release_device_state()

    worst = min(coverage)
    # the >= 0.9 coverage bar is a SOFT gate (known clean-tree flake on
    # loaded boxes: the wall clock spans scheduler preemptions the phase
    # spans legitimately exclude) — report the measured coverage and a
    # boolean instead of failing the whole bench run
    coverage_ok = worst >= 0.9
    if not coverage_ok:
        print(f"  warning: phase spans cover only {worst:.1%} of wall time "
              f"(coverage per request: {[round(c, 3) for c in coverage]}); "
              "queue/compute percentiles may under-report on this box",
              file=sys.stderr)

    def pct(vals, p) -> float:
        return round(float(np.percentile(vals, p)), 3)

    return {
        "breakdown_requests": requests_n,
        "breakdown_coverage_min": round(worst, 3),
        "breakdown_coverage_ok": coverage_ok,
        "ttft_queue_ms_p50": pct(queue_ms, 50),
        "ttft_queue_ms_p99": pct(queue_ms, 99),
        "ttft_compute_ms_p50": pct(compute_ms, 50),
        "ttft_compute_ms_p99": pct(compute_ms, 99),
    }


def measure_obs_overhead(model_dir: str, *, clients_n: int = 8,
                         requests_per_client: int = 3, new_tokens: int = 8,
                         rounds: int = 3, max_seq_len: int = 128) -> dict:
    """Observability-overhead micro-leg (ISSUE 15): the flight recorder
    and device telemetry are always-on by default, so their cost must be
    measured, not asserted. Runs the SAME 8-client generate workload
    against two pods that differ only in the recorder+telemetry knobs
    and compares best-of-``rounds`` wall time (min-of-rounds because CPU
    scheduling noise dwarfs the dict stores being measured — the bar is
    ``flightrec_overhead_pct`` < 2%). Also reads the measured-vs-
    reserved HBM accounting off the instrumented pod
    (``hbm_measured_vs_reserved_ratio``)."""
    import requests as _requests

    from modelx_tpu.dl.serve import ModelServer, ServerSet, serve
    from modelx_tpu.registry.server import free_port

    server = ModelServer(model_dir, name="default", max_seq_len=max_seq_len)
    server.load()
    vocab = int(getattr(server.cfg, "vocab_size", 0) or 256)
    out: dict = {"obs_overhead_clients": clients_n}

    def run_leg(obs_on: bool) -> float:
        sset = ServerSet({"default": server}, continuous_batch=True,
                         max_slots=4, stream_chunk_size=4,
                         flight_recorder=obs_on, device_telemetry=obs_on)
        sset.pool.mark_ready("default")
        httpd = serve(sset, listen=f"127.0.0.1:{free_port()}")
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        rng = np.random.RandomState(47)
        prompts = [rng.randint(1, vocab, (6,)).tolist()
                   for _ in range(clients_n)]
        errors: list = []

        def client(idx: int) -> None:
            try:
                for _ in range(requests_per_client):
                    r = _requests.post(
                        base + "/v1/generate",
                        json={"tokens": [prompts[idx]],
                              "max_new_tokens": new_tokens},
                        timeout=120)
                    if r.status_code != 200:
                        raise RuntimeError(f"client {idx}: {r.text[:200]}")
            except Exception as e:  # surfaced after join
                errors.append(e)

        def one_round() -> float:
            threads = [threading.Thread(target=client, args=(i,), daemon=True)
                       for i in range(clients_n)]
            t0 = time.monotonic()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise RuntimeError(f"obs-overhead leg failed: {errors[0]}")
            return time.monotonic() - t0

        try:
            one_round()  # warmup: compiles + first-admission costs
            best = min(one_round() for _ in range(rounds))
            if obs_on:
                # the instrumented leg also proves the telemetry surface:
                # measured occupancy lands next to the estimate
                snap = sset.pool.pool_snapshot()
                measured = int(snap.get("hbm_bytes_measured", 0))
                reserved = int(snap.get("hbm_reserved_bytes", 0))
                out["hbm_measured_vs_reserved_ratio"] = (
                    round(measured / reserved, 3) if reserved else None)
                out["hbm_measured_source"] = snap.get(
                    "hbm_measured_source", "none")
                cb = sset.cbatchers.get("default")
                out["flightrec_events"] = (
                    cb.flightrec.total if cb is not None
                    and cb.flightrec is not None else 0)
        finally:
            httpd.shutdown()
            for cb in sset.cbatchers.values():
                cb.close()
                cb.release_device_state()
        return best

    on_s = run_leg(True)
    off_s = run_leg(False)
    out["obs_on_wall_s"] = round(on_s, 4)
    out["obs_off_wall_s"] = round(off_s, 4)
    out["flightrec_overhead_pct"] = (
        round((on_s - off_s) / off_s * 100.0, 2) if off_s else None)
    return out


class _Budget:
    """Soft wall-clock budget for the whole capture (BENCH_r05 post-mortem:
    the run exceeded the driver's hard timeout and recorded NOTHING, rc
    124). Stages check ``allows(est)`` before starting and get skipped —
    recorded in ``timed_out_legs`` — when the remainder can't cover them;
    subprocess legs additionally clamp their own timeout to the remainder,
    so one wedged leg can't eat the capture."""

    def __init__(self, total_s: float) -> None:
        self.t0 = time.monotonic()
        self.total = float(total_s)

    def elapsed(self) -> float:
        return time.monotonic() - self.t0

    def remaining(self) -> float:
        return self.total - self.elapsed()

    def allows(self, est_s: float) -> bool:
        return self.remaining() >= est_s


def run_guarded(budget: _Budget, name: str, fn, est_s: float = 0.0,
                timed_out: list | None = None,
                leg_errors: dict | None = None):
    """Run one bench stage under the soft budget. Skipped stages land in
    ``timed_out`` (budget exhausted), failed ones in ``leg_errors`` — the
    capture keeps going and the final JSON always prints (a partial
    capture with named holes beats rc 124 with nothing)."""
    if not budget.allows(est_s):
        if timed_out is not None:
            timed_out.append(name)
        return None
    try:
        return fn()
    except Exception as e:
        if leg_errors is None:
            raise
        leg_errors[name] = repr(e)[:300]
        return None


def run_leg(kind: str, base: str, repo: str, workdir: str,
            timeout_s: float = 900.0) -> dict:
    """One timed leg in a FRESH subprocess (fresh per-process tunnel
    throttle state — see module docstring). Returns the child's JSON."""
    env = _device_child_env()  # children use the real device
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--leg", kind, base, repo, workdir],
        capture_output=True, text=True, env=env,
        timeout=max(60.0, timeout_s),
    )
    if p.returncode != 0:
        raise RuntimeError(f"{kind} leg failed: {p.stderr[-2000:]}")
    return json.loads(p.stdout.strip().splitlines()[-1])


def leg_main(kind: str, base: str, repo: str, workdir: str) -> int:
    """Child entry for one timed leg. Loads, then probes the raw link in
    the SAME process (still pre-first-execution, so the probe reflects the
    state the leg actually saw)."""
    from modelx_tpu.client.client import Client

    client = Client(base, quiet=True)
    manifest = client.get_manifest(repo, "v1")
    desc = next(b for b in manifest.blobs if b.name.endswith(".safetensors"))
    size = desc.size

    import jax

    devices = jax.devices()
    if kind == "baseline":
        secs = run_baseline(base, repo, desc, workdir, devices)
        print(json.dumps({
            "seconds": round(secs, 3),
            "link_gbps": round(probe_link_gbps(devices[0]), 3),
        }))
        return 0
    from modelx_tpu import native
    from modelx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(f"dp={len(devices)}")
    cache = None
    prefer_local: bool | None = None
    if kind in ("cold", "warm"):
        # blob-cache legs model a REMOTE pod: skip the colocated file
        # redirect (the registry and the leg share this host) so the cold
        # leg streams HTTP + tees to the cache, and the warm leg must be
        # served by the cache alone (zero network reads)
        from modelx_tpu.dl.blob_cache import BlobCache

        cache_dir = os.path.join(workdir, "blobcache")
        if kind == "cold":
            shutil.rmtree(cache_dir, ignore_errors=True)
        cache = BlobCache(cache_dir)
        prefer_local = False
    secs, src, stats = run_ours(
        client, repo, desc, mesh, size,
        quantize="int8" if kind == "int8" else None,
        cache=cache, prefer_local=prefer_local,
    )
    rec = {
        "seconds": round(secs, 3),
        "source": src,
        "native": native.available(),
        "bytes_fetched": stats.bytes_fetched,
        "fetch_seconds": round(stats.fetch_seconds, 3),
        "bytes_to_device": stats.bytes_to_device,
        "fetch_width": stats.fetch_width,
        "fetch_backoffs": stats.fetch_backoffs,
        "fetch_growths": stats.fetch_growths,
        "overlap_seconds": round(stats.overlap_seconds, 3),
        "device_put_seconds": round(stats.device_put_seconds, 3),
        "staging_allocs": stats.staging_allocs,
        "staging_reuses": stats.staging_reuses,
        "link_gbps": round(probe_link_gbps(devices[0]), 3),
    }
    if cache is not None:
        # warm = the load came off the local cache tier (LocalFileSource
        # over the verified entry), i.e. zero network reads
        rec["cache_state"] = "warm" if src == "LocalFileSource" else "cold"
        rec["blob_cache"] = dict(cache.stats)
    print(json.dumps(rec))
    return 0


def _device_child_env() -> dict:
    """Environment for subprocesses that must see the REAL device: this
    repo on PYTHONPATH, and any JAX_PLATFORMS=cpu override (the parent's
    own stay-off-the-TPU discipline) stripped."""
    here = os.path.dirname(os.path.abspath(__file__))
    existing = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               PYTHONPATH=here + (os.pathsep + existing if existing else ""))
    env.pop("JAX_PLATFORMS", None)
    return env


def wait_for_device(max_wait_s: float = 1800.0, probe_timeout_s: float = 120.0,
                    retry_s: float = 30.0) -> float:
    """Block until the ACCELERATOR answers, up to ``max_wait_s``.

    The tunnel relay occasionally dies and restarts (observed live: a
    mid-bench 'Connection refused' on its remote_compile endpoint, with
    ``jax.devices()`` hanging afterwards). A capture that starts while
    it's down burns every leg's full subprocess timeout and records
    nothing — probing first in SHORT-LIVED subprocesses (a hung backend
    init cannot be cancelled in-process) turns a transient outage into a
    delayed capture instead of a failed one. The probe REJECTS a
    cpu-fallback backend (outage modes where discovery fails fast would
    otherwise pass vacuously) and the last probe's stderr rides in the
    final error so a broken environment doesn't masquerade as a relay
    outage. Returns seconds waited."""
    env = _device_child_env()
    t0 = time.monotonic()
    last_err = ""
    while True:
        try:
            p = subprocess.run(
                [sys.executable, "-c",
                 "import jax; assert jax.devices()[0].platform != 'cpu', "
                 "'cpu fallback — accelerator not found'"],
                env=env, timeout=probe_timeout_s,
                stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
            )
            if p.returncode == 0:
                waited = time.monotonic() - t0
                if waited > probe_timeout_s:
                    print(f"# device came back after {waited:.0f}s",
                          file=sys.stderr)
                return waited
            last_err = (p.stderr or "").strip()[-500:]
        except subprocess.TimeoutExpired:
            last_err = f"probe hung > {probe_timeout_s:.0f}s (backend init)"
        if time.monotonic() - t0 > max_wait_s:
            raise RuntimeError(
                f"accelerator unreachable for {max_wait_s:.0f}s "
                "(tunnel relay down?) — refusing to record a dead capture; "
                f"last probe: {last_err or 'no stderr'}"
            )
        time.sleep(retry_s)


def main() -> None:
    workdir = tempfile.mkdtemp(prefix="modelx-bench-")
    settle_s = float(os.environ.get("BENCH_SETTLE_S", 8.0))
    # soft wall-clock budget for the WHOLE capture (BENCH_r05 post-mortem:
    # the run outgrew the driver's hard timeout and recorded NOTHING, rc
    # 124). Stages that no longer fit are skipped — named in
    # ``timed_out_legs`` — subprocess children clamp their timeouts to the
    # remainder, and the one JSON line prints no matter what. The default
    # must clear the harness's hard wall with margin (r05 recurred at
    # 2400: the budget equalled the wall, so any pre-budget overhead —
    # device wait, interpreter start — pushed the capture past it and the
    # driver killed the print itself).
    budget = _Budget(float(os.environ.get("BENCH_BUDGET_S", 1500.0)))
    timed_out: list[str] = []
    leg_errors: dict[str, str] = {}
    # headline keys are always present so a partial capture still parses
    # as the bench schema; stages fill them in as they complete
    out: dict = {"metric": "registry_to_hbm_gbps", "value": None,
                 "unit": "GB/s"}
    srv = None
    try:
        wait_for_device(
            # a down relay must not eat the whole budget and then record a
            # dead capture: cap the wait so a late device leaves a usable
            # remnant for at least the loader legs
            max_wait_s=min(
                float(os.environ.get("BENCH_DEVICE_WAIT_S", 1800.0)),
                max(120.0, budget.remaining() - 900.0),
            )
        )
        ckpt = os.path.join(workdir, "model.safetensors")
        target = int(os.environ.get("BENCH_BYTES", 512 * 1024 * 1024))
        size = build_checkpoint(ckpt, target)
        srv, base = start_registry(workdir)
        client, desc = push_checkpoint(base, "library/bench", ckpt)

        # small model for TTFT (BASELINE #3 scaled to the rig: the 500 ms
        # budget was set for a multi-chip pod; this rig is one tunneled chip)
        ttft_ckpt = os.path.join(workdir, "ttft.safetensors")
        build_checkpoint(ttft_ckpt, 48 * 1024 * 1024, hidden=512, inter=1408, vocab=8192)
        push_checkpoint(base, "library/ttft", ttft_ckpt)

        # TTFT first and subprocess-per-run; like every timed leg below, the
        # children own the device — this parent must not touch the TPU until
        # all measured subprocesses are done.
        # half the leg settle: the 48 MB TTFT children sip the burst bucket
        # where the 512 MB legs gulp it, but BENCH_SETTLE_S must scale both.
        # r05 trim: 3 scored runs + 1 int8 sample (medians were stable by 3
        # in every prior capture) instead of 5 + 2
        ttft = run_guarded(
            budget, "ttft",
            lambda: measure_ttft(
                base, "library/ttft", workdir, runs=3, int8_runs=1,
                settle_s=settle_s / 2,
                child_timeout_s=min(600.0, budget.remaining()),
            ),
            est_s=180.0, timed_out=timed_out, leg_errors=leg_errors,
        ) or {}
        # warm-restart TTFT: the children share a blob cache, run 0 fills
        # it, the scored runs model a pod restart that skips the network
        warm_ttft = run_guarded(
            budget, "ttft_warm",
            lambda: measure_ttft(
                base, "library/ttft", workdir, runs=2, int8_runs=0,
                settle_s=settle_s / 2,
                blob_cache_dir=os.path.join(workdir, "ttft-blobcache"),
                child_timeout_s=min(600.0, budget.remaining()),
            ),
            est_s=120.0, timed_out=timed_out, leg_errors=leg_errors,
        )
        if warm_ttft:
            ttft.update(ttft_warm_fields(warm_ttft))
        out.update(ttft)

        # compiled-program registry leg (ISSUE 11): the first pod pays the
        # full compile and publishes its AOT surface as a program bundle;
        # a second fresh-process pod with an EMPTY compile cache pulls the
        # bundle and warm-starts its compile leg — both children on the
        # same repo/registry as the TTFT legs above, with per-child fresh
        # cache dirs so nothing leaks between them
        out.update(run_guarded(
            budget, "program_store",
            lambda: measure_program_store(
                base, "library/ttft", workdir, settle_s=settle_s / 2,
                child_timeout_s=min(600.0, budget.remaining()),
            ),
            est_s=120.0, timed_out=timed_out, leg_errors=leg_errors,
        ) or {})

        # alternate subprocess legs with settle pauses (token-bucket tunnel;
        # see module docstring), baseline first = any leftover burst credit
        # goes to the reference's shape, not ours
        baseline_recs: list[dict] = []
        ours_recs: list[dict] = []
        int8_recs: list[dict] = []

        def leg(kind: str) -> dict:
            time.sleep(settle_s)
            return run_leg(kind, base, "library/bench", workdir,
                           timeout_s=min(900.0, budget.remaining()))

        # r05 trim: best-of-2 rounds (was 3) — the collapsed-leg guard
        # below already reruns throttled captures, so the third round
        # bought little evidence for ~3 subprocess legs of wall clock
        rounds = int(os.environ.get("BENCH_LOAD_ROUNDS", 2))
        for i in range(rounds):
            # each round is up to 3 subprocess legs: skip remaining rounds
            # (named) rather than let them blow the capture's budget
            if i and not budget.allows(3 * (settle_s + 60.0)):
                timed_out.append(f"load_round_{i}")
                break
            baseline_recs.append(leg("baseline"))
            ours_recs.append(leg("ours"))
            if i < 1:
                # int8 deploy leg: the loader quantizes on the host
                # (native fused kernel), so HALF the bytes cross the
                # link and the model decodes faster once resident
                # (int8_decode_speedup below). Effective GB/s counts
                # SOURCE bytes.
                int8_recs.append(leg("int8"))

        legs_retried: list[str] = []

        def best(recs: list[dict]) -> dict:
            return min(recs, key=lambda r: r["seconds"])

        def link_ceiling() -> float:
            return max(
                (r.get("link_gbps") or 0.0)
                for r in baseline_recs + ours_recs + int8_recs
            )

        # collapsed-leg guard (VERDICT r4): a leg that lost 4x to the
        # same-round baseline AND sat under 10% of the rig's measured link
        # is a throttled capture, not a code result — rerun it once in
        # another fresh process and keep the best.
        def collapsed(rec: dict, baseline_gbps: float) -> bool:
            gbps = size / rec["seconds"] / 1e9
            link = link_ceiling()
            return gbps < 0.25 * baseline_gbps and (
                not link or gbps < 0.10 * link
            )

        retry_est = settle_s + 60.0
        base_gbps = size / best(baseline_recs)["seconds"] / 1e9
        if base_gbps < 0.10 * link_ceiling() and budget.allows(retry_est):
            # the baseline itself collapsed: an inflated ratio would flatter
            # us dishonestly — rerun the baseline too
            baseline_recs.append(leg("baseline"))
            legs_retried.append("baseline")
            base_gbps = size / best(baseline_recs)["seconds"] / 1e9
        if collapsed(best(ours_recs), base_gbps) and budget.allows(retry_est):
            ours_recs.append(leg("ours"))
            legs_retried.append("ours")
        if collapsed(best(int8_recs), base_gbps) and budget.allows(retry_est):
            int8_recs.append(leg("int8"))
            legs_retried.append("int8")

        # blob-cache cold/warm split: one cold leg (HTTP + tee, fresh
        # cache), then warm legs served purely off the local cache tier —
        # the ServerlessLLM re-deploy story, measured
        def cold_warm() -> dict:
            cold_rec = leg("cold")
            warm_recs = [leg("warm"), leg("warm")]
            return cache_split_summary(size, cold_rec, best(warm_recs))

        cache_split = run_guarded(
            budget, "cache_split", cold_warm, est_s=3 * (settle_s + 60.0),
            timed_out=timed_out, leg_errors=leg_errors,
        ) or {}

        ours_s = best(ours_recs)["seconds"]
        baseline_s = best(baseline_recs)["seconds"]
        int8_s = best(int8_recs)["seconds"]
        best_rec = best(ours_recs)
        int8_rec = best(int8_recs)
        link_gbps = link_ceiling()

        def mt_stage() -> dict:
            m = measure_multitenant(base, "library/bench", desc, size)
            m.update(
                measure_redirect_multitenant(base, "library/bench", desc, size)
            )
            # load separation (the reference's core architectural claim,
            # docs/api.md:32-42): per-leg pass verdicts, stated explicitly
            # so a 1-core host's scheduling noise can't read as an
            # architecture regression. Direct legs stream through the
            # server process; the redirect legs never touch it — pass =
            # redirect path under 4-way load sustains the direct path's
            # single-client rate, with a 10% tolerance for the shared-core
            # scheduling noise.
            m["load_separation_pass"] = bool(
                m["mt_redirect_aggregate_gbps"] >= 0.9 * m["mt_single_gbps"]
            )
            return m

        multitenant = run_guarded(
            budget, "multitenant", mt_stage, est_s=150.0,
            timed_out=timed_out, leg_errors=leg_errors,
        ) or {}

        ours_gbps = size / ours_s / 1e9
        baseline_gbps = size / baseline_s / 1e9

        # headline recorded BEFORE the serving legs: if a later stage dies
        # or the budget runs out, the loader capture still prints
        out.update({
            "value": round(ours_gbps, 3),
            "vs_baseline": round(ours_gbps / baseline_gbps, 3),
            "baseline_gbps": round(baseline_gbps, 3),
            "bytes": size,
            "seconds": round(ours_s, 3),
            "baseline_seconds": round(baseline_s, 3),
            "seconds_runs": [round(r["seconds"], 3) for r in ours_recs],
            "baseline_seconds_runs": [round(r["seconds"], 3) for r in baseline_recs],
            # every timed leg ran in its own fresh subprocess; the guard
            # reruns collapsed captures once (see module docstring)
            "leg_isolation": "subprocess",
            "legs_retried": legs_retried,
            # per-leg link probes (same process as the leg, post-load):
            # the ceiling each leg actually had
            "leg_link_gbps": [r.get("link_gbps") for r in ours_recs],
            # decomposition of the winning leg: aggregate fetch-thread rate
            # vs bytes that crossed the host->device link (fetch and
            # transfer overlap, so the pieces don't sum to wall time)
            "fetch_gbps": round(
                best_rec["bytes_fetched"] / max(best_rec["fetch_seconds"], 1e-9) / 1e9, 3
            ),
            "fetch_thread_seconds": best_rec["fetch_seconds"],
            "bytes_to_device": best_rec["bytes_to_device"],
            "fetch_width": best_rec.get("fetch_width"),
            "fetch_backoffs": best_rec.get("fetch_backoffs"),
            "fetch_growths": best_rec.get("fetch_growths"),
            "overlap_seconds": best_rec.get("overlap_seconds"),
            "device_put_seconds": best_rec.get("device_put_seconds"),
            "staging_allocs": best_rec.get("staging_allocs"),
            "staging_reuses": best_rec.get("staging_reuses"),
            # blob-cache tier: cold tee vs warm (zero-network) restart
            **cache_split,
            # int8 deploy leg: same source checkpoint, half the link bytes
            "int8_load_seconds": round(int8_s, 3),
            "int8_load_gbps_effective": round(size / int8_s / 1e9, 3),
            "int8_vs_baseline": round(baseline_s / int8_s, 3),
            "int8_bytes_to_device": int8_rec["bytes_to_device"],
            "link_gbps": round(link_gbps, 3),
            "link_utilization": round(ours_gbps / link_gbps, 3) if link_gbps else None,
            "engine": {"native": best_rec.get("native"), "source": best_rec.get("source")},
            **multitenant,
        })

        if not budget.allows(240.0):
            # the serving legs need an in-process load + compiles: don't
            # start what can't finish
            timed_out.append("serving")
            return
        # the measured subprocesses are done: the parent may now touch the
        # device for the serving legs (its own link state no longer matters)
        import jax

        from modelx_tpu.dl.loader import load_safetensors
        from modelx_tpu.dl.sharding import LLAMA_RULES
        from modelx_tpu.dl.initializer import _blob_source
        from modelx_tpu.parallel.mesh import make_mesh

        devices = jax.devices()
        device_kind = getattr(devices[0], "device_kind", str(devices[0]))
        mesh = make_mesh(f"dp={len(devices)}")
        out.update({
            "device": str(devices[0]),
            "device_kind": device_kind,
            "n_devices": len(devices),
        })

        # serving: load once more (cheap assert it still works), reuse arrays
        source = _blob_source(client, "library/bench", desc)
        try:
            loaded, _stats = load_safetensors(source, mesh, LLAMA_RULES)
        finally:
            if hasattr(source, "close"):
                source.close()

        def guard(name: str, fn, est_s: float) -> None:
            out.update(run_guarded(budget, name, fn, est_s=est_s,
                                   timed_out=timed_out,
                                   leg_errors=leg_errors) or {})

        guard("serving",
              lambda: measure_serving(loaded, mesh, device_kind), 120.0)
        dtps = out.get("decode_tokens_per_s")
        guard("continuous",
              lambda: measure_continuous(loaded, mesh, dtps), 90.0)
        # pipelined-dispatch leg (ISSUE 7): identical traffic against
        # serial boundaries vs dispatch-ahead — the per-chunk overhead and
        # continuous-vs-batch ratio the tentpole is accountable for
        guard("decode_pipelined",
              lambda: measure_decode_pipelined(loaded, mesh, dtps), 120.0)
        # mixed prefill/decode leg: admit a long prompt into a saturated
        # decode batch; chunked prefill must bound the ITL jitter the
        # monolithic-admission baseline inflicts (ISSUE 2 acceptance)
        guard("mixed_prefill",
              lambda: measure_mixed_prefill(loaded, mesh), 90.0)
        # overload/self-healing leg: bounded admission sheds, deadline
        # expiry, and supervised recovery after an injected engine crash
        # (ISSUE 3 acceptance)
        guard("overload", lambda: measure_overload(loaded, mesh), 90.0)
        del loaded

        # model-swap leg: unload A / load B through the lifecycle pool
        # under live traffic to C, cold vs blob-cache-warm (ISSUE 5)
        guard("model_swap", lambda: measure_model_swap(base, workdir), 180.0)

        # registry-outage drill: brown out / kill the control plane under
        # live traffic; the data path must not drop a request and a swap-in
        # must still materialize from the pinned-manifest + blob caches
        # (ISSUE 19 acceptance: outage_dropped_requests == 0)
        guard("registry_outage",
              lambda: measure_registry_outage(workdir), 180.0)

        # fleet front-door leg: N pods behind the router vs one pod
        # direct (router tax on a one-device rig), sticky-hit ratio on
        # repeated-prefix conversations, pod-kill failover drill (ISSUE 8)
        def fleet_leg() -> dict:
            fleet_dir = os.path.join(workdir, "fleet")
            os.makedirs(fleet_dir, exist_ok=True)
            build_checkpoint(os.path.join(fleet_dir, "model.safetensors"),
                             48 * 1024 * 1024, hidden=512, inter=1408,
                             vocab=8192)
            return measure_fleet(fleet_dir)

        guard("fleet", fleet_leg, 180.0)

        # stream-continuation drill: seeded mid-stream pod kill behind the
        # router on a seeded sampled stream; the resume contract must hold
        # token-exactly (tokens_lost == 0) and the cost is one stall
        # (continuation_gap_ms) — ISSUE 12 acceptance
        def continuation_leg() -> dict:
            cont_dir = os.path.join(workdir, "fleet")
            if not os.path.exists(os.path.join(cont_dir,
                                               "model.safetensors")):
                os.makedirs(cont_dir, exist_ok=True)
                build_checkpoint(
                    os.path.join(cont_dir, "model.safetensors"),
                    48 * 1024 * 1024, hidden=512, inter=1408, vocab=8192)
            return measure_continuation(cont_dir)

        guard("continuation", continuation_leg, 120.0)

        # content-addressed prefix-KV leg (ISSUE 20): pod 1 publishes the
        # hot shared prompt's prefill KV to the registry; a fresh pod 2
        # installs it and serves that prompt with a suffix-only prefill
        def kv_leg() -> dict:
            kv_dir = os.path.join(workdir, "fleet")
            if not os.path.exists(os.path.join(kv_dir, "model.safetensors")):
                os.makedirs(kv_dir, exist_ok=True)
                build_checkpoint(
                    os.path.join(kv_dir, "model.safetensors"),
                    48 * 1024 * 1024, hidden=512, inter=1408, vocab=8192)
            return measure_kv_store(kv_dir, base)

        guard("kv_store", kv_leg, 120.0)

        # int8 weight-only serving: per-step weight reads halve, so decode
        # (HBM-bound) speeds up — the quantize flag the serve sidecar ships
        def int8_serving() -> dict:
            source = _blob_source(client, "library/bench", desc)
            try:
                loaded_q, _ = load_safetensors(
                    source, mesh, LLAMA_RULES, quantize="int8"
                )
            finally:
                if hasattr(source, "close"):
                    source.close()
            q = measure_serving(
                loaded_q, mesh, device_kind, decode_only=True,
                weight_bytes_per_param=1,  # int8 matmuls (embed stays bf16)
            )
            return {
                "int8_decode_tokens_per_s": q.get("decode_tokens_per_s"),
                "int8_decode_speedup": (
                    round(q["decode_tokens_per_s"] / dtps, 2)
                    if q.get("decode_tokens_per_s") and dtps else None
                ),
            }

        guard("int8_serving", int8_serving, 120.0)
    except Exception as e:
        import traceback

        traceback.print_exc(file=sys.stderr)
        leg_errors["fatal"] = repr(e)[:500]
    finally:
        # the one JSON line ALWAYS prints: a partial capture with named
        # holes beats rc 124 with nothing (BENCH_r05)
        out["timed_out_legs"] = timed_out
        if leg_errors:
            out["leg_errors"] = leg_errors
        out["bench_budget_s"] = budget.total
        out["bench_elapsed_s"] = round(budget.elapsed(), 1)
        print(json.dumps(out))
        if srv is not None:
            srv.terminate()  # before rmtree: never delete a live server's data
        shutil.rmtree(workdir, ignore_errors=True)


def tiny_main() -> int:
    """``bench.py --tiny``: the CPU proxy capture (``JAX_PLATFORMS=cpu``),
    one JSON line. Three stages: the fleet leg on a tiny synthetic llama
    (``fleet_throughput_scaling`` / ``sticky_hit_ratio`` /
    ``failover_recovery_ms``, ISSUE 8), the stream-continuation drill
    (``tokens_lost`` == 0 / ``continuation_gap_ms``, ISSUE 12), then
    the compiled-program registry
    acceptance (ISSUE 11) against a real registry subprocess — a
    bundle-warm second process's compile leg vs the cold publisher's
    (``program_warm_compile_ratio``, pass <= 0.5), and the lifecycle
    pool's swap-in time for a manifest with vs without programs
    (``ttft_swap_cold_ms`` vs ``ttft_swap_cold_ms_programs``)."""
    workdir = tempfile.mkdtemp(prefix="modelx-fleet-tiny-")
    srv = None
    try:
        import jax

        from modelx_tpu.dl import safetensors as st
        from modelx_tpu.models import llama

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        st.write_safetensors(
            os.path.join(workdir, "model.safetensors"),
            {k: np.asarray(v) for k, v in params.items()},
        )
        out: dict = {"metric": "fleet_throughput_scaling", "value": None,
                     "unit": "x"}
        out.update(measure_fleet(workdir, pods=3, clients=2,
                                 requests_per_client=3, conversations=4,
                                 turns=12, new_tokens=4, max_seq_len=128))
        out["value"] = out.get("fleet_throughput_scaling")

        # stream-continuation drill (ISSUE 12): seeded mid-stream pod
        # kill behind the router; tokens_lost must read 0
        out.update(measure_continuation(workdir, new_tokens=12,
                                        max_seq_len=128))

        # per-request latency breakdown (ISSUE 13): the engine's phase
        # timeline must account for >= 90% of client wall time, and the
        # TTFT queue-vs-compute split is the scaling signal
        out.update(measure_latency_breakdown(workdir, new_tokens=8,
                                             max_seq_len=128))

        # observability overhead (ISSUE 15): the always-on flight
        # recorder + device telemetry must cost < 2% of wall time, and
        # the measured-vs-reserved HBM accounting must be present
        out.update(measure_obs_overhead(workdir, new_tokens=8,
                                        max_seq_len=128))

        # tensor-parallel serving (ISSUE 16): continuous decode on a
        # forced-host dp=2,tp=2 mesh vs the dp=1 baseline — per-device
        # ratio passes >= 0.7, and the dp=1 engine must stay byte-exact
        out.update(measure_sharded_serving(workdir))

        # fused-sampling decode leg (ISSUE 17): mixed sampled/greedy
        # clients through the fused on-device sampler vs the all-greedy
        # baseline (sampled_vs_greedy_decode_ratio), the sampling
        # microbench at the engine's logits shape (sampling_ms_p50/p99
        # vs sampling_sort_ms_p50), and the pad-fraction accounting
        from modelx_tpu.parallel.mesh import make_mesh

        out.update(measure_decode_pipelined(
            params, make_mesh("dp=1"), None, clients=3, chunk=4,
            new_tokens=24, prompt_len=8, max_len=96))

        # --- compiled-program registry (ISSUE 11), CPU proxy ---
        # bench-shaped small checkpoint, not LlamaConfig.tiny: the ratio
        # should be measured on a model whose trace+compile is non-trivial
        prog_dir = os.path.join(workdir, "prog")
        os.makedirs(prog_dir, exist_ok=True)
        build_checkpoint(os.path.join(prog_dir, "model.safetensors"),
                         16 * 1024 * 1024, hidden=512, inter=1408, vocab=8192)
        srv, base = start_registry(workdir)
        push_checkpoint(base, "library/prog",
                        os.path.join(prog_dir, "model.safetensors"))
        env = dict(os.environ,
                   PYTHONPATH=os.path.dirname(os.path.abspath(__file__)),
                   JAX_PLATFORMS="cpu")

        # tiered-state swap (ISSUE 18): cold vs host-tier vs disk-tier
        # swap-in through the pool, live traffic on a neighbor model.
        # The bar: host promotion < 0.5x the cold swap. (The program leg
        # below re-sets ttft_swap_cold_ms with its own cold baseline;
        # the ratio here is computed against the tier leg's own.)
        tier = measure_tier_swap(base, workdir)
        out.update(tier)
        out["tier_swap_host_ratio"] = (
            round(tier["ttft_swap_host_ms"] / tier["ttft_swap_cold_ms"], 3)
            if tier["ttft_swap_cold_ms"] else None
        )

        # registry-outage leg (ISSUE 19): kill the registry under live
        # traffic, swap a model in offline off the pinned manifest + blob
        # cache, restart, drain the publish outbox. The acceptance bar:
        # outage_dropped_requests == 0.
        out.update(measure_registry_outage(workdir))

        # content-addressed prefix-KV leg (ISSUE 20): pod 1 streams a hot
        # shared prompt past the publish threshold and attaches its prefix
        # KV to the version; a fresh pod 2 installs it from the registry
        # and answers that prompt with a suffix-only prefill
        # (kv_warm_ttft_ratio, pass < 0.6)
        out.update(measure_kv_store(workdir, base, dtype="float32",
                                    prompt_len=48, suffix_len=8,
                                    new_tokens=4, max_seq_len=128))

        from modelx_tpu.dl.blob_cache import BlobCache
        from modelx_tpu.dl.serve import (ModelServer, ServerSet,
                                         enable_compile_cache)

        swap_root = os.path.join(workdir, "prog-swap")
        sset = ServerSet({"c": ModelServer(workdir, name="c")}, default="c",
                         allow_admin_load=True,
                         staging_root=os.path.join(swap_root, "staging"))
        sset.pool.blob_cache = BlobCache(os.path.join(swap_root, "blobcache"))
        sset.load_all()
        toks = np.ones((1, 16), np.int32)

        def one_swap(tag: str) -> float:
            # fresh compile cache per swap: every swap is a cold pod boot;
            # only the manifest's program bundle may warm the compile leg
            enable_compile_cache(os.path.join(swap_root, f"cache-{tag}"))
            t0 = time.monotonic()
            sset.pool.request_load("b", ref=f"{base}/library/prog@v1",
                                   wait=True)
            state = sset.pool.states()["b"]
            if state["state"] != "READY":
                raise RuntimeError(f"swap load of b landed {state}")
            sset.servers["b"].forward_argmax(toks)  # first token, AOT shape
            dt = (time.monotonic() - t0) * 1e3
            sset.pool.request_unload("b", wait=True)
            return dt

        # prime swap (unscored) fills the blob cache, so the two scored
        # swaps are equally byte-warm and differ ONLY in program bundles
        one_swap("prime")
        plain_ms = one_swap("plain")  # manifest holds no programs yet

        # pod-1-pays: the cold ttft child publishes its surface, the warm
        # child proves a second process boots compile-warm off the registry
        out.update(measure_program_store(base, "library/prog", workdir,
                                         settle_s=0.0, child_timeout_s=300.0,
                                         env=env))

        # full-surface publish (the `modelx programs push` flow) so the
        # pool's warmup shapes are covered, then the with-programs swap
        p = subprocess.run(
            [sys.executable, "-m", "modelx_tpu.cli", "programs", "push",
             f"{base}/library/prog@v1"],
            capture_output=True, text=True, env=env, timeout=300)
        if p.returncode != 0:
            raise RuntimeError(f"programs push failed: {p.stderr[-2000:]}")
        progs_ms = one_swap("programs")
        out["ttft_swap_cold_ms"] = round(plain_ms, 1)
        out["ttft_swap_cold_ms_programs"] = round(progs_ms, 1)
        out["program_swap_ratio"] = (
            round(progs_ms / plain_ms, 3) if plain_ms else None
        )
        print(json.dumps(out))
        return 0
    finally:
        if srv is not None:
            srv.terminate()
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--leg":
        sys.exit(leg_main(sys.argv[2], sys.argv[3], sys.argv[4], sys.argv[5]))
    if len(sys.argv) > 1 and sys.argv[1] == "--tiny":
        sys.exit(tiny_main())
    if len(sys.argv) > 1 and sys.argv[1] == "--sharded-child":
        sys.exit(sharded_child_main(sys.argv[2]))
    sys.exit(main())
