"""Compiled-program registry: ship AOT executables with the weights.

Cold start is compile-bound, not byte-bound (BENCH r05: warm weights ready
in ~330-480 ms while trace+lower+compile costs ~1.8 s of a ~2.3 s TTFT).
dl/aot_cache.py already removes that cost for a *node* that compiled
before; this module removes it for the *fleet*: the serialized
``jax.export`` artifacts for a model's compiled surface (the pow2
admit-width forward ladder, the first-token program, the score programs)
are bundled into one deterministic tar and attached to the model version
as a real manifest descriptor with its own mediaType
(``application/vnd.modelx.program.v1``) — NOT an annotation — so sha256
verification, scrub/quarantine, upload markers and GC referenced-digest
tracking apply to program bytes exactly as they do to weight bytes.

Flow: the first pod to compile publishes (``--publish-programs`` /
``modelx programs push``); every later pod's pull brings the bundle
through the blob cache and ``install_bundle`` drops the artifacts into
the local AOT cache *before* the first compile, so
``aot_cache.load_or_compile`` warm-starts. The store is an optimization,
never load-bearing: any miss, version skew, truncation or corruption is
logged and the caller proceeds to the plain trace+lower+compile path —
a registry wiped of program blobs behaves exactly like today.

A bundle carries two member kinds, both required for a truly warm boot:
the ``jax.export`` artifacts (``aot-<hex>.bin`` — skip trace+lower) and
the persistent-XLA-cache executables those exports compile into
(``jit_call-<hex>-cache`` — skip the backend compile; ``jit_call`` is
the module name every aot_cache compile carries, so the engine's donated
decode programs, which compile under their own names and are
deliberately node-local, never ship). XLA entries are content-addressed
by jax itself — an entry built for a different backend/topology/flag set
has a key the puller never computes, so at worst it sits unused.

Trust boundary: member names inside a bundle must look like AOT cache
entries or XLA executables (the two regexes below) and every member is
re-hashed against the bundle's own meta.json before it touches the cache
dir — a tampered or truncated bundle installs nothing. The bundle is
keyed by environment (jax version, backend, package-source digest):
programs exported by different code never deserialize here, they are
skipped wholesale.
"""

from __future__ import annotations

import glob
import hashlib
import io
import json
import logging
import os
import re
import tarfile

from modelx_tpu.types import (
    AnnotationProgramBackend,
    AnnotationProgramCode,
    AnnotationProgramCount,
    AnnotationProgramJax,
    AnnotationProgramMesh,
    Descriptor,
    Digest,
    Manifest,
    MediaTypeModelProgram,
)

logger = logging.getLogger("modelx.programs")

BUNDLE_FORMAT = 1
META_MEMBER = "meta.json"
# the only shapes of member name a bundle may carry: an AOT cache entry
# (serialized jax.export) or the persistent-XLA-cache executable such an
# export compiles into. Anything else (paths, traversal, stray files,
# jax's -atime bookkeeping companions) is rejected at install.
_ARTIFACT_RE = re.compile(r"^aot-[0-9a-f]{8,64}\.bin$")
_XLA_RE = re.compile(r"^jit_call-[0-9a-f]{64}-cache$")


def _member_name_ok(name: str) -> bool:
    return bool(_ARTIFACT_RE.match(name) or _XLA_RE.match(name))


def _mesh_str(mesh=None) -> str:
    """Normalize a mesh argument to the canonical ``"dp=2,tp=4"`` string.
    ``None`` derives the default serving topology (dp over all local
    devices — the same default ModelServer and plan_from_manifest use), a
    live Mesh renders its shape, a string passes through."""
    if isinstance(mesh, str):
        return mesh
    if mesh is not None and getattr(mesh, "shape", None) is not None:
        from modelx_tpu.parallel.mesh import mesh_str

        return mesh_str(mesh)
    import jax

    return f"dp={len(jax.devices())}"


def _env(mesh=None) -> tuple[str, str, str, str]:
    import jax

    from modelx_tpu.dl import aot_cache

    return (jax.__version__, jax.default_backend(), aot_cache.code_version(),
            _mesh_str(mesh))


def env_key(mesh=None) -> str:
    """Digest of (jax version, backend, package-source digest, mesh shape)
    — the bundle compatibility domain. Mesh is load-bearing: exported
    programs bake their GSPMD partitioning in, so a dp=1 surface must
    never warm-install (and mis-warm) a tp=4 pod. One bundle per
    environment coexists in a manifest; republishing from the same
    environment replaces it."""
    jx, backend, code, mesh_s = _env(mesh)
    h = hashlib.sha256(f"{jx}\x00{backend}\x00{code}\x00{mesh_s}".encode())
    return h.hexdigest()[:12]


def bundle_name(mesh=None) -> str:
    """Dotfile on purpose: push.parse_manifest_from_dir skips dotfiles, so
    a model dir holding a pulled bundle re-pushes cleanly — programs only
    ever attach to a manifest through :func:`publish`."""
    return f".programs-{env_key(mesh)}.tar"


# --- bundle build -------------------------------------------------------------


def build_bundle(cache_dir: str, keys=None, mesh=None) -> bytes | None:
    """Pack serialized exports from ``cache_dir`` into a deterministic tar
    (sorted members, zeroed mtimes/owners): same artifacts => same bytes
    => same content address, so republishing an unchanged surface is a
    registry no-op. ``keys=None`` bundles every AOT entry in the dir;
    otherwise only the named cache keys (missing ones are skipped — the
    bundle describes what this node actually compiled). The dir's
    ``jit_call`` XLA executables always ride along: jax content-addresses
    them internally, so they cannot be mapped to cache keys from here,
    and an extra entry costs bytes while a missing one costs every puller
    the backend compile. Returns None when there is nothing to ship."""
    from modelx_tpu.dl import aot_cache

    if keys is None:
        paths = sorted(glob.glob(os.path.join(cache_dir, "aot-*.bin")))
    else:
        paths = sorted(
            aot_cache.artifact_path(cache_dir, k) for k in dict.fromkeys(keys)
        )
    paths += sorted(glob.glob(os.path.join(cache_dir, "jit_call-*-cache")))
    artifacts = []
    members = []
    for path in paths:
        name = os.path.basename(path)
        if not _member_name_ok(name):
            continue
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            logger.warning("program bundle: skipping unreadable %s: %s", name, e)
            continue
        artifacts.append(
            {"name": name, "sha256": hashlib.sha256(data).hexdigest(), "size": len(data)}
        )
        members.append((name, data))
    if not members:
        return None
    jx, backend, code, mesh_s = _env(mesh)
    meta = {
        "formatVersion": BUNDLE_FORMAT,
        "jax": jx,
        "backend": backend,
        "codeVersion": code,
        "mesh": mesh_s,
        "artifacts": artifacts,
    }
    meta_bytes = json.dumps(meta, sort_keys=True, separators=(",", ":")).encode()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w", format=tarfile.USTAR_FORMAT) as tar:
        for name, data in [(META_MEMBER, meta_bytes)] + members:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            info.mode = 0o644
            tar.addfile(info, io.BytesIO(data))
    return buf.getvalue()


# --- bundle install -----------------------------------------------------------


def install_bundle(data: bytes, cache_dir: str, mesh=None) -> dict:
    """Install a bundle's artifacts into the local AOT cache dir.

    Never raises: every failure mode — undecodable tar, missing/invalid
    meta, environment skew, tampered or truncated member — is logged,
    counted, and skipped, so the caller's compile path simply stays cold.
    Existing cache entries are never overwritten (the local node's own
    exports are at least as fresh as any bundle)."""
    stats = {"installed": 0, "present": 0, "skipped": 0, "reasons": []}

    def _skip(reason: str, n: int = 1) -> dict:
        stats["skipped"] += n
        stats["reasons"].append(reason)
        logger.warning("program install: %s", reason)
        return stats

    try:
        tar = tarfile.open(fileobj=io.BytesIO(data), mode="r:")
    except (tarfile.TarError, ValueError, EOFError) as e:
        return _skip(f"unreadable bundle: {e}")
    with tar:
        try:
            member = tar.getmember(META_MEMBER)
            meta = json.loads(tar.extractfile(member).read())
        except (KeyError, tarfile.TarError, ValueError, AttributeError, OSError) as e:
            return _skip(f"bundle meta unreadable: {e}")
        if not isinstance(meta, dict) or meta.get("formatVersion") != BUNDLE_FORMAT:
            return _skip(f"unsupported bundle format {meta.get('formatVersion')!r}"
                         if isinstance(meta, dict) else "bundle meta is not an object")
        jx, backend, code, mesh_s = _env(mesh)
        got = (meta.get("jax"), meta.get("backend"), meta.get("codeVersion"))
        if got != (jx, backend, code):
            # the whole bundle is for another world: programs exported by
            # different code/framework must never deserialize here
            return _skip(
                "version skew: bundle built for jax=%s backend=%s code=%s, "
                "local jax=%s backend=%s code=%s" % (*got, jx, backend, code),
                n=len(meta.get("artifacts") or ()),
            )
        got_mesh = meta.get("mesh")
        if got_mesh is not None and got_mesh != mesh_s:
            # the exports bake their GSPMD partitioning in: a bundle
            # compiled for another mesh shape would deserialize fine and
            # then mis-warm (or fail at execute) on this topology.
            # Pre-mesh bundles carry no key and install as before.
            return _skip(
                f"mesh skew: bundle built for mesh={got_mesh}, "
                f"local mesh={mesh_s}",
                n=len(meta.get("artifacts") or ()),
            )
        os.makedirs(cache_dir, exist_ok=True)
        for art in meta.get("artifacts") or ():
            name = art.get("name", "") if isinstance(art, dict) else ""
            if not _member_name_ok(name):
                _skip(f"artifact name {name!r} rejected")
                continue
            target = os.path.join(cache_dir, name)
            if os.path.exists(target):
                stats["present"] += 1
                continue
            try:
                blob = tar.extractfile(tar.getmember(name)).read()
            except (KeyError, tarfile.TarError, AttributeError, OSError) as e:
                _skip(f"artifact {name} unreadable: {e}")
                continue
            if len(blob) != art.get("size") or hashlib.sha256(blob).hexdigest() != art.get(
                "sha256"
            ):
                _skip(f"artifact {name} fails hash/size check; not installing")
                continue
            tmp = f"{target}.tmp.{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, target)  # atomic: racing installs/compiles
            except OSError as e:
                _skip(f"artifact {name} write failed: {e}")
                try:
                    os.unlink(tmp)
                except OSError:
                    logger.debug("program install: tmp cleanup failed for %s", tmp)
                continue
            stats["installed"] += 1
    return stats


def install_from_dir(model_dir: str, cache_dir: str, mesh=None) -> dict:
    """Install every pulled program bundle found in a model dir (the
    lifecycle/boot path: pull_model drops ``.programs-*.tar`` next to the
    weights). Aggregated stats; never raises."""
    total = {"bundles": 0, "installed": 0, "present": 0, "skipped": 0, "reasons": []}
    for path in sorted(glob.glob(os.path.join(model_dir, ".programs-*.tar"))):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            total["reasons"].append(f"{os.path.basename(path)}: {e}")
            logger.warning("program install: cannot read %s: %s", path, e)
            continue
        total["bundles"] += 1
        stats = install_bundle(data, cache_dir, mesh=mesh)
        for k in ("installed", "present", "skipped"):
            total[k] += stats[k]
        total["reasons"].extend(stats["reasons"])
    return total


# --- registry plumbing --------------------------------------------------------


def program_descriptors(manifest: Manifest) -> list[Descriptor]:
    return [b for b in manifest.blobs if b.media_type == MediaTypeModelProgram]


def publish(remote, repository: str, version: str, data: bytes) -> Descriptor:
    """Attach a bundle to an existing model version as a real descriptor.

    The blob uploads first (content-addressed dedup via HEAD), then the
    manifest is re-PUT with the descriptor merged in by name — same-env
    republish replaces, other-env bundles coexist. The server's commit
    verification re-checks every referenced digest; a delta-shaped 400
    gets one blob re-upload + retry, the push.Pusher discipline."""
    from modelx_tpu import errors
    from modelx_tpu.client.push import commit_delta_digests

    meta = _bundle_meta(data)
    # name (and thereby replace-vs-coexist identity) follows the bundle's
    # OWN stamped environment: publish may run in a different process than
    # the export (modelx programs push), so never re-derive it locally
    name = bundle_name(meta.get("mesh"))
    desc = Descriptor(
        name=name,
        media_type=MediaTypeModelProgram,
        digest=Digest.from_bytes(data),
        size=len(data),
        annotations={
            AnnotationProgramJax: meta["jax"],
            AnnotationProgramBackend: meta["backend"],
            AnnotationProgramCode: meta["codeVersion"],
            AnnotationProgramMesh: meta.get("mesh") or _mesh_str(None),
            # programs, not members: the XLA executables are support acts
            AnnotationProgramCount: str(_program_count(meta)),
        },
    )
    if not remote.head_blob(repository, desc.digest):
        remote.upload_blob_content(repository, desc, data)
    manifest = remote.get_manifest(repository, version)
    manifest.blobs = [b for b in manifest.blobs if b.name != name] + [desc]
    try:
        remote.put_manifest(repository, version, manifest)
    except errors.ErrorInfo as e:
        if str(desc.digest) not in commit_delta_digests(e):
            raise
        # our blob lost a race (GC sweep / quarantine between upload and
        # commit): re-push it and commit once more
        remote.upload_blob_content(repository, desc, data)
        remote.put_manifest(repository, version, manifest)
    return desc


def _bundle_meta(data: bytes) -> dict:
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:") as tar:
        meta = json.loads(tar.extractfile(tar.getmember(META_MEMBER)).read())
    if not isinstance(meta, dict) or not isinstance(meta.get("artifacts"), list):
        raise ValueError("program bundle meta.json is not a bundle manifest")
    return meta


def _program_count(meta: dict) -> int:
    """Exported programs in a bundle meta (XLA executable members not
    counted — one program may or may not carry one, and "how many compiled
    surfaces warm-start" is the number every caller reports)."""
    return sum(
        1 for a in meta.get("artifacts") or ()
        if isinstance(a, dict) and _ARTIFACT_RE.match(a.get("name", ""))
    )


def bundle_program_count(data: bytes) -> int:
    return _program_count(_bundle_meta(data))


def pull_and_install(client, repository: str, manifest: Manifest,
                     cache_dir: str, cache=None, mesh=None) -> dict:
    """Fetch the manifest's program bundles (blob cache first — re-swaps
    are disk-warm) and install them into the local AOT cache. Corrupt
    bytes (digest mismatch) are logged and skipped, never installed;
    transport errors likewise — the caller's compile path just stays
    cold. Never raises."""
    total = {"bundles": 0, "installed": 0, "present": 0, "skipped": 0, "reasons": []}
    env = _env(mesh)
    for desc in program_descriptors(manifest):
        # a bundle stamped for another environment is skew by construction;
        # don't spend bytes on it (install_bundle re-checks via meta.json
        # anyway, for bundles with absent/wrong annotations)
        code = desc.annotations.get(AnnotationProgramCode)
        if code is not None and code != env[2]:
            total["skipped"] += 1
            total["reasons"].append(f"{desc.name}: version skew (annotation)")
            continue
        bundle_mesh = desc.annotations.get(AnnotationProgramMesh)
        if bundle_mesh is not None and bundle_mesh != env[3]:
            total["skipped"] += 1
            total["reasons"].append(f"{desc.name}: mesh skew (annotation)")
            continue
        try:
            data = _read_blob(client, repository, desc, cache=cache)
        except Exception as e:
            total["reasons"].append(f"{desc.name}: {e}")
            logger.warning("program pull: %s unavailable: %s", desc.name, e)
            continue
        if data is None:
            total["reasons"].append(f"{desc.name}: digest mismatch")
            continue
        total["bundles"] += 1
        stats = install_bundle(data, cache_dir, mesh=mesh)
        for k in ("installed", "present", "skipped"):
            total[k] += stats[k]
        total["reasons"].extend(stats["reasons"])
    return total


def _read_blob(client, repository: str, desc: Descriptor, cache=None) -> bytes | None:
    """Blob bytes via the local blob cache when possible, the registry
    otherwise; always digest-verified (None = corrupt). Network reads are
    admitted into the cache so the next swap is disk-warm."""
    if cache is not None and desc.digest:
        hit = cache.lookup(desc.digest, expected_size=desc.size or -1)
        if hit is not None:
            try:
                with open(hit, "rb") as f:
                    data = f.read()
                if str(Digest.from_bytes(data)) == str(desc.digest):
                    return data
                logger.warning("program pull: cached %s corrupt; refetching", desc.name)
            except OSError as e:
                logger.warning("program pull: cache read of %s failed: %s", desc.name, e)
    data = b"".join(client.remote.get_blob_content(repository, desc.digest))
    if str(Digest.from_bytes(data)) != str(desc.digest):
        logger.warning(
            "program pull: %s/%s bytes do not match their address; discarding",
            repository, desc.name,
        )
        return None
    if cache is not None and desc.digest:
        _admit(cache, str(desc.digest), data)
    return data


def _admit(cache, digest: str, data: bytes) -> None:
    import tempfile

    try:
        fd, tmp = tempfile.mkstemp(dir=cache.root, prefix=".programs-admit-")
        with os.fdopen(fd, "wb") as f:
            f.write(data)
    except OSError as e:
        logger.warning("program pull: blob-cache spool failed: %s", e)
        return
    if cache.admit_file(digest, tmp) is None:
        logger.warning("program pull: blob-cache admit refused %s", digest)


# --- compiled-surface export --------------------------------------------------


def export_surface(family, cfg, param_sds: dict, mesh, cache_dir: str,
                   widths=(1, 2, 4, 8), seq: int = 16,
                   first_token_shapes=((1, 4), (1, 16)),
                   score_shapes=((1, 16),), top_ks=(0,)) -> list[str]:
    """Compile (and thereby serialize into ``cache_dir``) the model's
    standard compiled surface from abstract params — no weights needed:
    the pow2 admit-width forward ladder (serve's batcher shapes), the
    first-token programs (the TTFT path), and the score programs. Returns
    the cache keys, in bundle order. Per-program failures are logged and
    skipped — an unexportable rung only loses its own warm start."""
    from modelx_tpu.dl import families as fam

    keys: list[str] = []

    def _one(label, key, fn):
        try:
            fn()
        except Exception as e:
            logger.warning("program export %s failed: %s", label, e)
            return
        keys.append(key)

    for w in widths:
        shape = (int(w), int(seq))
        key = fam.forward_program_key(family, cfg, "argmax_all", shape, mesh, param_sds)
        _one(f"argmax_all{shape}", key, lambda shape=shape: fam.precompile_forward(
            family, cfg, param_sds, shape, mesh=mesh, mode="argmax_all",
            cache_dir=cache_dir))
    for shape in first_token_shapes:
        key = fam.forward_program_key(family, cfg, "argmax_last", shape, mesh, param_sds)
        _one(f"argmax_last{shape}", key, lambda shape=shape: fam.precompile_forward(
            family, cfg, param_sds, shape, mesh=mesh, mode="argmax_last",
            cache_dir=cache_dir))
    for shape in score_shapes:
        for k in top_ks:
            key = fam.forward_program_key(
                family, cfg, f"score:{int(k)}", shape, mesh, param_sds
            )
            _one(f"score{shape}:{k}", key, lambda shape=shape, k=k: fam.precompile_score(
                family, cfg, param_sds, shape, top_k=int(k), mesh=mesh,
                cache_dir=cache_dir))
    return keys


def plan_from_manifest(client, repository: str, manifest: Manifest,
                       quantize: str | None = None, cache=None):
    """(family, cfg, param_sds, mesh) for a model known only by its
    manifest — the tensor-index annotations (ranged header reads as the
    fallback) fully determine the compiled surface, so ``modelx programs
    push`` can export without pulling a single weight byte."""
    import struct

    import jax

    from modelx_tpu.dl import families as fam
    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.initializer import _blob_source
    from modelx_tpu.dl.loader import fuse_expert_tensors
    from modelx_tpu.parallel.mesh import make_mesh
    from modelx_tpu.types import AnnotationTensorIndex

    infos: dict = {}
    for blob in manifest.blobs:
        if not blob.name.endswith(".safetensors"):
            continue
        if AnnotationTensorIndex in blob.annotations:
            parsed, _off = st.parse_index_annotation(blob.annotations[AnnotationTensorIndex])
        else:
            source = _blob_source(client, repository, blob, cache=cache)
            try:
                (hlen,) = struct.unpack("<Q", bytes(source.read_range(0, 8)))
                parsed = st.parse_header(bytes(source.read_range(8, hlen)))
            finally:
                if hasattr(source, "close"):
                    source.close()
        infos.update(parsed)
    if not infos:
        raise ValueError(f"{repository}: manifest has no safetensors blobs")
    family = fam.detect(list(infos))
    infos = fuse_expert_tensors(infos, family.rules)
    cfg = family.infer_config(fam.abstract_params(infos))
    # a checkpoint that pins its serving topology (modelx.shard.mesh) gets
    # its programs exported for THAT mesh — the shape a puller will serve
    # under; otherwise the local default (dp over all devices)
    from modelx_tpu.types import AnnotationShardMesh

    mesh = None
    pinned = manifest.annotations.get(AnnotationShardMesh, "")
    if pinned:
        try:
            mesh = make_mesh(pinned)
        except ValueError as e:
            logger.warning(
                "manifest pins mesh %r but it does not fit this host (%s); "
                "exporting for the local default mesh instead", pinned, e)
    if mesh is None:
        mesh = make_mesh(f"dp={len(jax.devices())}")
    sds = fam.abstract_params(infos, family.rules, mesh, quantize=quantize)
    return family, cfg, sds, mesh


def bundle_for_server(ref: str, server, cache_dir: str) -> bytes | None:
    """The LOCAL half of a server publish (PR 19 split): bundle the
    surface keys this server's shapes map to (only those its AOT cache
    actually holds) for the model version it was loaded from. No network
    — the bytes can be published now or spooled to the outbox
    (dl/outbox.py) for a drainer to push after a registry outage.
    Returns None when there is nothing to publish or the ref names no
    version."""
    from modelx_tpu.client.reference import parse_reference
    from modelx_tpu.dl import families as fam

    sds = getattr(server, "_param_sds", None)
    if not cache_dir or sds is None or server.family is None:
        return None
    parsed = parse_reference(ref)
    if not parsed.version:
        # a bare ref resolves "latest" on GET, but publishing must pin the
        # exact version whose surface this is — refuse rather than mint a
        # literal "latest" version in the registry
        logger.warning("programs publish skipped: %s names no version", ref)
        return None
    keys = [
        fam.forward_program_key(server.family, server.cfg, "argmax_all",
                                shape, server.mesh, sds)
        for shape in server.WARMUP_TOKEN_SHAPES
    ]
    for (lb, bb, top_k) in list(server._score_progs):
        keys.append(fam.forward_program_key(
            server.family, server.cfg, f"score:{int(top_k)}", (bb, lb),
            server.mesh, sds,
        ))
    from modelx_tpu.dl import aot_cache

    keys = [k for k in keys if os.path.isfile(aot_cache.artifact_path(cache_dir, k))]
    return build_bundle(cache_dir, keys=keys, mesh=server.mesh)


def publish_bundle(ref: str, data: bytes) -> Descriptor:
    """The NETWORK half of a server publish: attach pre-built bundle
    bytes to the version ``ref`` names. This is what the outbox drainer
    replays after a registry outage — the bundle carries its own stamped
    environment, so publishing later (or from another process) is
    identical to publishing now."""
    from modelx_tpu.client.reference import parse_reference

    parsed = parse_reference(ref)
    client = parsed.client(quiet=True)
    desc = publish(client.remote, parsed.repository, parsed.version, data)
    logger.info("published compiled programs for %s (%s, %d bytes)",
                ref, desc.name, desc.size)
    return desc


def publish_for_server(ref: str, server, cache_dir: str) -> Descriptor | None:
    """Best-effort publish of a freshly loaded server's compiled surface —
    the ``--publish-programs`` hook dl/lifecycle.py runs after mark_ready
    (directly, or via the outbox when one is attached). Returns the
    descriptor, or None when there is nothing to publish."""
    data = bundle_for_server(ref, server, cache_dir)
    if data is None:
        return None
    return publish_bundle(ref, data)
