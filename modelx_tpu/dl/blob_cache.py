"""Content-addressed local blob cache: the middle tier of the checkpoint
loading hierarchy (registry/object store -> local disk -> host staging ->
HBM), the ServerlessLLM design point (arxiv 2401.14351): a re-deploy of a
model the pod has already served must not pay the network again.

Placement: between ``ByteSource`` and the loader (dl/initializer._blob_source
is the seam). Cold loads wrap their network source in ``CachingByteSource``,
which tees every ranged read into a sparse spool file; when the read set
covers the blob (the loader's fetch plan reads each tensor's bytes exactly
once — see tests/test_loader.py TestByteAccounting2DMesh), the spool is
digest-verified and admitted. Warm loads find the blob by digest and serve
it via ``LocalFileSource`` preads — zero network reads, and the loader's
local fast path (native pread, page cache) applies.

Entries are keyed by the manifest blob digest (``algorithm:hex``), so the
cache is content-addressed: a re-pushed version with identical bytes hits,
a changed blob misses. Verification happens on BOTH ends — on admit (a
corrupted transfer never enters the cache) and on hit (a corrupted entry is
evicted and the caller falls back to the network), so the cache can never
serve bytes the registry didn't sign off on.

Eviction is size-capped LRU over entry mtimes (hits touch the file), run at
admit time; ``max_bytes == 0`` means unbounded.
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import threading

logger = logging.getLogger("modelx.dl")

# how much of a blob may be missing after a load and still be backfilled
# from the network at finalize time (the safetensors header + alignment
# padding are never part of a tensor fetch plan when the manifest carries
# the tensor index, so a healthy cold load leaves a few KB of gaps)
BACKFILL_MAX_FRACTION = 0.05
BACKFILL_MAX_BYTES = 4 << 20

_ENV_DIR = "MODELX_BLOB_CACHE_DIR"
_ENV_MAX = "MODELX_BLOB_CACHE_MAX_BYTES"

_tmp_counter = itertools.count()


def _hasher_for(digest: str):
    algo = digest.partition(":")[0]
    try:
        return hashlib.new(algo)
    except (ValueError, TypeError):
        return None


def _file_digest_hex(path: str, digest: str) -> str | None:
    h = _hasher_for(digest)
    if h is None:
        return None
    with open(path, "rb") as f:
        while chunk := f.read(4 << 20):
            h.update(chunk)
    return h.hexdigest()


class BlobCache:
    """Directory of digest-named blob files with size-capped LRU eviction.

    ``lookup`` verifies the entry's content digest before handing it out
    (a warm load reads the file anyway; one extra page-cache pass buys
    never serving corrupted weights) — pass ``verify_on_hit=False`` to
    trade that for a size-only check on trusted local disks.
    """

    def __init__(self, root: str, max_bytes: int = 0, verify_on_hit: bool = True) -> None:
        self.root = root
        self.max_bytes = max(0, int(max_bytes))
        self.verify_on_hit = verify_on_hit
        self._lock = threading.Lock()
        self.stats: dict = {
            "hits": 0, "misses": 0, "admitted": 0, "evicted": 0,
            "corrupt_rejected": 0, "admit_rejected": 0,
            # the subset of corrupt_rejected where the hit-path DIGEST
            # re-check failed on a size-plausible resident entry: a
            # rising value means the cache volume itself is rotting
            # (bit flips / torn writes), not just truncated spools
            "cache_corrupt_evictions": 0,
        }
        os.makedirs(root, exist_ok=True)
        self._sweep_stale_spools()

    def _sweep_stale_spools(self) -> None:
        """Delete spool files left by DEAD processes (a pod OOM-killed mid
        cold load never runs CachingByteSource.close). Spool names embed
        the writer's pid; a live pid's spool is left alone. Untracked
        spools would otherwise sit invisible to the LRU cap and fill the
        cache volume across crash loops."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            if ".tmp-" not in name:
                continue
            try:
                pid = int(name.split(".tmp-", 1)[1].split("-", 1)[0])
                os.kill(pid, 0)  # existence probe, no signal delivered
            except (ValueError, IndexError, PermissionError):
                continue  # unparseable, or pid alive under another uid
            except ProcessLookupError:
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass

    def entry_path(self, digest: str) -> str:
        algo, _, hexv = str(digest).partition(":")
        return os.path.join(self.root, f"{algo}-{hexv}.blob")

    def lookup(self, digest: str, expected_size: int = -1) -> str | None:
        """Path of a verified cached blob, or None (miss / corrupt entry —
        corrupt entries are deleted so the network fallback repairs them)."""
        if _hasher_for(digest) is None:
            return None
        path = self.entry_path(digest)
        try:
            size = os.path.getsize(path)
        except OSError:
            with self._lock:
                self.stats["misses"] += 1
            return None
        ok = expected_size < 0 or size == expected_size
        digest_bad = False
        if ok and self.verify_on_hit:
            digest_bad = (
                _file_digest_hex(path, digest) != str(digest).partition(":")[2]
            )
            ok = not digest_bad
        if not ok:
            logger.warning("blob cache entry %s failed verification; evicting", path)
            try:
                os.unlink(path)
            except OSError:
                pass
            with self._lock:
                self.stats["corrupt_rejected"] += 1
                if digest_bad:
                    self.stats["cache_corrupt_evictions"] += 1
            # returning None routes the caller back to the network: the
            # next successful fetch re-admits a clean copy
            return None
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        with self._lock:
            self.stats["hits"] += 1
        return path

    def wrap(self, source, digest: str, size: int):
        """Tee ``source``'s ranged reads toward admission. Returns the
        source unchanged when the blob can't be cached (no usable digest
        or unknown size)."""
        if size is None or size <= 0 or _hasher_for(digest) is None:
            return source
        return CachingByteSource(source, self, digest, size)

    def admit_file(self, digest: str, tmp_path: str) -> str | None:
        """Verify + atomically install a fully-spooled blob; evicts LRU
        entries first so the cache lands under ``max_bytes``. A blob larger
        than the whole cap is refused outright — evicting everything to
        install an over-cap entry would leave the cache permanently over
        budget. (In-flight spools are NOT counted against the cap; size the
        volume with one blob of transient headroom per concurrent cold
        load.) The temp file is consumed either way."""
        try:
            size = os.path.getsize(tmp_path)
            if self.max_bytes and size > self.max_bytes:
                logger.warning(
                    "blob %s (%d bytes) exceeds the cache cap (%d); not admitting",
                    digest, size, self.max_bytes,
                )
                with self._lock:
                    self.stats["admit_rejected"] += 1
                os.unlink(tmp_path)
                return None
            if _file_digest_hex(tmp_path, digest) != str(digest).partition(":")[2]:
                logger.warning(
                    "blob %s spool failed digest verification; not admitting", digest
                )
                with self._lock:
                    self.stats["admit_rejected"] += 1
                os.unlink(tmp_path)
                return None
            final = self.entry_path(digest)
            # plan + perform eviction and the final rename OUTSIDE the
            # lock (lint: blocking-under-lock): the replace is atomic at
            # the FS level and entries are content-addressed, so a racing
            # admit of the same digest lands identical bytes; two racing
            # admits of different digests can transiently overshoot the
            # cap by one blob until the next admit's sweep — the cap is a
            # budget, not an invariant. The lock now guards only stats.
            evicted = self._evict_for(size, keep=final)
            os.replace(tmp_path, final)
            with self._lock:
                self.stats["evicted"] += evicted
                self.stats["admitted"] += 1
            return final
        except OSError:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            return None

    def total_bytes(self) -> int:
        total = 0
        for name in self._entries():
            try:
                total += os.path.getsize(os.path.join(self.root, name))
            except OSError:
                pass
        return total

    def _entries(self) -> list[str]:
        try:
            return [n for n in os.listdir(self.root) if n.endswith(".blob")]
        except OSError:
            return []

    def _evict_for(self, incoming: int, keep: str = "") -> int:
        """LRU-evict (oldest mtime first) until incoming fits under the
        cap; returns the eviction count. Runs WITHOUT the lock — unlink
        is idempotent under races and the stats update happens in the
        caller's locked section."""
        if not self.max_bytes:
            return 0
        entries = []
        for name in self._entries():
            path = os.path.join(self.root, name)
            if path == keep:
                continue
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path))
        entries.sort()
        total = sum(size for _m, size, _p in entries)
        evicted = 0
        while entries and total + incoming > self.max_bytes:
            _mtime, size, path = entries.pop(0)
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            evicted += 1
        return evicted


class CachingByteSource:
    """Wraps a network ``ByteSource``; every ranged read is teed (pwrite)
    into a size-preallocated spool file. ``close()`` finalizes: small gaps
    (header/padding the fetch plan never touches) are backfilled from the
    network, then the spool is digest-verified and admitted to the cache.
    A load that fetched only a shard subset (multi-host) or died mid-way
    leaves gaps above the backfill bound and the spool is discarded —
    admission is all-or-nothing, the cache never holds partial blobs."""

    cache_state = "cold"

    def __init__(self, source, cache: BlobCache, digest: str, size: int) -> None:
        self.source = source
        self.cache = cache
        self.digest = str(digest)
        self._size = int(size)
        self.network_reads = 0
        self.network_bytes = 0
        self._lock = threading.Lock()
        self._spans: list[tuple[int, int]] = []  # merged, sorted coverage
        self._tmp = self.cache.entry_path(digest) + f".tmp-{os.getpid()}-{next(_tmp_counter)}"
        self._fd = os.open(self._tmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o644)
        os.ftruncate(self._fd, self._size)
        self._closed = False
        self._dead = False  # tee failed (e.g. cache volume full): loads go on

    def read_range(self, offset: int, length: int, out=None):
        buf = self.source.read_range(offset, length, out)
        if not self._dead:
            try:
                os.pwrite(
                    self._fd,
                    buf[:length] if isinstance(buf, bytes) else memoryview(buf)[:length],
                    offset,
                )
            except OSError:
                # the cache is an optimization, never load-bearing: a full
                # or unwritable cache volume must not fail the deploy —
                # stop teeing, serve the bytes, discard the spool at close
                self._dead = True
                logger.warning(
                    "blob cache spool write failed for %s; continuing uncached",
                    self.digest, exc_info=True,
                )
            else:
                with self._lock:
                    self._add_span(offset, offset + length)
        with self._lock:
            self.network_reads += 1
            self.network_bytes += length
        return buf

    def size(self) -> int:
        return self._size

    def _add_span(self, start: int, end: int) -> None:
        """Insert + merge (the fetch plan's reads rarely touch, so the list
        stays short). Caller holds the lock."""
        spans = self._spans
        spans.append((start, end))
        spans.sort()
        merged = [spans[0]]
        for s, e in spans[1:]:
            ls, le = merged[-1]
            if s <= le:
                merged[-1] = (ls, max(le, e))
            else:
                merged.append((s, e))
        self._spans = merged

    def _gaps(self) -> list[tuple[int, int]]:
        gaps, pos = [], 0
        for s, e in self._spans:
            if s > pos:
                gaps.append((pos, s))
            pos = max(pos, e)
        if pos < self._size:
            gaps.append((pos, self._size))
        return gaps

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            gaps = self._gaps() if not self._dead else [(0, self._size)]
            missing = sum(e - s for s, e in gaps)
            budget = max(BACKFILL_MAX_BYTES, int(BACKFILL_MAX_FRACTION * self._size))
            # backfill only a LOAD's leftovers (header/padding): requiring
            # majority coverage keeps a header-only probe of a small blob
            # from turning its close() into a full synchronous download
            if missing and missing <= budget and missing < self._size - missing:
                for s, e in gaps:
                    data = self.source.read_range(s, e - s)
                    os.pwrite(self._fd, memoryview(data)[: e - s] if not isinstance(data, bytes) else data, s)
                missing = 0
            os.close(self._fd)
            self._fd = -1
            if missing == 0:
                self.cache.admit_file(self.digest, self._tmp)
            else:
                os.unlink(self._tmp)
        except OSError:
            logger.warning("blob cache spool for %s abandoned", self.digest, exc_info=True)
            try:
                if self._fd >= 0:
                    os.close(self._fd)
                os.unlink(self._tmp)
            except OSError:
                pass
        finally:
            if hasattr(self.source, "close"):
                self.source.close()


# -- process-default cache ----------------------------------------------------
#
# Deploy surfaces (modelx-serve, modelx dl, dl/ttft) configure one cache per
# process; the env vars let subprocess harnesses (bench legs, TTFT children)
# inherit it without threading a path through every argv.

_default: "BlobCache | None" = None
_default_set = False
_default_lock = threading.Lock()


def configure_default(root: str, max_bytes: int = 0) -> "BlobCache | None":
    """Install (or, with an empty root, disable) the process-default cache."""
    global _default, _default_set
    with _default_lock:
        _default = BlobCache(root, max_bytes=max_bytes) if root else None
        _default_set = True
        return _default


def default_cache() -> "BlobCache | None":
    """The configured process default, else one built from
    ``MODELX_BLOB_CACHE_DIR`` / ``MODELX_BLOB_CACHE_MAX_BYTES``, else None."""
    global _default, _default_set
    with _default_lock:
        if _default_set:
            return _default
        root = os.environ.get(_ENV_DIR, "")
        if root:
            try:
                _default = BlobCache(root, max_bytes=int(os.environ.get(_ENV_MAX, "0") or 0))
            except (OSError, ValueError):
                _default = None
            _default_set = True
        return _default
