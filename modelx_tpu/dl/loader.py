"""The registry -> TPU HBM loader (the BASELINE metric lives here).

Pipeline: tensor index (from the ``modelx.tensor.index`` manifest annotation
or the safetensors header) -> per-tensor shard plan against the target
`Mesh` + partition rules -> parallel ranged reads (HTTP Range against the
registry/presigned URL, or local pread) -> `jax.Array` assembly via
`jax.make_array_from_single_device_arrays`, so each device shard is built
from exactly the bytes it needs and host->device copies overlap the fetches.

Fetch planning:

- tensors sharded on their leading axis (the common case for the big
  matmul weights) fetch **only each shard's rows** — a host never pulls
  bytes for devices it doesn't own (SURVEY.md §7 'aligning blob byte-ranges
  with shard slices so each host fetches exactly its bytes once');
- tensors sharded on inner axes or replicated fetch once per host and are
  sliced in memory (an inner-axis shard is byte-strided; one contiguous read
  beats thousands of tiny ranged reads).

Reference parity: this replaces cmd/modelxdl's "download files into a pod
volume, let a GPU container mmap them" with "bytes land in HBM, laid out for
GSPMD" (BASELINE.json north_star).

Tiering (docs/loading.md): fetched bytes stage through a reusable host
buffer pool (_StagingPool) whose bounded occupancy double-buffers the
fetch of shard k+1 against the device_put of shard k (_OverlapClock
reports the achieved overlap), and a content-addressed local blob cache
(dl/blob_cache.py, wired at the dl/initializer._blob_source seam) makes a
warm re-deploy of an already-served blob entirely network-free.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import re
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Protocol

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from modelx_tpu.dl import safetensors as st
from modelx_tpu.dl.sharding import Rules, sharding_for

DEFAULT_FETCH_CONCURRENCY = 0  # 0 = auto (auto_fetch_concurrency)
FETCH_RETRIES = 3  # per-shard retry budget (SURVEY §5: loader retries per shard)
# Shards below this ride a BATCHED jax.device_put (one dispatch for a whole
# list of arrays) instead of one dispatch each. Measured on a tunneled v5e,
# 56 small tensors cost 97 ms as 8-wide per-tensor puts vs 36 ms as one
# list put — deploy TTFT for small models is dispatch-latency-bound. Unlike
# the earlier packed-uint8 + on-device-unpack design (dropped: its unpack
# program cost a ~2 s compile per fresh process and hung some relays),
# a list device_put involves no program at all, so it is on by default.
DEFAULT_PACK_THRESHOLD = 1 << 20
PACK_CHUNK = 64 << 20  # bytes of small tensors batched per device_put call
# host bytes allowed to sit in the fetch->transfer queue (see _ByteBudget)
DEFAULT_TRANSFER_BUDGET = 1 << 30
# reads at least this big stage into the reusable host buffer pool
# (_StagingPool) instead of allocating fresh — below it the allocator is
# cheaper than the bookkeeping, and the packed-transfer path (which parks
# its arrays until load end) stays out of the pool by construction
DEFAULT_STAGING_MIN = 1 << 20
# remote ranged reads above this split into governor-gated subranges on
# parallel connections (HTTPSource keeps one connection per thread), so a
# lone multi-GB tensor can use the whole fetch width instead of one stream
DEFAULT_SPLIT_READ = 64 << 20


class _StagingPool:
    """Reusable host staging buffers for fetched shard bytes (the
    ServerlessLLM pinned-pool idea, arxiv 2401.14351): every shard read
    used to allocate a fresh numpy buffer, so a multi-hundred-shard load
    churned the allocator at GB/s. Buffers live in power-of-two size
    classes, and at most ``max_outstanding`` are out at once — an acquire
    past the cap BLOCKS until a transfer returns one, which is the
    double-buffering gate: fetch k+1 proceeds exactly while the puts of
    earlier shards drain, and allocation count tracks CONCURRENCY (fetch
    width + transfer width), not shard count. Freelists are bounded —
    overflow buffers fall to the GC rather than pinning peak-burst
    memory. Every acquired buffer MUST be released on every path, or the
    cap starves the remaining fetch workers."""

    MAX_FREE_PER_CLASS = 8

    def __init__(self, max_outstanding: int = 0) -> None:
        self._cv = threading.Condition()
        self._free: dict[int, list[np.ndarray]] = {}
        self._out = 0
        self.max_outstanding = int(max_outstanding)
        self.allocs = 0
        self.reuses = 0

    def acquire(self, nbytes: int) -> np.ndarray:
        cls = 1 << max(nbytes - 1, 0).bit_length()
        with self._cv:
            while True:
                free = self._free.get(cls)
                if free:
                    base = free.pop()
                    self.reuses += 1
                    break
                if not self.max_outstanding or self._out < self.max_outstanding:
                    base = None
                    self.allocs += 1
                    break
                self._cv.wait()
            self._out += 1
        if base is None:
            base = np.empty(cls, np.uint8)
        return base[:nbytes]

    def release(self, view: np.ndarray) -> None:
        base = view.base if view.base is not None else view
        if not isinstance(base, np.ndarray) or base.dtype != np.uint8:
            return
        cls = base.nbytes
        if cls & (cls - 1):  # not a pool buffer
            return
        with self._cv:
            self._out -= 1
            free = self._free.setdefault(cls, [])
            if len(free) < self.MAX_FREE_PER_CLASS:
                free.append(base)
            self._cv.notify_all()

    def forfeit(self, view: np.ndarray) -> None:
        """Give up a buffer WITHOUT recycling it: the device array aliases
        it (PJRT CPU zero-copies 64-byte-aligned host buffers), so its
        memory now belongs to the loaded weights. Frees the outstanding
        slot so the pipeline keeps moving; the buffer itself lives as long
        as the arrays that share it."""
        with self._cv:
            self._out -= 1
            self._cv.notify_all()


def _aliases_buffer(dev_arrays, host: np.ndarray) -> bool:
    """True when any device shard's buffer lives inside ``host``'s
    allocation — the zero-copy case where recycling the host buffer would
    rewrite the 'device' bytes. Unprovable (no buffer pointer API on this
    backend) counts as aliased: correctness over reuse."""
    base = host.base if host.base is not None else host
    h0 = base.__array_interface__["data"][0]
    h1 = h0 + base.nbytes
    for arr in dev_arrays:
        try:
            for shard in arr.addressable_shards:
                if h0 <= shard.data.unsafe_buffer_pointer() < h1:
                    return True
        except Exception:
            return True
    return False


class _OverlapClock:
    """Wall-clock accounting of the fetch / device_put pipeline: how long
    each phase had work in flight, and for how long BOTH did (the overlap
    the two-pool design exists to create). Entirely host-side counters —
    a load whose overlap_s ~ 0 on a big checkpoint is running its stages
    serially and has lost the pipeline."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._n = {"fetch": 0, "put": 0}
        self._last = time.monotonic()
        self.busy = {"fetch": 0.0, "put": 0.0}
        self.overlap_s = 0.0

    def _tick(self) -> None:
        now = time.monotonic()
        dt = now - self._last
        self._last = now
        if dt <= 0:
            return
        for kind, n in self._n.items():
            if n > 0:
                self.busy[kind] += dt
        if self._n["fetch"] > 0 and self._n["put"] > 0:
            self.overlap_s += dt

    def enter(self, kind: str) -> None:
        with self._lock:
            self._tick()
            self._n[kind] += 1

    def exit(self, kind: str) -> None:
        with self._lock:
            self._tick()
            self._n[kind] -= 1


class _ByteBudget:
    """Bounds the BYTES of fetched host arrays parked awaiting transfer, so
    the memory ceiling is independent of how many dispatch threads run. A
    request larger than the whole budget is admitted alone (clamped) rather
    than deadlocking."""

    def __init__(self, limit: int) -> None:
        self.limit = max(1, limit)
        self._avail = self.limit
        self._cv = threading.Condition()

    def acquire(self, n: int) -> int:
        """Returns the amount actually charged (clamped to the limit);
        callers must release exactly that — releasing the unclamped request
        would inflate the budget past its limit over time."""
        n = min(n, self.limit)
        with self._cv:
            while self._avail < n:
                self._cv.wait()
            self._avail -= n
        return n

    def release(self, n: int) -> None:
        with self._cv:
            self._avail += n
            self._cv.notify_all()


def _read_with_retry(source: "ByteSource", offset: int, length: int, out=None,
                     retries: int = FETCH_RETRIES, timer=None):
    """Ranged read with exponential backoff — a transient fetch error must
    not kill a multi-hundred-shard load (mirrors the reference's per-part
    retry x3, extension_s3.go:133-148). ``timer(nbytes, seconds)`` fires
    for the SUCCESSFUL attempt only: throughput consumers (the fetch
    governor) must see transfer time, not backoff sleeps or failed I/O."""
    for attempt in range(retries):
        t0 = time.monotonic()
        try:
            result = source.read_range(offset, length, out)
        except OSError:
            if attempt == retries - 1:
                raise
            time.sleep(0.2 * (2 ** attempt))
        else:
            if timer is not None:
                timer(length, time.monotonic() - t0)
            return result


def auto_fetch_concurrency(source) -> int:
    """Fetch width derived from the HOST, not a constant (BENCH_r04: a
    hard-coded 16 local-file fetchers + the transfer pool thrashed a 1-core
    host to 25 MB/s aggregate — 6.5x WORSE than one sequential stream).

    Local files: pread from page cache is memcpy-bound, so width beyond a
    couple of threads per core only adds scheduler churn; 2/core, max 8.
    HTTP: threads block on sockets (native path holds no GIL), so width
    buys round-trip overlap — 4/core in [8, 16]."""
    cpu = os.cpu_count() or 1
    if isinstance(getattr(source, "_source", source), LocalFileSource):
        return max(2, min(8, 2 * cpu))
    return max(8, min(16, 4 * cpu))


class _FetchGovernor:
    """Admission gate for fetch reads that HALVES its width when measured
    per-thread throughput collapses (the r4 failure signature: local reads
    at ~1.5 MB/s per thread while the same file streams at 1+ GB/s) and —
    new for the cache-tier loader — GROWS it while per-thread throughput
    shows headroom (``growth_bps``), up to ``max_width``. The r5 capture
    sat at width 2 with the link 56% idle; growth is what lets the width
    recover above the collapse floor. Oscillation guard: after 3 backoffs
    growth disables permanently — a link that keeps punishing added width
    gets no more probes. Gating happens per READ, so width changes take
    effect mid-load without tearing down pool threads."""

    MAX_GROWTH_BACKOFFS = 3

    def __init__(self, width: int, floor_bps: float, min_width: int = 2,
                 max_width: int = 0, growth_bps: float = 0.0) -> None:
        self.width = max(1, int(width))
        self.floor_bps = float(floor_bps)
        self.min_width = min(min_width, self.width)
        self.max_width = max(self.width, int(max_width))
        self.growth_bps = float(growth_bps)
        self._cv = threading.Condition()
        self._active = 0
        self._bytes = 0
        self._busy_s = 0.0
        self.backoffs = 0  # observability: how often the governor shrank
        self.growths = 0  # ... and how often it grew

    def acquire(self) -> None:
        with self._cv:
            while self._active >= self.width:
                self._cv.wait()
            self._active += 1

    def release(self, nbytes: int, seconds: float) -> None:
        with self._cv:
            self._active -= 1
            self._bytes += nbytes
            self._busy_s += seconds
            if (self.floor_bps or self.growth_bps) and self._busy_s >= 0.25:
                # per-busy-thread-second rate: busy seconds sum across
                # threads, so this is throughput per active thread
                rate = self._bytes / self._busy_s
                if (
                    self.floor_bps
                    and rate < self.floor_bps
                    and self.width > self.min_width
                ):
                    self.width = max(self.min_width, self.width // 2)
                    self.backoffs += 1
                elif (
                    self.growth_bps
                    and rate >= self.growth_bps
                    and self.width < self.max_width
                    and self.backoffs < self.MAX_GROWTH_BACKOFFS
                ):
                    self.width = min(self.max_width, self.width * 2)
                    self.growths += 1
                # decay: recent reads dominate the next verdict
                self._bytes //= 2
                self._busy_s /= 2
            self._cv.notify_all()


class ByteSource(Protocol):
    """Anything that serves ranged reads of a safetensors blob.

    ``read_range(offset, length, out=None)``: when ``out`` (a writable
    length-sized memoryview) is given, bytes land directly in it — the
    loader passes views over numpy-owned allocations, because jax's
    host->device fast path wants aligned, array-owned buffers (device_put
    from bytearray-backed arrays measured 3.5x slower on the TPU tunnel).
    """

    def read_range(self, offset: int, length: int, out: memoryview | None = None): ...

    def size(self) -> int: ...


class LocalFileSource:
    def __init__(self, path: str) -> None:
        self.path = path
        self._size = os.path.getsize(path)
        self._fd = os.open(path, os.O_RDONLY)
        try:
            from modelx_tpu import native

            self._native = native if native.available() else None
        except ImportError:
            self._native = None

    def read_range(self, offset: int, length: int, out: memoryview | None = None):
        if out is None:
            buf = np.empty(length, np.uint8)
            out = memoryview(buf)
        else:
            buf = out
        if self._native is not None and length > 0:
            # GIL-free positional read on the open fd (modelx_io.cc mx_pread_fd)
            self._native.pread_fd(self._fd, offset, length, out)
            return buf
        n = 0
        while n < length:
            got = os.preadv(self._fd, [out[n:]], offset + n)
            if got <= 0:
                break
            n += got
        if n != length:
            raise OSError(f"short read: want {length}, got {n}")
        return buf

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        os.close(self._fd)


class HTTPSource:
    """Ranged GETs against a URL (registry blob endpoint or presigned S3).

    Built on raw ``http.client`` with ``readinto`` and one persistent
    connection per thread: the requests/urllib3 stack shuttles small chunks
    through Python, which would throttle the registry->HBM path. Colocated
    clients should prefer the registry's ``file`` location redirect
    (LocalFileSource) — direct preads beat any loopback HTTP.
    """

    def __init__(self, url: str, headers: dict[str, str] | None = None, total: int = -1) -> None:
        import urllib.parse

        self.url = url
        self.headers = headers or {}
        u = urllib.parse.urlsplit(url)
        self._scheme = u.scheme
        self._host = u.hostname or ""
        self._port = u.port or (443 if u.scheme == "https" else 80)
        self._path = u.path + (f"?{u.query}" if u.query else "")
        self._netloc = u.netloc
        self._local = threading.local()
        self._size = total
        # native engine: raw-socket ranged GETs with the GIL released for the
        # whole transfer (http only; TLS stays on the python path)
        self._use_native = u.scheme == "http"
        self._native_headers = "".join(f"{k}: {v}\r\n" for k, v in self.headers.items())

    def _conn(self):
        import http.client

        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self._scheme == "https":
                kwargs = {}
                from modelx_tpu.client.remote import insecure_default

                if insecure_default():  # CLI --insecure covers ranged loads too
                    # NB set_insecure/Client(insecure=True) is PROCESS-WIDE
                    # (documented in docs/api.md): every source built after
                    # the flag flips skips verification. Public-API context
                    # construction, not ssl's private helper.
                    import ssl

                    ctx = ssl.create_default_context()
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl.CERT_NONE
                    kwargs["context"] = ctx
                conn = http.client.HTTPSConnection(
                    self._host, self._port, timeout=300, **kwargs
                )
            else:
                conn = http.client.HTTPConnection(self._host, self._port, timeout=300)
            self._local.conn = conn
        return conn

    def _request(self, method: str, headers: dict[str, str]):
        conn = self._conn()
        try:
            conn.request(method, self._path, headers=headers)
            return conn.getresponse()
        except (OSError, __import__("http.client", fromlist=["HTTPException"]).HTTPException):
            # stale keep-alive connection: rebuild once
            conn.close()
            self._local.conn = None
            conn = self._conn()
            conn.request(method, self._path, headers=headers)
            return conn.getresponse()

    def _native_conn(self):
        """Thread-local native keep-alive connection (None once disabled)."""
        conn = getattr(self._local, "native", None)
        if conn is None:
            try:
                from modelx_tpu import native
            except ImportError:
                self._use_native = False
                return None
            if not native.available():
                self._use_native = False
                return None
            conn = native.NativeHTTPConnection(self._host, self._port)
            self._local.native = conn
        return conn

    def read_range(self, offset: int, length: int, out: memoryview | None = None):
        if self._use_native:
            try:
                conn = self._native_conn()
            except OSError:
                conn = None
                self._use_native = False
            if conn is not None:
                if out is None:
                    buf = np.empty(length, np.uint8)
                    view = memoryview(buf)
                else:
                    buf, view = out, memoryview(out)
                try:
                    status = conn.get_range(self._path, offset, length, view, self._native_headers)
                except OSError:
                    # transport/protocol trouble (e.g. server ignored Range):
                    # drop to the python path for this source
                    self._local.native = None
                    conn.close()
                    self._use_native = False
                else:
                    if status in (200, 206):
                        return buf
                    raise OSError(f"ranged read failed: HTTP {status}")
        h = dict(self.headers)
        h["Range"] = f"bytes={offset}-{offset + length - 1}"
        resp = self._request("GET", h)
        try:
            if resp.status not in (200, 206):
                body = resp.read(4096)
                raise OSError(f"ranged read failed: HTTP {resp.status}: {body[:200]!r}")
            if resp.status == 200:  # server ignored Range
                data = resp.read()
                data = data[offset : offset + length]
                if out is not None:
                    out[:] = data
                    return out
                return data
            if out is None:
                buf = np.empty(length, np.uint8)
                view = memoryview(buf)
            else:
                buf, view = out, out
            n = 0
            while n < length:
                got = resp.readinto(view[n:])
                if not got:
                    break
                n += got
            if n != length:
                raise OSError(f"ranged read short: want {length}, got {n}")
            return buf
        finally:
            # drain so the keep-alive connection stays usable
            resp.read()

    def size(self) -> int:
        if self._size < 0:
            resp = self._request("HEAD", dict(self.headers))
            resp.read()
            self._size = int(resp.headers.get("Content-Length", -1))
        return self._size


@dataclasses.dataclass
class LoadStats:
    bytes_fetched: int = 0
    bytes_to_device: int = 0
    tensors: int = 0
    fetch_seconds: float = 0.0
    total_seconds: float = 0.0
    fetch_width: int = 0  # governor's final width (== initial when healthy)
    fetch_backoffs: int = 0  # times the governor halved the width
    fetch_growths: int = 0  # times the governor doubled it (headroom)
    # pipeline accounting (_OverlapClock): wall time ranged fetches were in
    # flight vs device_put dispatches, and the window where both were —
    # overlap ~ 0 on a big load means the fetch->HBM pipeline collapsed
    device_put_seconds: float = 0.0
    overlap_seconds: float = 0.0
    # staging pool: fresh buffer allocations vs pooled reuses; allocs track
    # concurrency, not shard count (tests assert this stays bounded)
    staging_allocs: int = 0
    staging_reuses: int = 0

    @property
    def gbps(self) -> float:
        return self.bytes_to_device / max(self.total_seconds, 1e-9) / 1e9


_EXPERT_NAME = re.compile(r"^(.+\.experts)\.(\d+)\.(.+)$")


def _match_index(name: str, rules: Rules) -> int:
    """Position of the first rule matching ``name`` (len(rules) if none)."""
    for i, (pattern, _spec) in enumerate(rules):
        if re.search(pattern, name):
            return i
    return len(rules)


def fuse_expert_tensors(
    tensors: dict[str, st.TensorInfo], rules: Rules | None = None
) -> dict[str, st.TensorInfo]:
    """Fold HF per-expert tensor entries (``...experts.<i>.w1.weight``) into
    virtual stacked tensors (``...experts.w1.weight`` with shape [E, ...])
    so MoE checkpoints pushed in stock HF layout load directly onto an
    ``ep``-sharded mesh (MIXTRAL_RULES target the stacked names, and
    models/mixtral.py consumes the stacked layout). Each device still
    fetches only the expert rows it owns — the stacked tensor's shards are
    assembled from the member tensors' byte ranges.

    When ``rules`` are given, a group is fused only if the rules address the
    fused name *more specifically* than the per-expert names — so shard-spec
    annotations written against the on-disk HF names keep working untouched.
    """
    groups: dict[str, dict[int, st.TensorInfo]] = {}
    out: dict[str, st.TensorInfo] = {}
    for name, info in tensors.items():
        m = _EXPERT_NAME.match(name)
        if m:
            groups.setdefault(f"{m.group(1)}.{m.group(3)}", {})[int(m.group(2))] = info
        else:
            out[name] = info
    for key, members in groups.items():
        idxs = sorted(members)
        first = members[idxs[0]]
        uniform = idxs == list(range(len(idxs))) and all(
            m.shape == first.shape and m.dtype == first.dtype for m in members.values()
        )
        if rules is not None and uniform:
            # first-match-wins: skip fusion only when a rule addresses the
            # per-expert HF name *strictly* earlier than the fused name —
            # on a tie (e.g. catch-all rules only) fuse, the stacked layout
            # is what models/mixtral.py consumes
            uniform = _match_index(key, rules) <= _match_index(first.name, rules)
        if not uniform:  # unexpected layout (or rules target HF names): pass through
            for info in members.values():
                out[info.name] = info
            continue
        ms = [members[i] for i in idxs]
        out[key] = st.TensorInfo(
            name=key, dtype=first.dtype, shape=(len(ms), *first.shape),
            start=first.start, end=first.start + sum(m.nbytes for m in ms),
            members=ms,
        )
    return out


def _transfer_packs(pack_jobs: dict) -> dict:
    """Ship small tensors batched: per device-set, ONE ``jax.device_put``
    of a whole list per <=PACK_CHUNK of host bytes — a single dispatch
    round-trip covers the lot, with no on-device unpack program (each list
    element arrives as its own typed array). Returns
    {(tensor name, group index): [(device, shard), ...]}."""
    out: dict[tuple, list] = {}
    for items in pack_jobs.values():
        chunks, cur, cur_bytes = [], [], 0
        for item in items:
            nb = item[2].nbytes
            if cur and cur_bytes + nb > PACK_CHUNK:
                chunks.append(cur)
                cur, cur_bytes = [], 0
            cur.append(item)
            cur_bytes += nb
        if cur:
            chunks.append(cur)
        for chunk in chunks:
            arrs = [np.ascontiguousarray(arr) for _n, _gi, arr, _g in chunk]
            devices = [dev for dev, _idx in chunk[0][3]]
            for dev in devices:
                shards = jax.device_put(arrs, dev)
                for (name, gi, _arr, _group), shard in zip(chunk, shards):
                    out.setdefault((name, gi), []).append((dev, shard))
    return out


def _leading_axis_only(spec: PartitionSpec) -> bool:
    if len(spec) == 0 or spec[0] is None:
        return False
    return all(s is None for s in spec[1:])


def load_safetensors(
    source: ByteSource,
    mesh: Mesh,
    rules: Rules,
    tensors: dict[str, st.TensorInfo] | None = None,
    data_offset: int | None = None,
    concurrency: int = DEFAULT_FETCH_CONCURRENCY,
    dtype=None,
    progress: Callable[[int], None] | None = None,
    transfer_concurrency: int = 0,
    quantize: str | None = None,
    pack_threshold: int = DEFAULT_PACK_THRESHOLD,
    transfer_budget_bytes: int = DEFAULT_TRANSFER_BUDGET,
    staging_min_bytes: int = DEFAULT_STAGING_MIN,
    split_read_bytes: int = DEFAULT_SPLIT_READ,
) -> tuple[dict[str, jax.Array], LoadStats]:
    """Load every tensor of a safetensors blob onto ``mesh`` per ``rules``.

    ``tensors``/``data_offset`` come from the manifest annotation when
    available; otherwise the header is fetched with two small ranged reads.
    ``concurrency`` <= 0 (the default) derives the fetch width from the
    host and source type (auto_fetch_concurrency), and a governor halves
    the ACTIVE width mid-load if per-thread throughput collapses
    (_FetchGovernor — thrash protection for small-core hosts).
    ``dtype`` optionally casts on the host before transfer (halves PCIe bytes
    when serving bf16 from an f32 checkpoint). ``transfer_concurrency``
    bounds concurrent host->device dispatches (0 = auto: 8, or 2 per local
    device up to 16 — concurrent device_puts pipeline per-dispatch latency
    AND fill the link: on the tunneled v5e, 512 MB measured 242 MB/s with 1
    dispatch thread vs 863-976 MB/s with 8-16, ~90% of the raw link probe).
    ``transfer_budget_bytes`` caps the host bytes parked between fetch and
    transfer — the RAM ceiling no longer scales with dispatch width. (The
    whole-tensor cache for byte-strided/int8-global-scale tensors is held
    OUTSIDE the budget until load end; checkpoints dominated by such
    tensors need headroom above the budget for the cached originals.)
    ``quantize="int8"`` converts the big matmul weights to weight-only int8
    (ops/quant.py) ON THE HOST, halving host->device bytes and HBM; the
    per-output-channel scales are computed globally so sharded math stays
    exact. Quantized entries come back as ``QTensor``s.
    ``pack_threshold``: per-device shards smaller than this collect into
    batched list ``jax.device_put`` calls (one dispatch per ~PACK_CHUNK of
    small tensors, no on-device program) — per-tensor dispatch latency
    (~5-40 ms on a tunneled device) would otherwise dominate checkpoints
    with many small tensors. 0 disables (every shard dispatches alone).
    ``staging_min_bytes``: reads at least this big land in pooled, reusable
    host staging buffers (_StagingPool) instead of fresh allocations; the
    pool plus the fetch/transfer thread pair is the double-buffering that
    overlaps the fetch of shard k+1 with the device_put of shard k
    (LoadStats carries the overlap accounting). 0 disables the pool.
    ``split_read_bytes``: remote ranged reads above this split into
    parallel governor-gated subrange reads (one connection per thread), so
    a single huge tensor doesn't serialize the link. 0 disables splitting;
    local files never split (pread has no per-stream ceiling to beat).
    """
    t0 = time.monotonic()
    # env-gated chaos drills (default off): MODELX_FAULT_PLAN with a
    # "loader.read" schedule wraps the source so operators can rehearse the
    # retry/governor behavior against a real deployment on demand
    from modelx_tpu.testing import faults as _faults

    _env_plan = _faults.from_env()
    if _env_plan is not None and _env_plan.has("loader.read"):
        source = _faults.FaultyByteSource(source, _env_plan)
    if tensors is None or data_offset is None:
        head = bytes(_read_with_retry(source, 0, 8))
        import struct

        (hlen,) = struct.unpack("<Q", head)
        tensors = st.parse_header(bytes(_read_with_retry(source, 8, hlen)))
        data_offset = 8 + hlen
    tensors = fuse_expert_tensors(tensors, rules)

    if concurrency <= 0:
        concurrency = auto_fetch_concurrency(source)
    # collapse floor: local page-cache reads under ~32 MB/s PER THREAD mean
    # the threads are fighting the scheduler, not the disk (healthy is
    # 300+ MB/s; the r4 collapse was 1.5 MB/s). HTTP sources skip the
    # governor's floor — a genuinely slow remote link must not trigger a
    # width collapse that makes it slower still. Growth: remote sources may
    # double width up to 2x the auto width while per-thread throughput holds
    # above 24 MB/s (the r5 capture left 56% of the link idle at width 2);
    # local sources may regrow only back to the auto width, and only while
    # per-thread reads run at healthy page-cache rates (4x the floor).
    # unwrap a fault-injection wrapper for the policy check: injected
    # faults must not silently flip the governor to the remote profile
    is_local = isinstance(
        getattr(source, "_source", source), LocalFileSource
    )
    governor = _FetchGovernor(
        concurrency,
        floor_bps=32e6 if is_local else 0.0,
        max_width=concurrency if is_local else 2 * concurrency,
        growth_bps=128e6 if is_local else 24e6,
    )
    n_transfer = transfer_concurrency
    if n_transfer <= 0:
        n_transfer = max(8, min(16, 2 * len(mesh.local_devices)))
    clock = _OverlapClock()
    # the outstanding-buffer cap is what makes the pool a PIPELINE gate:
    # one buffer per fetch thread, one per transfer thread, plus slack so a
    # fetch never waits on an about-to-finish put
    staging_pool = _StagingPool(max_outstanding=concurrency + n_transfer + 2)

    def _gated_read(offset: int, length: int, out=None):
        """Ranged read under the governor's gate; the retry policy stays
        single-sourced in _read_with_retry, whose timer reports only the
        successful attempt — backoff sleeps and failed attempts' I/O are a
        retry story, not a width story, and must not read as a collapse
        that permanently sheds fetch parallelism."""
        sample = [0, 0.0]

        def timer(n: int, secs: float) -> None:
            sample[0], sample[1] = n, secs

        # acquire is pinned by the try/finally IMMEDIATELY (lint:
        # lock-leak): clock.enter used to sit between acquire and try, so
        # an exception there would have leaked a governor slot forever
        governor.acquire()
        try:
            clock.enter("fetch")
            try:
                return _read_with_retry(source, offset, length, out, timer=timer)
            finally:
                clock.exit("fetch")
        finally:
            governor.release(sample[0], sample[1])

    # per-blob multi-connection fetch: huge reads split into subranges run
    # on a DEDICATED executor (split tasks never submit further work, so the
    # fetch pool can block on them without starving itself); the governor
    # still gates every subrange, so total width stays under its control
    split_pool = None
    if split_read_bytes and not is_local:
        split_pool = ThreadPoolExecutor(max_workers=min(8, max(2, concurrency)))

    def _fetch_bytes(offset: int, length: int, out=None):
        if split_pool is None or length <= split_read_bytes:
            return _gated_read(offset, length, out)
        if out is None:
            buf = np.empty(length, np.uint8)
            view = memoryview(buf)
        else:
            buf = out
            view = out if isinstance(out, memoryview) else memoryview(out)
        futs = [
            split_pool.submit(
                _gated_read, offset + o, min(split_read_bytes, length - o),
                view[o : o + min(split_read_bytes, length - o)],
            )
            for o in range(0, length, split_read_bytes)
        ]
        for f in futs:
            f.result()
        return buf

    stats = LoadStats()
    lock = threading.Lock()
    results: dict[str, jax.Array] = {}

    # plan: one job per (tensor, shard-group). A shard-group is the set of
    # devices that receive identical bytes (replicas); bytes are fetched once
    # per group and device_put to each member.
    plans: dict[str, tuple[NamedSharding, list]] = {}
    for name, info in tensors.items():
        sharding = sharding_for(name, rules, mesh)
        # index per device: mapping device -> tuple of slices
        dev_indices = sharding.addressable_devices_indices_map(info.shape)
        groups: dict[tuple, list] = {}
        for dev, idx in dev_indices.items():
            key = _index_key(idx, info.shape)
            groups.setdefault(key, []).append((dev, idx))
        plans[name] = (sharding, list(groups.values()))

    if quantize not in (None, "int8"):
        raise ValueError(f"unsupported quantize mode {quantize!r}")
    if quantize:
        from modelx_tpu.ops import quant as qt

    def _quantized(name: str, info: st.TensorInfo) -> bool:
        return (
            quantize == "int8"
            and info.members is None
            and len(info.shape) == 2
            and qt.DEFAULT_ELIGIBLE.search(name) is not None
        )

    # whole-tensor fetches are deduped across shard-groups of the same tensor
    _full_cache: dict[str, bytes] = {}
    _full_lock = threading.Lock()
    # single-flight events: the get-then-fetch window would otherwise let
    # two groups of the same inner-sharded tensor BOTH miss and BOTH pull
    # the whole tensor (the exactly-once byte accounting the fetch plan
    # promises — TestByteAccounting2DMesh — raced away under load)
    _full_events: dict[str, threading.Event] = {}
    # global per-channel scales for quantized tensors on the full-fetch path
    _scale_cache: dict[str, np.ndarray] = {}

    def _cached_full_tensor(info: st.TensorInfo) -> bytes:
        while True:
            with _full_lock:
                cached = _full_cache.get(info.name)
                if cached is not None:
                    return cached
                ev = _full_events.get(info.name)
                if ev is None:
                    ev = _full_events[info.name] = threading.Event()
                    fetching = True
                else:
                    fetching = False
            if not fetching:
                ev.wait()  # the owner fills the cache (or fails; then retry)
                continue
            try:
                raw = _fetch_bytes(data_offset + info.start, info.nbytes)
                with _full_lock:
                    _full_cache[info.name] = raw
                return raw
            finally:
                # event removed BEFORE set: a waiter that finds no cache
                # entry and no event becomes the next owner (owner failed)
                with _full_lock:
                    _full_events.pop(info.name, None)
                ev.set()

    def _fetch_slice(
        info: st.TensorInfo, full_spec: tuple, pool_ok: bool = True
    ) -> tuple[np.ndarray, int, np.ndarray | None]:
        """Fetch one tensor's slice. Contiguous row blocks (inner dims full)
        are fetched with one exact ranged read; byte-strided inner-axis
        slices fetch the whole tensor once (cached) and slice in memory.
        Returns (array, bytes_read, staging): ``staging`` is the pooled host
        buffer backing the array when one was used — the caller must release
        it to the pool once the bytes are on device (or copied).
        ``pool_ok=False`` skips the pool: a caller that accumulates SEVERAL
        slices before releasing any (stacked-expert assembly) would
        hold-and-wait against the pool's bounded occupancy — the classic
        deadlock shape — so it allocates fresh instead."""
        np_dtype = info.np_dtype()
        inner_full = all(
            s.start == 0 and s.stop == dim
            for s, dim in zip(full_spec[1:], info.shape[1:])
        )
        if info.shape and inner_full:
            lead = full_spec[0]
            b0, b1 = st.row_range(info, lead.start, lead.stop)
            length = b1 - b0
            staging = None
            out = None
            if pool_ok and staging_min_bytes and length >= staging_min_bytes:
                staging = staging_pool.acquire(length)
                out = memoryview(staging)
            try:
                raw = _fetch_bytes(data_offset + b0, length, out)
            except BaseException:
                # a leaked buffer starves the pool's outstanding cap — the
                # sibling fetch workers would deadlock behind a dead load
                if staging is not None:
                    staging_pool.release(staging)
                raise
            arr = _as_np(
                staging if staging is not None else raw,
                np_dtype, (lead.stop - lead.start, *info.shape[1:]),
            )
            return arr, length, staging
        raw = _cached_full_tensor(info)
        arr = _as_np(raw, np_dtype, info.shape)
        sliced = np.ascontiguousarray(arr[full_spec]) if info.shape else arr.reshape(())
        return sliced, len(raw), None

    def fetch_group(info: st.TensorInfo, group: list):
        """Fetch one shard-group's bytes; hand the host array to the transfer
        pool. Fetches run wide (network-bound); device dispatches run
        several-wide too — each device_put pays a round-trip dispatch
        latency, so a single dispatch thread leaves the link idle between
        puts (measured 3.5-4x slower than 8-wide on the tunneled v5e for
        both a 56-tensor 48 MB model and a 40-tensor 512 MB one).
        Returns a future of [(device, on-device shard), ...]."""
        _dev0, idx0 = group[0]
        full_spec = _normalize_index(idx0, info.shape)
        # backpressure: admit the group against the byte budget BEFORE the
        # read — acquiring after the fetch would let fetch_concurrency whole
        # arrays pile up uncounted. The cost is the bytes this group will
        # materialize: its slice, or the whole tensor when a byte-strided
        # inner-axis slice forces a (cached) full fetch.
        itemsize = info.np_dtype().itemsize
        if dtype is not None:
            # a host-side upcast parks the POST-cast bytes; charge for those
            itemsize = max(itemsize, np.dtype(dtype).itemsize)
        slice_bytes = itemsize * int(
            np.prod([s.stop - s.start for s in full_spec], initial=1)
        )
        if info.members is not None:
            # stacked expert tensor: fetched per member against
            # full_spec[1:], so the full-fetch fallback triggers only when
            # the MEMBER's inner dims (full_spec[2:]) are strided — charging
            # the whole E-stacked tensor here would serialize MoE loads
            if all(s.start == 0 and s.stop == dim
                   for s, dim in zip(full_spec[2:], info.shape[2:])):
                cost = slice_bytes
            else:
                lead = full_spec[0]
                cost = max(slice_bytes, sum(
                    info.members[e].nbytes for e in range(lead.start, lead.stop)
                ))
        elif all(s.start == 0 and s.stop == dim
                 for s, dim in zip(full_spec[1:], info.shape[1:])):
            cost = slice_bytes
        else:
            # strided inner-axis slice -> whole-tensor fetch, but only the
            # group that MISSES the cache pays it; siblings arriving later
            # slice the cached bytes and must not serialize on a full charge
            with _full_lock:
                cached = info.name in _full_cache
            cost = slice_bytes if cached else max(slice_bytes, info.nbytes)
        cost = inflight.acquire(cost)  # clamped: release exactly this much
        staging = None
        try:
            tf0 = time.monotonic()
            if info.members is not None:
                # virtual stacked tensor: assemble the shard from the member
                # tensors (per-expert ranges) this group owns. pool_ok=False:
                # holding E pooled buffers at once while siblings do the
                # same would hold-and-wait against the pool's bounded
                # occupancy (np.stack copies anyway)
                lead = full_spec[0]
                parts, nread = [], 0
                for e in range(lead.start, lead.stop):
                    part, nb, _stg = _fetch_slice(
                        info.members[e], full_spec[1:], pool_ok=False
                    )
                    parts.append(part)
                    nread += nb
                arr = np.stack(parts)
            else:
                arr, nread, staging = _fetch_slice(info, full_spec)
            with lock:
                stats.bytes_fetched += nread
                stats.fetch_seconds += time.monotonic() - tf0
            scale = None
            if _quantized(info.name, info):
                inner = full_spec[1].start == 0 and full_spec[1].stop == info.shape[1]
                if inner:
                    # this group's rows are complete channels: local scales
                    # ARE the global per-channel scales — fused single-pass
                    # quantize (native when available)
                    arr, scale = qt.quantize_fused(arr)
                else:
                    # input dim sharded: scales must span the full contraction
                    # axis — compute once from the cached full tensor
                    with _full_lock:
                        scale_full = _scale_cache.get(info.name)
                    if scale_full is None:
                        full = _as_np(_cached_full_tensor(info), info.np_dtype(), info.shape)
                        scale_full = qt.channel_scales(full)
                        with _full_lock:
                            _scale_cache[info.name] = scale_full
                    scale = np.ascontiguousarray(
                        scale_full[full_spec[0].start : full_spec[0].stop]
                    )
                    arr = qt.quantize_rows(arr, scale)
            elif dtype is not None and arr.dtype != np.dtype(dtype):
                arr = arr.astype(dtype)
            if staging is not None and not np.may_share_memory(arr, staging):
                # a host-side cast/quantize copied the bytes out: the pooled
                # buffer is free for the next fetch right now, not after the
                # transfer
                staging_pool.release(staging)
                staging = None
            if progress:
                progress(arr.nbytes * len(group))
            if arr.nbytes < cost:
                # the parked array is smaller than what the fetch charged
                # (full-fetch fallback, host-side cast/quantize): give the
                # difference back so sibling groups stop waiting on bytes
                # nobody is holding
                inflight.release(cost - arr.nbytes)
                cost = arr.nbytes
            # batched transfer involves plain device_put (same dtype
            # canonicalization as the unbatched path), so ANY small
            # unquantized shard qualifies
            packable = (
                scale is None and pack_threshold and arr.nbytes < pack_threshold
            )
            if packable:
                # small shard: ride the packed transfer instead of paying a
                # per-tensor device round-trip. Budget released now: packs
                # park until every fetch settles, and the packable tail is
                # bounded by pack_threshold x tensor count, not the budget
                inflight.release(cost)
                if staging is not None:
                    # packs park until load end — copy out so the pooled
                    # buffer doesn't sit hostage under a small tensor
                    arr = arr.copy()
                    staging_pool.release(staging)
                return ("pack", arr, group)
        except BaseException:
            inflight.release(cost)
            if staging is not None:
                staging_pool.release(staging)
            raise

        def xfer():
            pooled = staging
            try:
                clock.enter("put")
                try:
                    out = [
                        (
                            dev,
                            jax.device_put(arr, dev),
                            jax.device_put(scale, dev) if scale is not None else None,
                        )
                        for dev, _ in group
                    ]
                    if pooled is not None:
                        # the transfer may still be reading the pooled host
                        # buffer asynchronously: wait before recycling it —
                        # and if the backend zero-copied (the device array
                        # ALIASES the buffer, PJRT CPU with 64-byte-aligned
                        # hosts), hand the memory over instead of recycling
                        devs = [t[1] for t in out]
                        jax.block_until_ready(devs)
                        if _aliases_buffer(devs, pooled):
                            staging_pool.forfeit(pooled)
                        else:
                            staging_pool.release(pooled)
                        pooled = None
                finally:
                    clock.exit("put")
                return out
            finally:
                inflight.release(cost)
                if pooled is not None:  # device_put raised before handoff
                    staging_pool.release(pooled)

        try:
            return transfer_pool.submit(xfer)
        except BaseException:
            # submit can refuse (pool shut down after a sibling error); give
            # the budget back or the remaining fetch workers deadlock
            inflight.release(cost)
            if staging is not None:
                staging_pool.release(staging)
            raise

    inflight = _ByteBudget(transfer_budget_bytes)
    # contexts unwind LIFO, so the cleanup stack runs first on ANY exit: a
    # failed load must not strand the split executor's idle threads in a
    # long-lived serve process (one leak per retry against a flaky registry)
    with ThreadPoolExecutor(max_workers=concurrency) as pool, ThreadPoolExecutor(
        max_workers=n_transfer
    ) as transfer_pool, contextlib.ExitStack() as _cleanup:
        if split_pool is not None:
            _cleanup.callback(split_pool.shutdown, False)
        futures = {}
        # big tensors first: their fetch+transfer dominates the critical path
        for name, info in sorted(tensors.items(), key=lambda kv: -kv[1].nbytes):
            _sharding, groups = plans[name]
            futures[name] = [pool.submit(fetch_group, info, g) for g in groups]
        # drain fetches: big tensors already stream through the transfer
        # pool; small ones collect into pack jobs keyed by device-set
        settled: dict[str, list] = {}
        pack_jobs: dict[tuple, list] = {}
        for name in futures:
            entries = []
            for gi, fut in enumerate(futures[name]):
                r = fut.result()
                if isinstance(r, tuple) and r and r[0] == "pack":
                    _tag, arr, group = r
                    key = tuple(sorted(d.id for d, _idx in group))
                    pack_jobs.setdefault(key, []).append((name, gi, arr, group))
                    entries.append(None)  # shard arrives via the pack
                else:
                    entries.append(r)
            settled[name] = entries
        packed = _transfer_packs(pack_jobs)
        for name, info in tensors.items():
            sharding, _groups = plans[name]
            shards, scale_shards = [], []
            for gi, entry in enumerate(settled[name]):
                if entry is None:
                    shards.extend(arr for _dev, arr in packed[(name, gi)])
                    continue
                for _dev, arr, sc in entry.result():
                    shards.append(arr)
                    if sc is not None:
                        scale_shards.append(sc)
            global_shape = info.shape if info.shape else ()
            if scale_shards:
                spec = sharding.spec
                scale_sharding = NamedSharding(
                    mesh, PartitionSpec(spec[0] if len(spec) else None)
                )
                results[name] = qt.QTensor(
                    jax.make_array_from_single_device_arrays(global_shape, sharding, shards),
                    jax.make_array_from_single_device_arrays(
                        (info.shape[0],), scale_sharding, scale_shards
                    ),
                )
                stats.bytes_to_device += int(np.prod(info.shape)) + info.shape[0] * 4
            else:
                target_dtype = np.dtype(dtype) if dtype is not None else info.np_dtype()
                results[name] = jax.make_array_from_single_device_arrays(
                    global_shape, sharding, shards
                )
                stats.bytes_to_device += int(np.prod(info.shape or (1,))) * target_dtype.itemsize
            stats.tensors += 1
        _full_cache.clear()
        _scale_cache.clear()

    jax.block_until_ready(results)  # QTensor entries are pytrees
    stats.total_seconds = time.monotonic() - t0
    stats.fetch_width = governor.width
    stats.fetch_backoffs = governor.backoffs
    stats.fetch_growths = governor.growths
    stats.device_put_seconds = clock.busy["put"]
    stats.overlap_seconds = clock.overlap_s
    stats.staging_allocs = staging_pool.allocs
    stats.staging_reuses = staging_pool.reuses
    from modelx_tpu.utils import trace

    trace.tracer().record({
        "path": "dl.load",
        "start_s": t0,
        "duration_s": stats.total_seconds,
        "tensors": stats.tensors,
        "bytes_fetched": stats.bytes_fetched,
        "bytes_to_device": stats.bytes_to_device,
        "fetch_thread_s": round(stats.fetch_seconds, 3),
        "overlap_s": round(stats.overlap_seconds, 3),
        "staging_allocs": stats.staging_allocs,
        "gbps": round(stats.gbps, 3),
    })
    return results, stats


def _as_np(raw, np_dtype, shape) -> np.ndarray:
    """View raw bytes (np.uint8 array or bytes) as a typed array, zero-copy."""
    if isinstance(raw, np.ndarray):
        return raw.view(np_dtype).reshape(shape)
    return np.frombuffer(raw, dtype=np_dtype).reshape(shape)


def _normalize_index(idx: tuple, shape: tuple) -> tuple:
    out = []
    for s, dim in zip(idx, shape):
        start = s.start or 0
        stop = s.stop if s.stop is not None else dim
        out.append(slice(start, stop))
    return tuple(out)


def _index_key(idx: tuple, shape: tuple) -> tuple:
    return tuple((s.start or 0, s.stop if s.stop is not None else dim) for s, dim in zip(idx, shape))
