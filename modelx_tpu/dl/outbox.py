"""Durable publish outbox: derived-artifact pushes survive registry death.

``--publish-programs`` (PR 11) attaches a freshly loaded server's compiled
surface to its model version — a write against the registry on the tail of
every runtime load. PR 19 makes the registry a soft dependency, and a
publish that blocks or fails a load during an outage would defeat that: so
publishes ENQUEUE here instead. The outbox is a bounded on-disk spool
(``{seq}.bin`` payload + ``{seq}.json`` meta, meta written last so a torn
entry is invisible); a background :class:`Drainer` replays entries through
the real publish with exponential backoff, so bundles built during a
brownout land in the registry within one backoff cycle of recovery — and
survive a pod restart in between, because the spool is just files.

A full spool DROPS the new entry (counted, logged) rather than blocking:
program bundles are an optimization (the next puller boots cold instead of
warm), and the load path must never wait on registry health.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

logger = logging.getLogger("modelx.dl")


class Outbox:
    """Bounded on-disk FIFO of pending publishes.

    One entry = ``{seq:08d}.bin`` (the payload bytes) plus
    ``{seq:08d}.json`` (kind/ref/size/enqueued_at). The meta file commits
    the entry: it is written with temp+rename AFTER the payload, so a
    crash mid-enqueue leaves only an orphan ``.bin`` that the next
    construction sweeps. Entries from a previous process generation are
    picked up as-is — that is the durability the chaos drill asserts."""

    DEFAULT_MAX_ENTRIES = 64
    DEFAULT_MAX_BYTES = 512 * 1024 * 1024

    def __init__(self, root: str, max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES) -> None:
        self.root = root
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self.stats = {"enqueued_total": 0, "drained_total": 0,
                      "drop_full_total": 0, "publish_failures_total": 0,
                      "dropped_unknown_kind_total": 0}
        self._seq = 0
        for seq, meta_path, _bin_path in self._scan():
            self._seq = max(self._seq, seq + 1)
        # sweep orphan payloads (crash between payload write and meta
        # commit) so they don't count against the byte budget forever
        metas = {seq for seq, _m, _b in self._scan()}
        for fn in os.listdir(root):
            if fn.endswith(".bin"):
                try:
                    seq = int(fn[:-4])
                except ValueError:
                    continue
                if seq not in metas:
                    try:
                        os.unlink(os.path.join(root, fn))
                    except OSError as e:
                        logger.warning("outbox orphan sweep %s: %s", fn, e)

    def _scan(self) -> list[tuple[int, str, str]]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for fn in names:
            if not fn.endswith(".json"):
                continue
            try:
                seq = int(fn[:-5])
            except ValueError:
                continue
            out.append((seq, os.path.join(self.root, fn),
                        os.path.join(self.root, fn[:-5] + ".bin")))
        out.sort()
        return out

    def depth(self) -> int:
        return len(self._scan())

    def pending_bytes(self) -> int:
        total = 0
        for _seq, _meta, bin_path in self._scan():
            try:
                total += os.path.getsize(bin_path)
            except OSError:
                pass
        return total

    def enqueue(self, kind: str, ref: str, data: bytes) -> bool:
        """Spool one publish; False (and a counted drop) when the spool
        is full or the disk refuses the write — never raises, never
        blocks on the registry."""
        # admission + seq reservation under the lock; the disk writes run
        # lock-free (meta-commits-entry keeps them atomic on their own).
        # A concurrent enqueue racing an in-flight write can overshoot the
        # byte budget by at most that one payload — bounded and benign.
        with self._lock:
            if (self.depth() >= self.max_entries
                    or self.pending_bytes() + len(data) > self.max_bytes):
                self.stats["drop_full_total"] += 1
                logger.warning("outbox full (%d entries); dropping %s publish "
                               "for %s", self.depth(), kind, ref)
                return False
            seq = self._seq
            self._seq += 1
        base = os.path.join(self.root, f"{seq:08d}")
        try:
            with open(base + ".bin.tmp", "wb") as f:
                f.write(data)
            os.replace(base + ".bin.tmp", base + ".bin")
            meta = {"kind": kind, "ref": ref, "size": len(data),
                    "enqueued_at": time.time()}
            with open(base + ".json.tmp", "w") as f:
                json.dump(meta, f)
            os.replace(base + ".json.tmp", base + ".json")
        except OSError as e:
            with self._lock:
                self.stats["drop_full_total"] += 1
            logger.warning("outbox spool write failed for %s: %s", ref, e)
            for suffix in (".bin.tmp", ".bin", ".json.tmp"):
                try:
                    os.unlink(base + suffix)
                except OSError:
                    continue  # already gone / never written
            return False
        with self._lock:
            self.stats["enqueued_total"] += 1
        return True

    def peek(self) -> tuple[int, dict, bytes] | None:
        """Oldest pending entry as (seq, meta, payload), or None."""
        for seq, meta_path, bin_path in self._scan():
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
                with open(bin_path, "rb") as f:
                    data = f.read()
            except (OSError, ValueError) as e:
                logger.warning("outbox entry %08d unreadable (%s); removing",
                               seq, e)
                self.remove(seq)
                continue
            return seq, meta, data
        return None

    def remove(self, seq: int) -> None:
        base = os.path.join(self.root, f"{seq:08d}")
        for suffix in (".json", ".bin"):
            try:
                os.unlink(base + suffix)
            except OSError:
                continue  # half-removed entries finish disappearing here

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
        out["depth"] = self.depth()
        out["pending_bytes"] = self.pending_bytes()
        return out


class Drainer:
    """Background replay of the outbox through the real publish.

    One entry at a time, oldest first; a failure keeps the entry, counts
    it, and backs off exponentially (capped), so a dead registry costs a
    bounded poll instead of a retry storm. ``kick()`` short-circuits the
    backoff — the lifecycle calls it after every enqueue, and tests call
    it after restarting the registry so the drain lands within one cycle.
    ``sleeper`` injects the wait primitive (``sleeper(event, timeout) ->
    bool``) for sleep-free tests."""

    BACKOFF_S = 0.5
    BACKOFF_CAP_S = 30.0

    # spool entries written before the meta carried a kind are compiled
    # programs by construction (the only artifact the outbox shipped then):
    # a restart over an old spool must drain them through the right
    # publisher, not drop them
    DEFAULT_KIND = "programs"

    def __init__(self, outbox: Outbox, handler=None, backoff_s: float = BACKOFF_S,
                 backoff_cap_s: float = BACKOFF_CAP_S, recorder=None,
                 sleeper=None) -> None:
        self.outbox = outbox
        self.handler = handler  # (kind, ref, data) -> None; fallback for any kind
        # per-kind dispatch (ISSUE 20): an entry routes to its kind's
        # registered publisher first, the legacy fallback second
        self.handlers: dict = {}
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.recorder = recorder  # flight recorder (or None)
        self._sleeper = sleeper or threading.Event.wait
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._failures = 0  # consecutive, resets on success
        self.last_error = ""

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="outbox-drainer")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def kick(self) -> None:
        self._wake.set()

    def register_handler(self, kind: str, fn) -> None:
        """Route spool entries of ``kind`` to ``fn(kind, ref, data)``."""
        self.handlers[kind] = fn

    def _record(self, event: str, **fields) -> None:
        rec = self.recorder
        if rec is not None:
            rec.record(event, **fields)

    def drain_once(self) -> bool:
        """Attempt the oldest entry; True when one drained. Public so
        tests (and a synchronous shutdown flush) can drive the drain
        without the thread."""
        item = self.outbox.peek()
        if item is None:
            return False
        seq, meta, data = item
        kind = meta.get("kind") or self.DEFAULT_KIND
        ref = meta.get("ref", "")
        handler = self.handlers.get(kind, self.handler)
        if handler is None:
            # an artifact kind nobody registered must not wedge the FIFO
            # behind it forever: drop it, counted and recorded
            self.outbox.remove(seq)
            with self.outbox._lock:
                self.outbox.stats["dropped_unknown_kind_total"] += 1
            self._record("outbox.dropped_unknown_kind", ref=ref, kind=kind)
            logger.warning("outbox dropping %s entry for %s: no handler "
                           "registered", kind, ref)
            return True
        try:
            handler(kind, ref, data)
        except Exception as e:
            self._failures += 1
            self.last_error = str(e)
            with self.outbox._lock:
                self.outbox.stats["publish_failures_total"] += 1
                key = f"publish_failures_{kind}_total"
                self.outbox.stats[key] = self.outbox.stats.get(key, 0) + 1
            self._record("outbox.publish_failed", ref=ref, kind=kind,
                         failures=self._failures)
            logger.warning("outbox publish of %s %s failed (attempt %d): %s",
                           kind, ref, self._failures, e)
            return False
        self.outbox.remove(seq)
        self._failures = 0
        self.last_error = ""
        with self.outbox._lock:
            self.outbox.stats["drained_total"] += 1
            key = f"drained_{kind}_total"
            self.outbox.stats[key] = self.outbox.stats.get(key, 0) + 1
        self._record("outbox.drained", ref=ref, kind=kind,
                     depth=self.outbox.depth())
        logger.info("outbox drained %s publish for %s (%d pending)",
                    kind, ref, self.outbox.depth())
        return True

    def _delay_s(self) -> float:
        if self._failures <= 0:
            return 0.0
        return min(self.backoff_s * (2 ** (self._failures - 1)),
                   self.backoff_cap_s)

    def _run(self) -> None:
        while not self._stop.is_set():
            drained = self.drain_once()
            if self._stop.is_set():
                return
            if drained and self.outbox.depth() > 0:
                continue  # keep draining a backlog at full speed
            delay = self._delay_s()
            self._wake.clear()
            # idle (empty spool, no failure): park until a kick; failed:
            # wake early on kick, else at the backoff boundary
            self._sleeper(self._wake, delay if delay > 0 else None)

    def snapshot(self) -> dict:
        out = self.outbox.snapshot()
        out["consecutive_failures"] = self._failures
        out["backoff_s"] = round(self._delay_s(), 3)
        if self.last_error:
            out["last_error"] = self.last_error
        out["running"] = self._thread is not None
        return out
