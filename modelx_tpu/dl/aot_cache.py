"""Serialized-executable cache: skip trace+lower on warm starts.

The persistent XLA compilation cache (dl/serve.enable_compile_cache) removes
the *XLA compile* from a fresh sidecar's critical path, but jax still pays
tracing + lowering in Python every process (~370 ms measured for the 48 MB
bench model on this host — 80% of the warm precompile cost, and on a
small-core host that CPU time is stolen from the concurrent weight load).
This cache stores the ``jax.export`` artifact (StableHLO, ~36 KB for the
same model) keyed by everything that shapes the program; a warm start
deserializes (~10 ms) and compiles the artifact (persistent-cache hit), so
the deploy's compile leg is ~4x cheaper on CPU.

No reference equivalent (the reference never compiles anything); this is
TTFT machinery for the BASELINE north star (p50 < 500 ms leaves no room for
retracing a model every pod start).
"""

from __future__ import annotations

import hashlib
import logging
import os

import jax
# jax < 0.6 doesn't bind the ``export`` submodule on bare ``import jax``;
# importing it explicitly makes ``jax.export.*`` resolve on every version
from jax import export as _jax_export  # noqa: F401

logger = logging.getLogger("modelx.aot")

_code_version: str | None = None  # digest of the package source, once


def _version_tag() -> str:
    """Digest of every modelx_tpu source file. NOT git metadata: a deployed
    image has no .git (and `git` in an arbitrary CWD reads some other
    repo's HEAD), yet a forward fix shipped by image upgrade must still
    miss the cache. ~0.5 MB of source hashes in milliseconds, once."""
    global _code_version
    if _code_version is None:
        import modelx_tpu

        root = os.path.dirname(os.path.abspath(modelx_tpu.__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in sorted(os.walk(root)):
            dirnames.sort()
            for name in sorted(filenames):
                if name.endswith(".py"):
                    p = os.path.join(dirpath, name)
                    h.update(os.path.relpath(p, root).encode())
                    with open(p, "rb") as f:
                        h.update(f.read())
        _code_version = h.hexdigest()[:16]
    return _code_version


def code_version() -> str:
    """Public handle on the package-source digest, for callers that stamp
    artifacts with the environment they were built in (dl/program_store.py):
    a bundle exported by different code must be rejected at install, not
    deserialize a pre-fix program."""
    return _version_tag()


def artifact_name(key: str) -> str:
    """Filename of the serialized export for ``key`` — the single naming
    convention shared by load_or_compile and the program-store bundler."""
    return f"aot-{key}.bin"


def artifact_path(cache_dir: str, key: str) -> str:
    return os.path.join(cache_dir, artifact_name(key))


def cache_key(*parts) -> str:
    """Stable digest over everything that shapes the compiled program —
    including the framework version + git commit, because the program BODY
    (family.forward) lives in this package: a forward fix must miss the
    cache, not warm-start the pre-fix StableHLO."""
    h = hashlib.sha256()
    h.update(jax.__version__.encode())
    h.update(jax.default_backend().encode())
    h.update(_version_tag().encode())
    for p in parts:
        h.update(b"\x00")
        h.update(repr(p).encode())
    return h.hexdigest()[:32]


def describe_sds(param_sds: dict) -> list:
    """Key material for a pytree of ShapeDtypeStructs (QTensor entries
    flatten to their leaves), shardings included — a changed partition rule
    or quantize mode must miss the cache, not execute stale."""
    out = []
    for path, s in jax.tree_util.tree_flatten_with_path(param_sds)[0]:
        sharding = getattr(s, "sharding", None)
        spec = getattr(sharding, "spec", None)
        out.append((jax.tree_util.keystr(path), tuple(s.shape), str(s.dtype), str(spec)))
    return out


def load_or_compile(fn, args: tuple, cache_dir: str, key: str):
    """Compile ``fn`` for abstract ``args``, reusing a serialized export.

    Warm path: deserialize the stored StableHLO and compile it (persistent
    XLA cache makes that compile cheap) — no tracing of ``fn``. Cold path:
    export ``fn`` once (one trace), compile from the exported artifact, and
    persist it. Every failure falls back to the plain trace+lower+compile —
    the cache is an optimization, never load-bearing.
    """
    path = artifact_path(cache_dir, key)
    if os.path.isfile(path):
        try:
            with open(path, "rb") as f:
                exp = jax.export.deserialize(bytearray(f.read()))
            return jax.jit(exp.call).lower(*args).compile()
        except Exception as e:
            logger.warning("aot cache read failed (%s); recompiling", e)
            try:
                os.unlink(path)
            except OSError:
                pass
    try:
        exp = jax.export.export(jax.jit(fn))(*args)
        # compile the serialize->deserialize ROUNDTRIP, not the in-memory
        # export: the roundtrip perturbs the module bytes enough to change
        # the persistent-XLA-cache key, so compiling `exp` directly would
        # file that cache's executable under a key no warm start (which
        # only ever sees deserialized artifacts) can hit — measured, the
        # warm compile then pays the full XLA compile despite a "warm"
        # cache dir. Compiling the roundtrip writes the entry the warm
        # path (and every pod installing this node's program bundle,
        # dl/program_store.py) will actually look up, and proves the
        # artifact deserializes before it is persisted or shipped.
        blob = exp.serialize()
        warm = jax.export.deserialize(bytearray(blob))
        compiled = jax.jit(warm.call).lower(*args).compile()
    except Exception as e:
        logger.warning("aot export failed (%s); plain compile", e)
        return jax.jit(fn).lower(*args).compile()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(cache_dir, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)  # atomic: concurrent warmups must not torn-read
    except Exception as e:
        logger.warning("aot cache write failed: %s", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return compiled
