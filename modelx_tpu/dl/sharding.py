"""Shard-layout annotations: tensor-name -> PartitionSpec rules.

The manifest's ``modelx.shard.spec`` annotation carries a JSON list of
``[regex, partition_spec]`` rules (first match wins), where partition_spec is
a list with one entry per tensor dimension: an axis name ("tp"), a list of
axis names, or null for replicated. This is the registry-storable form of a
GSPMD layout — the t5x/maxtext logical-axis-rules idea flattened onto
checkpoint tensor names.

Default rule sets for the model families live here too, so a checkpoint
pushed without annotations still loads sharded.
"""

from __future__ import annotations

import json
import logging
import re
from typing import TYPE_CHECKING, Sequence

if TYPE_CHECKING:  # jax is imported lazily: the rule tables and the
    # encode/decode/infer_family half of this module must stay importable
    # from jax-free contexts (the client-side push annotates manifests
    # with these rules without ever touching a device)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

Rules = list[tuple[str, list]]


def encode_rules(rules: Rules) -> str:
    return json.dumps([[pattern, spec] for pattern, spec in rules])


def decode_rules(payload: str) -> Rules:
    return [(pattern, spec) for pattern, spec in json.loads(payload)]


def spec_for(name: str, rules: Rules) -> PartitionSpec:
    """First-match-wins lookup of a tensor's PartitionSpec."""
    from jax.sharding import PartitionSpec

    for pattern, spec in rules:
        if re.search(pattern, name):
            return PartitionSpec(*[tuple(s) if isinstance(s, list) else s for s in spec])
    return PartitionSpec()


def clean_spec(spec: PartitionSpec, mesh: Mesh) -> PartitionSpec:
    """Drop axis names the mesh doesn't have (e.g. tp rules on a dp-only mesh)."""
    from jax.sharding import PartitionSpec

    cleaned = []
    for entry in spec:
        if entry is None:
            cleaned.append(None)
        elif isinstance(entry, tuple):
            kept = tuple(a for a in entry if a in mesh.axis_names)
            cleaned.append(kept if kept else None)
        else:
            cleaned.append(entry if entry in mesh.axis_names else None)
    return PartitionSpec(*cleaned)


def sharding_for(name: str, rules: Rules, mesh: Mesh) -> NamedSharding:
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, clean_spec(spec_for(name, rules), mesh))


def cache_sharding(mesh: Mesh, shape: Sequence[int], batch_dim: int = 0,
                   head_dim: int = 2) -> NamedSharding:
    """NamedSharding for one KV-cache leaf: slots over dp, kv heads over
    tp — each axis applied only when the mesh has it AND its size divides
    the dimension (GQA head counts and tiny test models routinely don't
    divide; an indivisible dim replicates rather than erroring). Pass
    ``batch_dim=-1`` for pooled/paged leaves whose leading dim is a global
    page index no axis may split."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec: list = [None] * len(shape)

    def _assign(axis: str, dim: int) -> None:
        size = dict(mesh.shape).get(axis, 1)
        if 0 <= dim < len(shape) and size > 1 and shape[dim] % size == 0:
            spec[dim] = axis

    _assign("dp", batch_dim)
    _assign("tp", head_dim)
    return NamedSharding(mesh, PartitionSpec(*spec))


# -- default rule sets --------------------------------------------------------

# Llama-family (HF safetensors names). Megatron-style: attention q/k/v and
# ffn up/gate column-parallel (shard dim 0, the output features), o_proj and
# down_proj row-parallel (shard dim 1), embeddings sharded over vocab.
LLAMA_RULES: Rules = [
    (r"embed_tokens\.weight$", ["tp", None]),
    (r"lm_head\.weight$", ["tp", None]),
    (r"(q|k|v)_proj\.weight$", ["tp", None]),
    (r"o_proj\.weight$", [None, "tp"]),
    (r"(gate|up)_proj\.weight$", ["tp", None]),
    (r"down_proj\.weight$", [None, "tp"]),
    (r"norm\.weight$", [None]),
    (r".*", []),
]

# Llama with FSDP: every weight additionally shards its non-tp dimension
# over the ``fsdp`` axis (ZeRO-3 / scaling-book "fully sharded" layout);
# XLA all-gathers params just-in-time per layer and reduce-scatters grads.
# The embedding shards VOCAB over both axes (hidden replicated): an
# fsdp-sharded hidden dim would make the lookup's output hidden-sharded,
# and resharding that to the batch-sharded activation layout is an
# involuntary full rematerialization in the SPMD partitioner; the
# vocab-parallel table lowers to masked-gather + psum instead and is just
# as fully sharded.
LLAMA_FSDP_RULES: Rules = [
    (r"embed_tokens\.weight$", [["tp", "fsdp"], None]),
    (r"lm_head\.weight$", ["tp", "fsdp"]),
    (r"(q|k|v)_proj\.weight$", ["tp", "fsdp"]),
    (r"o_proj\.weight$", ["fsdp", "tp"]),
    (r"(gate|up)_proj\.weight$", ["tp", "fsdp"]),
    (r"down_proj\.weight$", ["fsdp", "tp"]),
    (r"norm\.weight$", [None]),
    (r".*", []),
]

# Qwen2 (HF names): llama's layout plus q/k/v input biases, which split
# with their column-parallel weights' output features (dim 0 over tp).
QWEN2_RULES: Rules = [
    (r"(q|k|v)_proj\.bias$", ["tp"]),
    *LLAMA_RULES,
]

# Gemma2 (HF names): llama's projection layout; the extra sandwich norms
# (pre/post_feedforward_layernorm) are 1-D and replicate via the norm rule.
GEMMA2_RULES: Rules = LLAMA_RULES

# Phi-3 (HF names): llama with FUSED qkv_proj / gate_up_proj. The fused
# tensors shard their output rows over tp like their unfused counterparts;
# the forward's in-jit q/k/v (gate/up) slices cross shard boundaries when
# the sub-block sizes don't divide by tp, and GSPMD inserts the reshard —
# correct everywhere, optimal when tp divides each sub-block.
PHI3_RULES: Rules = [
    (r"embed_tokens\.weight$", ["tp", None]),
    (r"lm_head\.weight$", ["tp", None]),
    (r"qkv_proj\.weight$", ["tp", None]),
    (r"o_proj\.weight$", [None, "tp"]),
    (r"gate_up_proj\.weight$", ["tp", None]),
    (r"down_proj\.weight$", [None, "tp"]),
    (r"norm\.weight$", [None]),
    (r".*", []),
]

# GPT-2 (HF names; Conv1D weights are [in, out] so column-parallel = dim 1).
GPT2_RULES: Rules = [
    (r"wte\.weight$", ["tp", None]),
    (r"wpe\.weight$", [None, None]),
    (r"c_attn\.weight$", [None, "tp"]),
    (r"c_attn\.bias$", ["tp"]),
    (r"attn\.c_proj\.weight$", ["tp", None]),
    (r"c_fc\.weight$", [None, "tp"]),
    (r"c_fc\.bias$", ["tp"]),
    (r"mlp\.c_proj\.weight$", ["tp", None]),
    (r".*", []),
]

# BERT (HF names).
BERT_RULES: Rules = [
    (r"word_embeddings\.weight$", ["tp", None]),
    (r"(query|key|value)\.weight$", ["tp", None]),
    (r"(query|key|value)\.bias$", ["tp"]),
    (r"attention\.output\.dense\.weight$", [None, "tp"]),
    (r"intermediate\.dense\.weight$", ["tp", None]),
    (r"intermediate\.dense\.bias$", ["tp"]),
    (r"output\.dense\.weight$", [None, "tp"]),
    (r".*", []),
]

# Mixtral (llama attention + stacked-expert MoE FFN; models/mixtral.py).
# Expert axis over ep, expert ffn features over tp within each expert.
MIXTRAL_RULES: Rules = [
    (r"embed_tokens\.weight$", ["tp", None]),
    (r"lm_head\.weight$", ["tp", None]),
    (r"(q|k|v)_proj\.weight$", ["tp", None]),
    (r"o_proj\.weight$", [None, "tp"]),
    (r"block_sparse_moe\.gate\.weight$", [None, None]),
    (r"experts\.(w1|w3)\.weight$", ["ep", "tp", None]),
    (r"experts\.w2\.weight$", ["ep", None, "tp"]),
    (r"norm\.weight$", [None]),
    (r".*", []),
]

DEFAULT_RULES: dict[str, Rules] = {
    "llama": LLAMA_RULES,
    "qwen2": QWEN2_RULES,
    "gemma2": GEMMA2_RULES,
    "phi3": PHI3_RULES,
    "gpt2": GPT2_RULES,
    "bert": BERT_RULES,
    "mixtral": MIXTRAL_RULES,
}


def rules_for_family(family: str) -> Rules:
    return DEFAULT_RULES.get(family, [(r".*", [])])


logger = logging.getLogger("modelx.dl")


def infer_family(tensor_names: Sequence[str]) -> str:
    names = list(tensor_names)
    joined = "\n".join(names)
    if "block_sparse_moe" in joined:
        return "mixtral"
    if "pre_feedforward_layernorm" in joined:
        # llama layout + sandwich norms: gemma2 — but gemma3 ALSO carries
        # them, adding per-head q_norm/k_norm attention norms (and a
        # different rope/window schedule) that gemma2's math doesn't have;
        # running gemma3 through the gemma2 branch would decode garbage
        # while the extra norm tensors load silently replicated. Fail
        # loudly instead of matching (families.detect raises on "").
        if "q_norm" in joined or "k_norm" in joined:
            logger.warning(
                "checkpoint has gemma2-style sandwich norms AND q_norm/"
                "k_norm attention-norm tensors (gemma3?): refusing the "
                "gemma2 family match — these layer tensors are not part "
                "of any supported architecture"
            )
            return ""
        return "gemma2"
    if "qkv_proj" in joined:
        return "phi3"  # llama layout with fused qkv/gate_up projections
    if "q_proj.bias" in joined:
        return "qwen2"  # llama layout + qkv biases
    if "q_proj" in joined or "gate_proj" in joined:
        return "llama"
    if "c_attn" in joined or "wte" in joined:
        return "gpt2"
    if "word_embeddings" in joined:
        return "bert"
    return ""
