"""Checkpoint/resume through the registry (SURVEY.md §5: "the registry *is*
a checkpoint store" — versioned manifests + content-addressed incremental
push/pull, docs/how-modelx-born.md:211-222).

TPU-native shape: a training state (params + optax optimizer state + step)
is flattened to named host tensors and written as *layer-grouped* safetensors
shards. Grouping by layer makes incremental push real: after N more steps
only the shards whose tensors changed get uploaded — unchanged shards are
skipped by the push engine's content-address HEAD dedup (push.go:169-177
semantics), and pull/restore re-downloads only changed shards (pull hash-skip).

Restore goes through the HBM loader, so resumed state lands directly on the
mesh with the same partition rules that trained it.

    ckpt = Checkpointer(dir)
    ckpt.save(params, opt_state, step=100)
    client.push(...)                       # or ckpt.push(uri)
    params, opt_state, step = ckpt.restore(template_params, template_opt,
                                           mesh, rules)
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import jax
import numpy as np

from modelx_tpu.dl import safetensors as st

STEP_FILE = "checkpoint.json"
_OPT_PREFIX = "__opt__"
_SEP = "|"


# -- pytree <-> flat named tensors --------------------------------------------


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return _SEP.join(parts)


def flatten_state(opt_state: Any) -> dict[str, np.ndarray]:
    """Flatten any pytree of arrays into named host tensors (names encode
    the tree path; scalars included)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(opt_state)[0]:
        flat[_OPT_PREFIX + _path_str(path)] = np.asarray(leaf)
    return flat


def restore_state(template: Any, flat: dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree shaped like ``template`` from flattened tensors."""
    paths_and_leaves = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, tmpl_leaf in paths_and_leaves[0]:
        key = _OPT_PREFIX + _path_str(path)
        if key not in flat:
            raise KeyError(f"checkpoint missing optimizer leaf {key}")
        arr = flat[key]
        want = tuple(np.shape(tmpl_leaf))
        if tuple(arr.shape) != want:
            if int(np.prod(arr.shape or (1,))) != int(np.prod(want or (1,))):
                raise ValueError(f"optimizer leaf {key}: shape {arr.shape} != {want}")
            arr = arr.reshape(want)  # 0-d leaves round-trip as shape-(1,)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(paths_and_leaves[1], leaves)


# -- layer-grouped sharding ----------------------------------------------------

_LAYER = re.compile(r"(?:^|\.)layers?\.(\d+)\.")


def group_key(name: str) -> str:
    """Shard-group for a tensor: its layer index, or 'base' for the rest.
    Optimizer leaves group with the params they track when their path
    embeds a layer index."""
    m = _LAYER.search(name.replace(_SEP, "."))
    return f"layer-{int(m.group(1)):05d}" if m else "base"


def save_sharded(directory: str, tensors: dict[str, np.ndarray]) -> list[str]:
    """Write tensors as layer-grouped safetensors files. Deterministic
    grouping + deterministic safetensors serialization => unchanged layers
    produce byte-identical files across saves (the dedup unit). Each shard
    is written to a temp name and renamed, so a crash mid-save never
    corrupts an existing shard."""
    groups: dict[str, dict[str, np.ndarray]] = {}
    for name, arr in sorted(tensors.items()):
        groups.setdefault(group_key(name), {})[name] = arr
    os.makedirs(directory, exist_ok=True)
    written = []
    for key, members in sorted(groups.items()):
        fname = f"state-{key}.safetensors"
        path = os.path.join(directory, fname)
        tmp = path + f".tmp-{os.getpid()}"
        st.write_safetensors(tmp, members)
        os.replace(tmp, path)
        written.append(fname)
    return written


class Checkpointer:
    """Save/restore a (params, opt_state, step) training state in a local
    directory shaped for registry push (content-addressed incremental)."""

    def __init__(self, directory: str) -> None:
        self.directory = directory

    def save(self, params: dict, opt_state: Any = None, step: int = 0) -> list[str]:
        tensors: dict[str, np.ndarray] = {k: np.asarray(v) for k, v in params.items()}
        if opt_state is not None:
            tensors.update(flatten_state(opt_state))
        written = save_sharded(self.directory, tensors)
        meta = {"step": int(step), "files": written, "params": sorted(params)}
        tmp = os.path.join(self.directory, STEP_FILE + f".tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(meta, f, sort_keys=True)
        os.replace(tmp, os.path.join(self.directory, STEP_FILE))  # commit point
        # prune shards from an older layout only AFTER the commit point: a
        # crash before the rename must leave every shard the still-current
        # checkpoint.json references. Only files matching this class's own
        # shard naming scheme (state-*.safetensors) are candidates — the
        # directory may also hold pulled model weights (model.safetensors
        # etc.), which a checkpoint save must never touch.
        import glob

        for path in glob.glob(os.path.join(self.directory, "state-*.safetensors")):
            if os.path.basename(path) not in written:
                os.unlink(path)
        return written

    def _shard_paths(self) -> list[str]:
        import glob

        meta_path = os.path.join(self.directory, STEP_FILE)
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                files = json.load(f).get("files")
            if files:
                return [os.path.join(self.directory, fn) for fn in files]
        return sorted(glob.glob(os.path.join(self.directory, "*.safetensors")))

    def _step(self) -> int:
        meta_path = os.path.join(self.directory, STEP_FILE)
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return int(json.load(f).get("step", 0))
        return 0

    def _read_flat(self, want=None) -> dict[str, np.ndarray]:
        """Read tensors from the manifest's shard list; ``want(name)``
        filters without reading skipped tensors' bytes."""
        flat: dict[str, np.ndarray] = {}
        for path in self._shard_paths():
            flat.update(st.read_tensors(path, want))
        return flat

    def restore(
        self,
        template_params: dict,
        template_opt: Any = None,
        mesh=None,
        rules=None,
    ) -> tuple[dict, Any, int]:
        """Returns (params, opt_state, step). With ``mesh``+``rules`` the
        params stream through the HBM loader (sharded, parallel ranged
        reads); optimizer state follows the same placement rules as the
        params its leaves track."""
        step = self._step()
        use_loader = mesh is not None and rules is not None
        if use_loader:
            # one header parse per shard: optimizer leaves read inline into
            # host memory, param bytes stream through the HBM loader
            from modelx_tpu.dl.loader import LocalFileSource, load_safetensors

            params: dict = {}
            opt_flat: dict[str, np.ndarray] = {}
            for path in self._shard_paths():
                with open(path, "rb") as f:
                    infos, off = st.read_header(f)
                    for name, info in infos.items():
                        if name.startswith(_OPT_PREFIX):
                            f.seek(off + info.start)
                            raw = f.read(info.nbytes)
                            opt_flat[name] = (
                                np.frombuffer(raw, info.np_dtype()).reshape(info.shape).copy()
                            )
                wanted = {n: i for n, i in infos.items() if not n.startswith(_OPT_PREFIX)}
                if not wanted:
                    continue
                src = LocalFileSource(path)
                try:
                    loaded, _stats = load_safetensors(
                        src, mesh, rules, tensors=wanted, data_offset=off
                    )
                finally:
                    src.close()
                params.update(loaded)
        else:
            flat = self._read_flat()
            opt_flat = {k: v for k, v in flat.items() if k.startswith(_OPT_PREFIX)}
            params = {k: v for k, v in flat.items() if not k.startswith(_OPT_PREFIX)}

        missing = set(template_params) - set(params)
        if missing:
            raise KeyError(f"checkpoint missing params: {sorted(missing)[:4]}...")

        opt_state = None
        if template_opt is not None:
            opt_state = restore_state(template_opt, opt_flat)
            if mesh is not None:
                # optimizer leaves inherit the sharding of their params when
                # the tree path names one (adam mu/nu mirror the param tree)
                from modelx_tpu.dl.sharding import sharding_for

                def place(path, leaf):
                    name = _path_str(path)
                    for pname in template_params:
                        if name.endswith(_SEP + pname) or name == pname:
                            return jax.device_put(leaf, sharding_for(pname, rules, mesh))
                    return jax.device_put(leaf)

                opt_state = jax.tree_util.tree_map_with_path(place, opt_state)
        return params, opt_state, step

    def push(self, uri: str, quiet: bool = True) -> None:
        """Push the checkpoint directory as a model version; unchanged layer
        shards are skipped by content-address dedup."""
        from modelx_tpu.client.reference import parse_reference

        ref = parse_reference(uri)
        ref.client(quiet=quiet).push(ref.repository, ref.version or "latest", self.directory)
