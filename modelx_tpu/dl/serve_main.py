"""`modelx-serve` console entrypoint: the serving container's command
(referenced by dl/podspec.py's generated pod spec).

Single model:      modelx-serve --model-dir /mnt/model
Multi-tenant:      modelx-serve --model a=/mnt/a --model b=/mnt/b
                   (BASELINE config #5: concurrent pull+serve of N models)
"""

from __future__ import annotations

import logging
import signal
import threading
import time

import click

from modelx_tpu.dl.serve import ModelServer, ServerSet, enable_compile_cache, serve


@click.command("modelx-serve")
@click.option("--model-dir", default="", help="volume with *.safetensors (from modelx dl)")
@click.option("--model", "models", multiple=True,
              help="name=dir; repeatable for multi-tenant serving")
@click.option("--mesh", default="", help='mesh spec, e.g. "dp=1,tp=8" (default: dp over all devices)')
@click.option("--dtype", default="bfloat16", type=click.Choice(["bfloat16", "float32"]))
@click.option("--listen", default=":8000")
@click.option("--max-seq-len", default=2048, type=int)
@click.option("--compile-cache/--no-compile-cache", default=True,
              help="persistent XLA compilation cache (restart TTFT)")
@click.option("--blob-cache-dir", default="",
              help="content-addressed local blob cache for registry-backed "
                   "loads (dl/blob_cache.py): warm restarts of an "
                   "already-served checkpoint skip the network")
@click.option("--blob-cache-max-bytes", default=0, type=int,
              help="blob cache size cap; LRU eviction (0 = unbounded)")
@click.option("--concurrent-load", is_flag=True, help="overlap multi-model loads")
@click.option("--trace-dir", default="", help="jax profiler output dir (/v1/profile)")
@click.option("--dynamic-batch", is_flag=True,
              help="coalesce concurrent forward requests into one device call")
@click.option("--continuous-batch", is_flag=True,
              help="iteration-level (in-flight) batching: generate/stream "
                   "requests join a running decode at chunk boundaries "
                   "(supersedes --dynamic-batch for generate traffic; "
                   "composes with --speculative-k: a lone greedy row "
                   "speculates inside the engine)")
@click.option("--max-slots", default=8, type=int,
              help="continuous batching: concurrent decode slots (KV cache "
                   "rows held on device)")
@click.option("--kv-page-size", default=0, type=int,
              help="continuous batching: paged KV — the engine state becomes "
                   "a pool of PAGE_SIZE-token pages + a block table, so HBM "
                   "scales with live tokens instead of max_slots x "
                   "max_seq_len (use with --max-slots 16+; 0 = dense)")
@click.option("--kv-live-tokens", default=0, type=int,
              help="paged KV: pool capacity in tokens (default "
                   "max_slots x max_seq_len / 4)")
@click.option("--kv-attention", default="gather",
              type=click.Choice(["gather", "in-place"]),
              help="paged KV chunk attention: 'gather' (default) is "
                   "bit-exact vs every other decode path; 'in-place' reads "
                   "the page pools directly (blockwise softmax, per-step "
                   "transient = one page block — long-context deployments; "
                   "sampled rows may flip at bf16 near-ties)")
@click.option("--max-batch", default=32, type=int,
              help="dynamic batching: max requests coalesced per device call")
@click.option("--batch-window-ms", default=3.0, type=float,
              help="dynamic batching: how long a request waits for "
                   "companions (latency/throughput dial)")
@click.option("--stream-chunk-size", default=8, type=int,
              help="tokens decoded per flush on streaming responses (also "
                   "the continuous engine's chunk length)")
@click.option("--pipeline-depth", default=2, type=int,
              help="continuous batching: decode chunks kept in flight "
                   "before syncing the oldest — hides the dispatch/fetch "
                   "round-trip behind device compute (stop-token and "
                   "disconnect exits lag by up to DEPTH chunks of wasted "
                   "compute; 1 = classic lockstep)")
@click.option("--dispatch-depth", default=0, type=int,
              help="continuous batching: decode chunks scanned per device "
                   "program — in steady decode (no admission, prefill "
                   "piece, or stream flush due) the engine dispatches "
                   "DEPTH x stream-chunk-size steps per call, amortizing "
                   "the fixed dispatch round-trip DEPTH-fold; any pending "
                   "boundary event snaps back to per-chunk dispatch. "
                   "EOS/cancel/deadline detection lags by up to the "
                   "program's span (wasted compute, never wrong tokens — "
                   "outputs stay byte-exact and streams keep per-chunk "
                   "flush granularity). 0 = auto (4 in steady decode); "
                   "1 = classic per-chunk dispatch")
@click.option("--burst-window-ms", default=1.0, type=float,
              help="continuous batching: when a request hits an IDLE "
                   "engine, wait this long for co-arrivals so the burst "
                   "admits as one program and decodes in step (0 = off)")
@click.option("--prefill-chunk", default=0, type=int,
              help="continuous batching: chunked prefill — prompts longer "
                   "than this many tokens (16-bucketed) land piece by "
                   "piece between decode chunks instead of as one "
                   "monolithic admission prefill, bounding the inter-token "
                   "latency jitter a long admission inflicts on the "
                   "running batch (0 = off)")
@click.option("--prefill-budget", default=0, type=int,
              help="chunked prefill: per-boundary token budget — decode "
                   "rows spend chunk_size each first, prefill pieces pack "
                   "into the remainder (the head piece always lands; "
                   "0 = one piece per filling row per boundary)")
@click.option("--max-queue-depth", default=0, type=int,
              help="continuous batching: bound the admission backlog — a "
                   "submit past this many not-yet-admitted rows is shed "
                   "with 429 + Retry-After instead of queueing into "
                   "unbounded latency (0 = unbounded)")
@click.option("--request-timeout", default=0.0, type=float,
              help="continuous batching: per-request deadline in seconds — "
                   "a request older than this expires with 504 at the next "
                   "chunk boundary, whether it is still queued, prefilling, "
                   "or decoding (0 = no deadline)")
@click.option("--prefix-cache", default=0, type=int,
              help="keep the prefill KV of the last N single-row stream "
                   "prompts on device: multi-turn chats that re-send their "
                   "history prefill only the new suffix (0 = off)")
@click.option("--prefix-cache-max-bytes", default=0, type=int,
              help="additional BYTE cap on the prefix cache's stored KV "
                   "(entry count alone over-commits HBM for long "
                   "prefixes; 0 = entry cap only)")
@click.option("--quantize", type=click.Choice(["int8"]), default=None,
              help="weight-only int8: half the HBM/transfer bytes for the big matmuls")
@click.option("--speculative-k", default=0, type=int,
              help="prompt-lookup speculative decoding for single-row greedy "
                   "requests: verify up to K proposed tokens per device step "
                   "(token-exact; 0 = off)")
@click.option("--lora", "loras", multiple=True, metavar="NAME=ADAPTER_DIR",
              help="merge a PEFT-style LoRA adapter into model NAME at load "
                   "('default' for --model-dir); repeatable")
@click.option("--hbm-budget-bytes", default=0, type=int,
              help="model lifecycle pool: PER-DEVICE memory budget — a "
                   "runtime load whose estimated per-device footprint "
                   "(manifest/safetensors sizes divided by the mesh's "
                   "weight-shard factor: tp*ep*pp*fsdp) does not fit is "
                   "refused with 507, or makes room by LRU-evicting idle "
                   "models under --evict-idle (0 = unbudgeted)")
@click.option("--evict-idle", is_flag=True,
              help="with --hbm-budget-bytes: LRU-evict READY models that "
                   "have no in-flight requests to make room for a new load "
                   "instead of refusing it")
@click.option("--host-state-budget-bytes", default=0, type=int,
              help="tiered live state (dl/tiers.py): bound for the host-RAM "
                   "tier that evicted/unloaded models' params demote into "
                   "instead of being discarded — a later load of the same "
                   "content is a tier promotion (device_put, no pull/parse). "
                   "LRU within the tier; overflow spills to the disk tier "
                   "(0 = host tier off)")
@click.option("--disk-state-budget-bytes", default=0, type=int,
              help="bound for the local-disk tier (decoded-tensor spool "
                   "under --state-spool-dir) that host-tier overflow spills "
                   "into; disk overflow drops oldest (0 = disk tier off; "
                   "both 0 = tiering off, eviction discards as before)")
@click.option("--state-spool-dir", default="",
              help="where the disk tier spools decoded tensors — put it "
                   "next to --blob-cache-dir (default: "
                   "$TMPDIR/modelx-state-spool)")
@click.option("--allow-admin-load", is_flag=True,
              help="enable the runtime lifecycle surface: POST "
                   "/admin/models pulls+loads a registry ref while traffic "
                   "is live, DELETE /admin/models/{name} drains and frees "
                   "one (GET /admin/models always reports states)")
@click.option("--publish-programs", is_flag=True,
              help="after a runtime (registry-ref) load reaches READY, "
                   "export the pod's compiled programs and attach them to "
                   "the model version as a program bundle "
                   "(application/vnd.modelx.program.v1) so the next "
                   "puller boots compile-warm")
@click.option("--registry-mirror", "registry_mirrors", multiple=True,
              help="read mirror(s) of the registry (comma list; "
                   "repeatable): manifest/blob GETs fail over to them and "
                   "ranged blob reads hedge across them — writes (publish) "
                   "always go to the primary (docs/serving.md outage "
                   "playbook)")
@click.option("--manifest-cache-dir", default="",
              help="pin every fetched manifest to this dir: when the "
                   "registry AND all mirrors are down, digest-pinned "
                   "cached manifests + the blob cache serve pulls offline "
                   "(control_plane: offline on /healthz; readiness is "
                   "never gated on it)")
@click.option("--publish-kv", is_flag=True,
              help="sweep the prefix caches of runtime (registry-ref) "
                   "loaded models for entries hit at least "
                   "--kv-publish-threshold times and attach them to the "
                   "model version as kv bundles "
                   "(application/vnd.modelx.kvcache.v1) so replicas skip "
                   "re-prefilling shared prompt prefixes (docs/kv.md)")
@click.option("--kv-publish-threshold", default=2, type=int,
              help="prefix-cache hit count at which an entry becomes hot "
                   "enough to publish (with --publish-kv)")
@click.option("--kv-fetch-through", is_flag=True,
              help="on a prefix-cache miss, consult the model version's "
                   "published kv bundles and install a matching prefix "
                   "(bounded by --prefix-cache-max-bytes; runtime loads "
                   "only)")
@click.option("--publish-outbox-dir", default="",
              help="durable publish outbox: --publish-programs and "
                   "--publish-kv bundles spool here and a background "
                   "drainer pushes them with backoff, so a registry outage "
                   "never blocks or fails a load (pending entries survive "
                   "pod restarts)")
@click.option("--outbox-max-entries", default=0, type=int,
              help="outbox spool bound; a full spool drops new publishes "
                   "with a counted warning (0 = default 64)")
@click.option("--admin-token", "admin_tokens", multiple=True,
              help="bearer token accepted on the /admin surface "
                   "(repeatable; none = anonymous admin — dev pods only)")
@click.option("--staging-dir", default="",
              help="where runtime-pulled model blobs land before loading "
                   "(default: $TMPDIR/modelx-pool-staging)")
@click.option("--drain-seconds", default=5.0, type=float,
              help="on SIGTERM, serve 503 on /healthz for this long (so load "
                   "balancers drain) before stopping")
@click.option("--drain-grace", default=0.0, type=float,
              help="coordinated drain: on SIGTERM, stop admission and wait "
                   "for in-flight requests (streams included, to their last "
                   "byte) to reach zero, up to this many seconds, instead "
                   "of the fixed --drain-seconds sleep. The fleet router "
                   "proactively CONTINUES this pod's live streams elsewhere "
                   "once /healthz reports draining (docs/router.md), so the "
                   "count drains fast (0 = fixed-sleep drain)")
@click.option("--boundary-watchdog-s", default=0.0, type=float,
              help="continuous batching: treat a device dispatch that makes "
                   "no chunk-boundary progress for this many seconds as a "
                   "crash — the engine's restart/breaker machinery applies "
                   "and waiters get EngineBrokenError instead of hanging "
                   "forever (a wedged TPU dispatch is otherwise silent; "
                   "0 = off). Size it well above the worst legitimate "
                   "boundary: first-request compiles run minutes on TPU")
@click.option("--access-log", default="",
              help="append one JSON line per request (request id, hashed "
                   "client identity, model, status, per-phase timing) to "
                   "this path; empty = off")
@click.option("--access-log-max-bytes", default=0, type=int,
              help="rotate the access log once it exceeds this many bytes "
                   "(renamed to <path>.1, one generation kept; 0 = never)")
@click.option("--flight-dump-dir", default="",
              help="continuous batching: on an engine crash, watchdog "
                   "fire, or circuit-break, write the flight recorder's "
                   "last events + per-slot state as a JSON-lines black-box "
                   "file here (the live ring is GET /debug/flightrec; "
                   "empty = no dump files)")
@click.option("--flightrec-capacity", default=0, type=int,
              help="flight recorder ring size in events (0 = default 512)")
@click.option("--flight-recorder/--no-flight-recorder", default=True,
              help="record structured engine events (admission, dispatch, "
                   "readback, preemption, EOS, deadline, crash) into a "
                   "bounded in-memory ring")
@click.option("--device-telemetry/--no-device-telemetry", default=True,
              help="sample measured device memory (jax memory_stats, "
                   "live-buffer census fallback) into /metrics and "
                   "/admin/models next to the lifecycle estimates")
def main(model_dir: str, models: tuple[str, ...], mesh: str, dtype: str, listen: str,
         max_seq_len: int, compile_cache: bool,
         blob_cache_dir: str, blob_cache_max_bytes: int,
         concurrent_load: bool, trace_dir: str,
         dynamic_batch: bool, continuous_batch: bool, max_slots: int,
         kv_page_size: int, kv_live_tokens: int, kv_attention: str,
         max_batch: int, batch_window_ms: float, stream_chunk_size: int,
         pipeline_depth: int, dispatch_depth: int, burst_window_ms: float,
         prefill_chunk: int, prefill_budget: int,
         max_queue_depth: int, request_timeout: float,
         prefix_cache: int, prefix_cache_max_bytes: int,
         quantize: str | None, speculative_k: int,
         hbm_budget_bytes: int, evict_idle: bool,
         host_state_budget_bytes: int, disk_state_budget_bytes: int,
         state_spool_dir: str, allow_admin_load: bool,
         publish_programs: bool, publish_kv: bool,
         kv_publish_threshold: int, kv_fetch_through: bool,
         registry_mirrors: tuple[str, ...], manifest_cache_dir: str,
         publish_outbox_dir: str, outbox_max_entries: int,
         admin_tokens: tuple[str, ...], staging_dir: str,
         loras: tuple[str, ...], drain_seconds: float,
         drain_grace: float, boundary_watchdog_s: float,
         access_log: str, access_log_max_bytes: int,
         flight_dump_dir: str, flightrec_capacity: int,
         flight_recorder: bool, device_telemetry: bool) -> None:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    from modelx_tpu.parallel.distributed import initialize

    initialize()  # no-op single-process; wires multi-host TPU pods
    if compile_cache:
        enable_compile_cache()
    if blob_cache_dir:
        # process-default blob cache: every registry-backed load this
        # process performs (deploy-time pulls, re-loads) tees through it
        from modelx_tpu.dl.blob_cache import configure_default

        configure_default(blob_cache_dir, max_bytes=blob_cache_max_bytes)
    if registry_mirrors:
        # comma lists and repeats both accepted; process-wide so every
        # registry client this pod builds (pulls, tier keying, outbox
        # drains) fails over identically
        from modelx_tpu.client.remote import set_mirrors

        flat: list[str] = []
        for m in registry_mirrors:
            flat.extend(p.strip() for p in m.split(","))
        set_mirrors([m for m in flat if m])
    if manifest_cache_dir:
        from modelx_tpu.dl import manifest_cache

        manifest_cache.configure_default(manifest_cache_dir)
    entries: dict[str, str] = {}
    if model_dir:
        entries["default"] = model_dir
    for spec in models:
        name, _, path = spec.partition("=")
        if not path:
            raise click.UsageError(f"--model wants name=dir, got {spec!r}")
        entries[name] = path
    if not entries:
        raise click.UsageError("need --model-dir or at least one --model name=dir")
    lora_dirs: dict[str, str] = {}
    for spec in loras:
        name, _, path = spec.partition("=")
        if not path:
            raise click.UsageError(f"--lora wants NAME=ADAPTER_DIR, got {spec!r}")
        if name not in entries:
            raise click.UsageError(f"--lora {name!r}: no such --model")
        lora_dirs[name] = path
    if lora_dirs and quantize:
        # int8 quantizes exactly the 2-D proj weights LoRA targets; merging
        # into QTensors is rejected downstream — fail before the multi-GB
        # base streams to HBM, not after
        raise click.UsageError("--lora cannot combine with --quantize "
                               "(adapters merge into full-precision weights)")

    # one mesh shared by every tenant (same devices either way; sharing keeps
    # shardings comparable and avoids rebuilding device lists per model)
    import jax

    from modelx_tpu.parallel.mesh import make_mesh

    shared_mesh = make_mesh(mesh) if mesh else make_mesh(f"dp={len(jax.devices())}")
    from modelx_tpu.parallel.mesh import mesh_str, weight_shard_factor

    logging.getLogger("modelx.serve").info(
        "serving mesh %s (%d device(s), weight shard factor %d)",
        mesh_str(shared_mesh), shared_mesh.size,
        weight_shard_factor(shared_mesh),
    )
    servers = {
        name: ModelServer(path, dtype=dtype, max_seq_len=max_seq_len,
                          name=name, mesh=shared_mesh, quantize=quantize,
                          speculative_k=speculative_k,
                          lora_dir=lora_dirs.get(name, ""),
                          prefix_cache_size=prefix_cache,
                          prefix_cache_max_bytes=prefix_cache_max_bytes)
        for name, path in entries.items()
    }
    if continuous_batch and speculative_k:
        logging.getLogger("modelx.serve").info(
            "--continuous-batch + --speculative-k: the engine speculates "
            "whenever a single greedy row has the device to itself"
        )
    if prefill_chunk and not continuous_batch:
        logging.getLogger("modelx.serve").warning(
            "--prefill-chunk is inert without --continuous-batch "
            "(chunked prefill is the continuous engine's admission policy)"
        )
    if (max_queue_depth or request_timeout) and not continuous_batch:
        logging.getLogger("modelx.serve").warning(
            "--max-queue-depth/--request-timeout are inert without "
            "--continuous-batch (bounded admission is the continuous "
            "engine's submit policy)"
        )
    if prefix_cache and speculative_k and not continuous_batch:
        # the speculative decoder owns single-row streams before the
        # ChunkedDecoder (the prefix cache's stream seam) is consulted;
        # under --continuous-batch the engine's ADMISSION path uses the
        # prefix cache, so that combination is first-class
        logging.getLogger("modelx.serve").warning(
            "--prefix-cache is inert under --speculative-k "
            "(the speculative decoder handles the streams it would accelerate)"
        )
    sset = ServerSet(servers, trace_dir=trace_dir, dynamic_batch=dynamic_batch,
                     continuous_batch=continuous_batch, max_slots=max_slots,
                     max_batch=max_batch, batch_window_ms=batch_window_ms,
                     stream_chunk_size=stream_chunk_size,
                     kv_page_size=kv_page_size, kv_live_tokens=kv_live_tokens,
                     kv_attention=kv_attention, pipeline_depth=pipeline_depth,
                     dispatch_depth=dispatch_depth,
                     burst_window_ms=burst_window_ms,
                     prefill_chunk=prefill_chunk,
                     prefill_budget=prefill_budget,
                     max_queue_depth=max_queue_depth,
                     request_timeout_s=request_timeout,
                     boundary_watchdog_s=boundary_watchdog_s,
                     hbm_budget_bytes=hbm_budget_bytes,
                     evict_idle=evict_idle,
                     allow_admin_load=allow_admin_load,
                     admin_tokens=admin_tokens,
                     staging_root=staging_dir,
                     host_state_budget_bytes=host_state_budget_bytes,
                     disk_state_budget_bytes=disk_state_budget_bytes,
                     state_spool_dir=state_spool_dir,
                     flight_recorder=flight_recorder,
                     flightrec_capacity=flightrec_capacity,
                     flight_dump_dir=flight_dump_dir,
                     device_telemetry=device_telemetry)
    # runtime-loaded models get the same cache knobs the boot set got
    sset.server_defaults.update(
        prefix_cache_size=prefix_cache,
        prefix_cache_max_bytes=prefix_cache_max_bytes,
    )
    if (publish_programs or publish_kv) and publish_outbox_dir \
            and sset.pool is not None:
        sset.pool.attach_outbox(
            publish_outbox_dir,
            max_entries=outbox_max_entries or None,
        )
    if publish_programs:
        if sset.pool is not None:
            sset.pool.publish_programs = True
        if not allow_admin_load:
            logging.getLogger("modelx.serve").warning(
                "--publish-programs only fires on runtime (registry-ref) "
                "loads; without --allow-admin-load none happen — use "
                "`modelx programs push` to publish for boot-loaded models"
            )
    if publish_kv and sset.pool is not None:
        sset.pool.attach_kv_publisher(threshold=kv_publish_threshold)
        if not prefix_cache:
            logging.getLogger("modelx.serve").warning(
                "--publish-kv is inert without --prefix-cache "
                "(there is no prefix KV to publish)"
            )
    if kv_fetch_through and sset.pool is not None:
        sset.pool.kv_fetch_through = True
        if not prefix_cache:
            logging.getLogger("modelx.serve").warning(
                "--kv-fetch-through is inert without --prefix-cache "
                "(there is no prefix cache to install into)"
            )
    if evict_idle and not hbm_budget_bytes:
        logging.getLogger("modelx.serve").warning(
            "--evict-idle is inert without --hbm-budget-bytes "
            "(eviction only runs to fit a load under the budget)"
        )
    if publish_outbox_dir and not (publish_programs or publish_kv):
        logging.getLogger("modelx.serve").warning(
            "--publish-outbox-dir is inert without --publish-programs or "
            "--publish-kv (only derived-artifact publishes spool through "
            "the outbox)"
        )
    if state_spool_dir and not disk_state_budget_bytes:
        logging.getLogger("modelx.serve").warning(
            "--state-spool-dir is inert without --disk-state-budget-bytes "
            "(nothing spools to a 0-byte disk tier)"
        )
    httpd = serve(sset, listen=listen,  # starts serving 503s while loading
                  access_log=access_log,
                  access_log_max_bytes=access_log_max_bytes)
    stats = sset.load_all(concurrent=concurrent_load)
    logging.getLogger("modelx.serve").info("models loaded: %s", stats)
    stop = threading.Event()
    abort = threading.Event()  # SIGINT: skip/cut short any drain window

    def _on_signal(num, _frame):
        if num == signal.SIGINT:
            abort.set()
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()
    # graceful drain: flip /healthz to 503 so the load balancer stops
    # routing here, give in-flight requests the drain window, then stop.
    # Only for SIGTERM (the LB-managed path); an interactive Ctrl-C —
    # whether it started the shutdown or lands MID-drain — exits now
    # (an Event wait, unlike time.sleep, isn't resumed after the handler)
    sset.draining = True
    if not abort.is_set() and drain_grace > 0:
        # coordinated drain: admission is off (ready is False -> /healthz
        # 503 "draining"), so the in-flight count only falls. The fleet
        # router sees DRAINING and proactively continues this pod's live
        # streams on other pods (token-exact resume), so streams hand off
        # instead of running to completion here. Exit as soon as the pod
        # is idle; the grace bound caps a stuck stream.
        log = logging.getLogger("modelx.serve")
        log.info("draining: waiting up to %.0fs for %d in-flight "
                 "request(s)", drain_grace, sset.inflight)
        deadline = time.monotonic() + drain_grace
        while sset.inflight > 0 and time.monotonic() < deadline:
            if abort.wait(timeout=0.05):
                break  # Ctrl-C mid-drain: exit now
        if sset.inflight > 0:
            log.warning("drain grace expired with %d request(s) still "
                        "in flight", sset.inflight)
    elif not abort.is_set() and drain_seconds > 0:
        logging.getLogger("modelx.serve").info(
            "draining for %.0fs before shutdown", drain_seconds)
        abort.wait(timeout=drain_seconds)
    # snapshot: requests during the drain window may still lazily create
    # batchers while this iterates
    for batcher in list(sset.batchers.values()):
        batcher.close()
    for cb in list(sset.cbatchers.values()):
        cb.close()
    if sset.pool is not None:
        # pending outbox entries stay on disk; the next generation's
        # drainer picks them up (that persistence is the point)
        sset.pool.stop_kv()
        sset.pool.stop_outbox()
    httpd.shutdown()


if __name__ == "__main__":
    main()
