"""`modelx-serve` console entrypoint: the serving container's command
(referenced by dl/podspec.py's generated pod spec)."""

from __future__ import annotations

import logging
import signal
import threading

import click

from modelx_tpu.dl.serve import ModelServer, serve


@click.command("modelx-serve")
@click.option("--model-dir", required=True, help="volume with *.safetensors (from modelx dl)")
@click.option("--mesh", default="", help='mesh spec, e.g. "dp=1,tp=8" (default: dp over all devices)')
@click.option("--dtype", default="bfloat16", type=click.Choice(["bfloat16", "float32"]))
@click.option("--listen", default=":8000")
@click.option("--max-seq-len", default=2048, type=int)
def main(model_dir: str, mesh: str, dtype: str, listen: str, max_seq_len: int) -> None:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    server = ModelServer(model_dir, mesh_spec=mesh, dtype=dtype, max_seq_len=max_seq_len)
    httpd = serve(server, listen=listen)  # starts serving 503s while loading
    stats = server.load()
    logging.getLogger("modelx.serve").info("model loaded: %s", stats)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    httpd.shutdown()


if __name__ == "__main__":
    main()
