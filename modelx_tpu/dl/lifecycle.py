"""Dynamic model lifecycle: runtime load / drain / unload / evict.

modelx's whole point is that models are registry objects materialized at
deploy time — yet the serving container used to fix its model set at boot:
adding, swapping, or retiring a model meant a pod restart and a cold TTFT.
This module is the missing scheduler (the ServerlessLLM-style runtime half;
PR 1 built the fast-materialization half): a ``ModelPool`` owns every
``ModelServer`` behind a ``ServerSet`` and drives each through an explicit
state machine

    PULLING -> LOADING -> READY -> DRAINING -> UNLOADED
                  \\-> FAILED (slot retryable)

exposed on the serving HTTP surface as

    GET    /admin/models          every entry's state + accounting
    POST   /admin/models          {"name", "ref"|"model_dir", "wait"?}
                                  pull a registry ref (blob-cache-warm when
                                  the node has served it before) and load it
                                  while traffic to other models is live
    DELETE /admin/models/{name}   drain in-flight requests, stop admission,
                                  free device + host state

(dl/serve.py routes them, behind the admin bearer-token filter).

Request routing during transitions is typed (dl/serving_errors.py): a model
that is PULLING/LOADING answers 503 + ``Retry-After``, DRAINING answers
409, FAILED answers 503 with the reason, UNLOADED/unknown answers 404 —
identically on the native and OpenAI surfaces.

HBM budget: every load first ESTIMATES its device footprint (manifest
``.safetensors`` blob sizes for a registry ref, file sizes for a local
dir — both ≈ parameter bytes; int8 loads over-reserve, the safe direction)
and reserves it against ``hbm_budget_bytes``. A load that cannot fit is
refused with 507 — unless ``evict_idle`` is set, in which case READY
models with no in-flight requests are LRU-evicted (least-recently-used
first) until the load fits. Reservations tighten to the measured
``load_bytes`` once a model lands READY.

No reference equivalent (the reference stores models; it cannot serve
them, let alone schedule them) — this turns the sidecar into the
serverless-style multi-tenant node the ROADMAP's north star asks for.
"""

from __future__ import annotations

import glob
import logging
import os
import shutil
import threading
import time

from modelx_tpu.utils import devmem

logger = logging.getLogger("modelx.lifecycle")

# -- lifecycle states ---------------------------------------------------------
PULLING = "PULLING"      # registry blobs streaming to the staging dir
LOADING = "LOADING"      # safetensors streaming onto the mesh + compiling
READY = "READY"          # serving traffic
DRAINING = "DRAINING"    # admission stopped; in-flight requests finishing
UNLOADED = "UNLOADED"    # freed; the name 404s, the entry records history
FAILED = "FAILED"        # load crashed; slot retryable via re-POST

# states that hold (or are about to hold) device memory: their reservations
# count against the HBM budget
_RESERVING = (PULLING, LOADING, READY, DRAINING)


class PoolError(Exception):
    """An admin-surface refusal with its HTTP status (the serving layer
    maps it 1:1 to a JSON error body). ``headers`` ride onto the
    response: a 507 whose pressure could clear carries ``Retry-After``
    (demotion/drain could make room), a hard refusal carries none."""

    def __init__(self, status: int, message: str,
                 headers: dict | None = None) -> None:
        super().__init__(message)
        self.status = status
        self.headers = dict(headers or {})


class ModelEntry:
    """One named model's lifecycle record. Lives for the pool's lifetime
    (an UNLOADED/FAILED entry keeps its counters and is re-usable: a
    re-POST of the same name retries into the same slot)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.state = LOADING
        self.state_since = time.monotonic()
        self.server = None          # ModelServer once LOADING starts
        self.error: str | None = None
        self.ref = ""               # registry uri when pulled at runtime
        self.model_dir = ""
        self.hbm_reserved_bytes = 0
        self.loads_total = 0
        self.evictions_total = 0
        self.drain_seconds: float | None = None  # last drain's duration
        self.inflight = 0
        self.last_used = time.monotonic()
        self._staged = False        # model_dir is pool-owned (safe to rm)
        self.tier_key = ""          # content digest into the tier store
        # which rung of the degradation ladder materialized the bytes:
        # "registry" | "mirror" | "cache" (offline) | "tier" | "dir"
        self.load_source = ""

    def to(self, state: str, error: str | None = None) -> None:
        self.state = state
        self.state_since = time.monotonic()
        self.error = error

    def snapshot(self) -> dict:
        """JSON-safe view for GET /admin/models and /metrics."""
        snap = {
            "state": self.state,
            "state_age_s": round(time.monotonic() - self.state_since, 3),
            "hbm_reserved_bytes": int(self.hbm_reserved_bytes),
            "loads_total": self.loads_total,
            "evictions_total": self.evictions_total,
            "inflight": self.inflight,
        }
        if self.ref:
            snap["ref"] = self.ref
        if self.error:
            snap["error"] = self.error
        if self.load_source:
            snap["load_source"] = self.load_source
        if self.drain_seconds is not None:
            snap["drain_seconds"] = round(self.drain_seconds, 3)
        return snap


def estimate_dir_bytes(model_dir: str) -> int:
    """Device-footprint estimate for a local checkpoint dir: the summed
    ``*.safetensors`` file sizes (header overhead is noise next to the
    tensor data, which loads byte-for-byte onto the mesh)."""
    total = 0
    for path in glob.glob(os.path.join(model_dir, "*.safetensors")):
        try:
            total += os.path.getsize(path)
        except OSError:
            pass
    return total


def estimate_ref_bytes(uri: str) -> int:
    """Device-footprint estimate for a registry ref, read from the
    manifest's ``.safetensors`` blob sizes — BEFORE any byte is pulled, so
    an over-budget load is refused for free."""
    from modelx_tpu.client.reference import parse_reference

    ref = parse_reference(uri)
    client = ref.client(quiet=True)
    manifest = client.get_manifest(ref.repository, ref.version)
    return sum(
        (b.size or 0) for b in manifest.blobs
        if b.name.endswith(".safetensors")
    )


class ModelPool:
    """Owns the lifecycle of every model behind a ServerSet.

    The pool is ALWAYS attached (dl/serve.ServerSet creates one): it tracks
    states, in-flight counts, and per-model metrics for the boot-time model
    set too. The admin load/unload surface additionally requires
    ``allow_admin_load`` (--allow-admin-load)."""

    # how long DELETE waits for in-flight requests before forcing the free
    DEFAULT_DRAIN_TIMEOUT_S = 30.0

    def __init__(self, sset, hbm_budget_bytes: int = 0, evict_idle: bool = False,
                 allow_admin_load: bool = False, staging_root: str = "",
                 drain_timeout_s: float = DEFAULT_DRAIN_TIMEOUT_S,
                 blob_cache=None, mesh=None,
                 host_state_budget_bytes: int = 0,
                 disk_state_budget_bytes: int = 0,
                 state_spool_dir: str = "") -> None:
        self.sset = sset
        self.hbm_budget_bytes = int(hbm_budget_bytes)
        # the serving mesh (ServerSet's shared mesh): --hbm-budget-bytes is
        # PER-DEVICE HBM, and on a weight-sharding mesh (tp/ep/pp/fsdp)
        # each device holds only 1/factor of a model's bytes — checkpoint
        # file sizes and load_bytes are divided by this before they meet
        # the budget. dp/sp replicate weights, so a dp-only mesh keeps
        # factor 1 and every pre-mesh deployment budgets exactly as before.
        self.mesh = mesh
        self.weight_shard_factor = 1
        if mesh is not None:
            from modelx_tpu.parallel.mesh import weight_shard_factor

            self.weight_shard_factor = max(1, weight_shard_factor(mesh))
        self.evict_idle = bool(evict_idle)
        self.allow_admin_load = bool(allow_admin_load)
        self.staging_root = staging_root
        # the local blob cache the pull path tees through (None = the
        # process default, dl/blob_cache.configure_default / --blob-cache-dir)
        self.blob_cache = blob_cache
        # --publish-programs: after a ref-based load reaches READY, export
        # this pod's compiled surface to the model's registry version so
        # the next puller boots warm (dl/program_store.py)
        self.publish_programs = False
        # --publish-kv (ISSUE 20): sweep live prefix caches for entries
        # hot enough to ship to the registry as kv bundles; --kv-fetch-
        # through consults the registry on a prefix-cache miss
        self.publish_kv = False
        self.kv_publish_threshold = 2
        self.kv_fetch_through = False
        self.kv_publisher = None
        self._kv_fetchers: dict = {}
        self.drain_timeout_s = float(drain_timeout_s)
        # pool-level flight recorder (ISSUE 18): tier promotions and
        # demotions, OOM shed-and-retry — the lifecycle counterpart of the
        # engines' rings, served under the same /debug/flightrec surface
        from modelx_tpu.utils.flightrec import FlightRecorder

        self.flightrec = FlightRecorder(capacity=256)
        # durable publish outbox (PR 19): when attached, program-bundle
        # publishes spool to disk and a background drainer pushes them —
        # a registry outage never blocks or fails a load
        self.outbox = None
        self.outbox_drainer = None
        # control-plane transitions (ok|degraded|offline) land on this
        # pool's recorder — the pod-level view /debug/flightrec serves
        from modelx_tpu.dl import manifest_cache as _mc

        _mc.health().recorder = self.flightrec
        # multi-tier live state (dl/tiers.py): demoted models' params
        # staged in bounded host RAM / local disk so a re-load is a tier
        # promotion, not a re-pull. Both budgets 0 (the default) keeps
        # the store inert and the pool byte-identical to before.
        from modelx_tpu.dl import tiers as tiers_mod

        mesh_key = ""
        if mesh is not None:
            from modelx_tpu.parallel.mesh import mesh_str

            mesh_key = mesh_str(mesh)
        self.tiers = tiers_mod.TierStore(
            host_budget_bytes=host_state_budget_bytes,
            disk_budget_bytes=disk_state_budget_bytes,
            spool_root=state_spool_dir, mesh_key=mesh_key,
            recorder=self.flightrec,
        )
        self._lock = threading.RLock()
        self._idle = threading.Condition(self._lock)  # inflight hit zero
        self.entries: dict[str, ModelEntry] = {}
        self.stats = {"loads_total": 0, "evictions_total": 0,
                      "load_failures_total": 0, "unloads_total": 0}
        for name, server in sset.servers.items():
            e = ModelEntry(name)
            e.server = server
            e.model_dir = server.model_dir
            self.entries[name] = e

    def attach_outbox(self, spool_dir: str, max_entries: int | None = None,
                      max_bytes: int | None = None,
                      backoff_s: float | None = None,
                      start: bool = True) -> None:
        """Enable the durable publish outbox (``--publish-outbox-dir``):
        program publishes enqueue to the on-disk spool and the background
        drainer replays them through the registry with backoff. Pending
        entries from a previous process generation drain too — that is
        the restart-durability the chaos drill asserts."""
        from modelx_tpu.dl import kv_store
        from modelx_tpu.dl import outbox as outbox_mod
        from modelx_tpu.dl import program_store

        kwargs = {}
        if max_entries is not None:
            kwargs["max_entries"] = max_entries
        if max_bytes is not None:
            kwargs["max_bytes"] = max_bytes
        self.outbox = outbox_mod.Outbox(spool_dir, **kwargs)

        dkwargs = {"recorder": self.flightrec}
        if backoff_s is not None:
            dkwargs["backoff_s"] = backoff_s
        self.outbox_drainer = outbox_mod.Drainer(self.outbox, **dkwargs)
        # one spool, two artifact kinds: each entry replays through its
        # own publisher (meta-less pre-upgrade entries default "programs")
        self.outbox_drainer.register_handler(
            "programs", lambda _k, ref, data: program_store.publish_bundle(ref, data))
        self.outbox_drainer.register_handler(
            kv_store.OUTBOX_KIND,
            lambda _k, ref, data: kv_store.publish_bundle(ref, data))
        if start:
            self.outbox_drainer.start()

    def stop_outbox(self) -> None:
        if self.outbox_drainer is not None:
            self.outbox_drainer.stop()

    # -- prefix-KV publish / fetch-through (ISSUE 20) -------------------------

    def attach_kv_publisher(self, threshold: int | None = None,
                            interval_s: float = 5.0, start: bool = True) -> None:
        """Enable the prefix-KV publisher (``--publish-kv``): a background
        sweep bundles hot PrefixKVCache entries of every ref-loaded model
        and hands them to the outbox (kind ``"kvcache"``) when one is
        attached, or publishes directly otherwise."""
        from modelx_tpu.dl import kv_store

        if threshold is not None:
            self.kv_publish_threshold = max(1, int(threshold))
        self.publish_kv = True

        def targets():
            with self._lock:
                return [
                    (e.ref, e.server) for e in self.entries.values()
                    if e.ref and e.server is not None
                    and self._effective_state(e) in (READY, DRAINING)
                ]

        def sink(ref: str, data: bytes) -> None:
            if self.outbox is not None:
                if not self.outbox.enqueue(kv_store.OUTBOX_KIND, ref, data):
                    raise RuntimeError("outbox refused kv bundle")
                if self.outbox_drainer is not None:
                    self.outbox_drainer.kick()
            else:
                kv_store.publish_bundle(ref, data)
            self.flightrec.record("kv.publish_enqueued", ref=ref,
                                  bytes=len(data))

        self.kv_publisher = kv_store.KVPublisher(
            targets, sink, threshold=self.kv_publish_threshold,
            interval_s=interval_s,
        )
        if start:
            self.kv_publisher.start()

    def stop_kv(self) -> None:
        if self.kv_publisher is not None:
            self.kv_publisher.stop()
        with self._lock:
            fetchers = list(self._kv_fetchers.values())
            self._kv_fetchers.clear()
        for f in fetchers:
            f.stop()

    def _per_device(self, total_bytes: int) -> int:
        """Per-device footprint of ``total_bytes`` of weights on this
        pool's mesh (ceiling division — budgets must never round a
        footprint down to a free lunch)."""
        f = self.weight_shard_factor
        return int(total_bytes) if f <= 1 else -(-int(total_bytes) // f)

    # -- state transitions driven by ServerSet.load_all -----------------------

    def mark_loading(self, name: str) -> None:
        with self._lock:
            e = self.entries.get(name)
            if e is not None:
                e.to(LOADING)

    def mark_ready(self, name: str) -> None:
        with self._lock:
            e = self.entries.get(name)
            if e is None:
                return
            e.to(READY)
            e.loads_total += 1
            self.stats["loads_total"] += 1
            if e.server is not None:
                e.hbm_reserved_bytes = self._per_device(
                    e.server.stats.get("load_bytes", 0) or 0
                ) or e.hbm_reserved_bytes
            e.last_used = time.monotonic()

    def mark_failed(self, name: str, reason: str) -> None:
        with self._lock:
            e = self.entries.get(name)
            if e is None:
                return
            e.to(FAILED, error=reason)
            e.hbm_reserved_bytes = 0
            self.stats["load_failures_total"] += 1
            server = e.server
        if server is not None:
            # the crashed load may have landed SOME shards on the mesh;
            # the reservation above just went to zero, so those partial
            # arrays must actually free or the budget undercounts and a
            # later load can oversubscribe real HBM. (A FAILED boot
            # tenant stays in routing for /healthz's degraded report, but
            # check_admission 503s its requests before params are touched.)
            try:
                self._free_server(name, server)
            except Exception:
                logger.exception("freeing failed load of %s", name)

    # -- routing --------------------------------------------------------------

    def check_admission(self, name: str) -> None:
        """Raise the typed lifecycle error for a model that must not take
        new requests; no-op for READY (or pool-unknown: the legacy direct
        paths stay untouched). The serving layer calls this after route
        resolution and maps the exceptions to 503/409/404."""
        from modelx_tpu.dl.serving_errors import (
            ModelDrainingError, ModelFailedError, ModelLoadingError,
        )

        with self._lock:
            e = self.entries.get(name)
            if e is None:
                return
            state = self._effective_state(e)
            if state == DRAINING:
                raise ModelDrainingError(name)
            if state in (PULLING, LOADING):
                raise ModelLoadingError(
                    name, state=state.lower(), retry_after=self._retry_after(e)
                )
            if state == FAILED:
                raise ModelFailedError(name, e.error or "")
        # UNLOADED falls through: the server is gone from the ServerSet, so
        # route resolution already 404s — exactly the contract we want.

    def routing_error(self, name: str):
        """The typed error (or None) for a name that did NOT resolve to a
        live server — PULLING/LOADING entries have no server yet, so the
        404 path consults the pool before giving up."""
        from modelx_tpu.dl.serving_errors import ServingError

        try:
            self.check_admission(name)
        except ServingError as e:
            return e
        return None

    def _retry_after(self, e: ModelEntry) -> float:
        # a load that just started gets a longer back-off than one that has
        # been running a while (it is presumably nearly done)
        age = time.monotonic() - e.state_since
        return 2.0 if age < 10.0 else 1.0

    def _effective_state(self, e: ModelEntry) -> str:
        """The entry's state, reconciled with direct-load paths that bypass
        the pool (tests constructing ServerSet and calling server.load()
        themselves): a LOADING entry whose server turned ready is READY."""
        if e.state == LOADING and e.server is not None and e.server.ready:
            e.to(READY)
            if not e.hbm_reserved_bytes:
                e.hbm_reserved_bytes = self._per_device(
                    e.server.stats.get("load_bytes", 0) or 0
                )
        return e.state

    # -- in-flight accounting (drain + LRU recency) ---------------------------

    def enter(self, name: str) -> None:
        """Register one in-flight request. Raises when the model flipped
        to DRAINING (409) or all the way to UNLOADED (404) since the
        admission check — taken under the SAME lock the drain waits on,
        so a request either counts (the drain waits for it) or is
        refused; it can never slip between the two and run against a
        freed model."""
        from modelx_tpu.dl.serving_errors import (
            ModelDrainingError, ModelUnloadedError,
        )

        with self._lock:
            e = self.entries.get(name)
            if e is None:
                return
            if e.state == DRAINING:
                raise ModelDrainingError(name)
            if e.state == UNLOADED:
                # a zero-in-flight drain or eviction completed in the
                # window since check_admission: the server is freed
                raise ModelUnloadedError(name)
            e.inflight += 1
            e.last_used = time.monotonic()

    def exit(self, name: str) -> None:
        with self._lock:
            e = self.entries.get(name)
            if e is not None:
                e.inflight = max(0, e.inflight - 1)
                e.last_used = time.monotonic()
                if e.inflight == 0:
                    self._idle.notify_all()

    # -- observability --------------------------------------------------------

    def states(self) -> dict:
        """{name: snapshot} for GET /admin/models, /v1/models, /metrics."""
        with self._lock:
            out = {}
            for name, e in self.entries.items():
                st = self._effective_state(e)
                snap = e.snapshot()
                if self.tiers.enabled:
                    snap["tier"] = ("hbm" if st in _RESERVING
                                    else self.tiers.tier_of(e.tier_key)
                                    or "none")
                out[name] = snap
            return out

    def reserved_bytes(self) -> int:
        with self._lock:
            return sum(
                e.hbm_reserved_bytes for e in self.entries.values()
                if self._effective_state(e) in _RESERVING
            )

    def pool_snapshot(self) -> dict:
        snap = dict(self.stats)
        snap["hbm_reserved_bytes"] = self.reserved_bytes()
        if self.hbm_budget_bytes:
            snap["hbm_budget_bytes"] = self.hbm_budget_bytes
        snap["evict_idle"] = self.evict_idle
        if self.mesh is not None:
            from modelx_tpu.parallel.mesh import mesh_str

            snap["mesh"] = mesh_str(self.mesh)
            snap["mesh_devices"] = int(self.mesh.size)
            snap["weight_shard_factor"] = self.weight_shard_factor
        # measured occupancy next to the estimate (ISSUE 15): the
        # reservations above are FILE-SIZE guesses; this is the device's
        # own accounting, and the delta is the estimator's running error
        dm = devmem.sample()
        snap["hbm_bytes_measured"] = dm["hbm_bytes_in_use"]
        snap["hbm_measured_vs_reserved_delta"] = (
            dm["hbm_bytes_in_use"] - snap["hbm_reserved_bytes"])
        snap["hbm_measured_source"] = dm["source"]
        if self.tiers.enabled:
            snap["tiers"] = self.tiers.snapshot()
        if self.outbox is not None:
            snap["outbox"] = (self.outbox_drainer.snapshot()
                              if self.outbox_drainer is not None
                              else self.outbox.snapshot())
        if self.kv_publisher is not None:
            snap["kv_publisher"] = self.kv_publisher.snapshot()
        return snap

    def failed(self) -> dict[str, str]:
        """{name: reason} for every FAILED entry (/healthz's degraded set)."""
        with self._lock:
            return {
                name: (e.error or "load failed")
                for name, e in self.entries.items()
                if self._effective_state(e) == FAILED
            }

    # -- admin: load ----------------------------------------------------------

    def request_load(self, name: str, ref: str = "", model_dir: str = "",
                     wait: bool = False, wait_timeout_s: float = 600.0) -> dict:
        """Admit a load request: validate the name, estimate + reserve the
        HBM footprint (evicting idle models if allowed and needed), then
        run PULLING -> LOADING -> READY on a background thread. ``wait``
        blocks until the entry leaves the transient states (tests and
        synchronous tooling). Returns the entry snapshot."""
        if not self.allow_admin_load:
            raise PoolError(403, "admin model loading is disabled "
                                 "(start with --allow-admin-load)")
        if not name or not all(c.isalnum() or c in "._-" for c in name):
            raise PoolError(400, "name must be [A-Za-z0-9._-]+")
        if bool(ref) == bool(model_dir):
            raise PoolError(400, "send exactly one of ref or model_dir")

        # estimate BEFORE mutating any state: an unreachable ref or empty
        # dir must refuse cleanly, reserving nothing. The same (name,
        # size) pairs that sum to the estimate ARE the tier key material,
        # so a tier-store hit is decided before any weight byte moves.
        from modelx_tpu.dl import tiers as tiers_mod

        try:
            pairs = tiers_mod.ref_pairs(ref) if ref else tiers_mod.dir_pairs(model_dir)
        except Exception as e:
            # a registry outage with no pinned manifest is TRANSIENT: the
            # pressure clears when the control plane recovers, so it gets
            # the retryable-507 contract (PR 19) rather than the
            # deterministic 400 a bad ref earns
            from modelx_tpu import errors as _errors
            from modelx_tpu.utils.retry import retriable_status as _retriable

            if isinstance(e, _errors.ErrorInfo) and _retriable(e.http_status):
                raise PoolError(
                    507,
                    f"registry unreachable and no pinned manifest for "
                    f"{ref or model_dir!r}: {e}",
                    headers={"Retry-After": "5"},
                )
            raise PoolError(400, f"cannot estimate footprint for "
                                 f"{ref or model_dir!r}: {e}")
        est = sum(p[1] for p in pairs)
        if est <= 0:
            raise PoolError(400, f"no safetensors found under {ref or model_dir!r}")
        tier_key = self.tiers.key_for(pairs) if self.tiers.enabled else ""
        # checkpoint file sizes are TOTAL weight bytes; the budget admits
        # what one device will actually hold on this pool's mesh
        est = self._per_device(est)

        frees: list = []
        try:
            with self._lock:
                e = self.entries.get(name)
                if e is not None:
                    state = self._effective_state(e)
                    if state not in (UNLOADED, FAILED):
                        raise PoolError(409, f"model {name!r} is {state}")
                self._ensure_budget(est, loading=name, frees=frees)
                if e is None:
                    e = self.entries[name] = ModelEntry(name)
                e.server = None
                e.ref = ref
                e.model_dir = model_dir
                e.load_source = "" if ref else "dir"
                e.hbm_reserved_bytes = est
                e.drain_seconds = None
                e.tier_key = tier_key
                e.to(PULLING if ref else LOADING)
        finally:
            # evicted victims' engines/params/staging close OUTSIDE the
            # lock (their routing entries already flipped UNLOADED), and
            # even when the budget STILL refused after partial eviction —
            # those models are gone either way and must free fully
            for art in frees:
                self._finish_free(art)
        t = threading.Thread(target=self._do_load, args=(e,), daemon=True,
                             name=f"model-load-{name}")
        t.start()
        if wait:
            deadline = time.monotonic() + wait_timeout_s
            while time.monotonic() < deadline:
                with self._lock:
                    if e.state in (READY, FAILED, UNLOADED):
                        break
                time.sleep(0.02)
        with self._lock:
            return {name: e.snapshot()}

    def _measured_shortfall(self, est: int) -> bool:
        """Does the DEVICE's own accounting say ``est`` more bytes will
        not fit — regardless of what the reservation ledger believes?
        Only the accountant-backed source counts: the live-buffer census
        (CPU fallback) reports usage but no limit, so it can never veto
        a load the ledger admitted."""
        dm = devmem.sample()
        return (dm["source"] == "memory_stats"
                and est > dm["hbm_bytes_reservable"])

    def _fits(self, est: int, reserved: int) -> bool:
        if self.hbm_budget_bytes and reserved + est > self.hbm_budget_bytes:
            return False
        return not self._measured_shortfall(est)

    def _ensure_budget(self, est: int, loading: str = "",
                       frees: list | None = None) -> None:
        """Caller holds the lock. Refuse (507) or LRU-evict until ``est``
        fits BOTH the reservation ledger and the device's measured free
        HBM (utils/devmem — the ledger admits estimates; the accountant
        vetoes loads a leak or estimator error would crash); evicted
        victims' heavy artifacts land in ``frees`` for the caller to
        close after releasing the lock."""
        if not self.hbm_budget_bytes and not self._measured_shortfall(est):
            return
        reserved = self.reserved_bytes()  # RLock: safe under the lock
        if self._fits(est, reserved):
            return
        if self.evict_idle:
            # LRU order over READY models with nothing in flight; never the
            # model being (re)loaded
            victims = sorted(
                (
                    e for e in self.entries.values()
                    if self._effective_state(e) == READY
                    and e.inflight == 0 and e.name != loading
                ),
                key=lambda e: e.last_used,
            )
            for victim in victims:
                if len(self._serving_names()) <= 1:
                    # same stance as request_unload: never empty the node —
                    # if the incoming load then FAILED, nothing would serve
                    break
                # the eviction decision ran on ESTIMATES; log the
                # measured occupancy alongside so an operator can see
                # how far off the estimator was when it mattered
                dm = devmem.sample()
                logger.info(
                    "evicting idle model %s (%d bytes reserved) for the "
                    "HBM budget; device measures %d bytes in use "
                    "(source=%s, delta=%+d vs %d reserved pool-wide)",
                    victim.name, victim.hbm_reserved_bytes,
                    dm["hbm_bytes_in_use"], dm["source"],
                    dm["hbm_bytes_in_use"] - reserved, reserved,
                )
                art = self._free_entry_locked(victim, evicted=True)
                if frees is not None:
                    frees.append(art)
                reserved = self.reserved_bytes()
                if self._fits(est, reserved):
                    return
        # the 507 contract (ISSUE 18): when pressure COULD clear — busy
        # victims whose drain would free enough bytes — the refusal says
        # so and carries Retry-After; otherwise it is a hard refusal
        # (no combination of demotions makes the load fit).
        could_free = sum(
            e.hbm_reserved_bytes for e in self.entries.values()
            if self._effective_state(e) in (READY, DRAINING)
            and e.name != loading
        )
        budget = self.hbm_budget_bytes
        free_now = (budget - reserved) if budget else 0
        if budget and est <= free_now + could_free:
            raise PoolError(
                507,
                f"load needs ~{est} bytes but only {free_now} of the "
                f"{budget}-byte HBM budget is free; demoting busy models "
                f"could free {could_free} more — retry after in-flight "
                "work drains"
                + ("" if self.evict_idle else
                   " (--evict-idle is off; unload a model first)"),
                headers={"Retry-After": "2"},
            )
        if budget:
            raise PoolError(
                507,
                f"load needs ~{est} bytes but only {free_now} of the "
                f"{budget}-byte HBM budget is free, and no demotion can "
                "make room (hard refusal)"
                + ("" if self.evict_idle else
                   " (--evict-idle is off; unload a model first)"),
            )
        dm = devmem.sample()
        raise PoolError(
            507,
            f"load needs ~{est} bytes but the device measures only "
            f"{dm['hbm_bytes_reservable']} bytes reservable "
            f"(source={dm['source']})"
            + ("; demotion could make room — retry after in-flight work "
               "drains" if could_free else " (hard refusal)"),
            headers={"Retry-After": "2"} if could_free else None,
        )

    def _staging_dir(self, name: str) -> str:
        root = self.staging_root or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "modelx-pool-staging"
        )
        # per-load generation counter: a retry after FAILED must not trip
        # over a half-pulled previous attempt
        gen = int(time.monotonic() * 1e3) % 1_000_000
        return os.path.join(root, f"{name}-{gen}")

    def _do_load(self, e: ModelEntry) -> None:
        name = e.name
        try:
            # tier promotion (ISSUE 18): a host/disk hit materializes the
            # demoted state — device_put to each tensor's recorded
            # sharding — skipping the registry pull AND the safetensors
            # parse; misses fall through to the pull path unchanged
            promo = self.tiers.promote(e.tier_key) if e.tier_key else None
            if promo is not None:
                dest = self._staging_dir(name)
                if promo.sidecar_dir:
                    # tokenizer/config sidecars preserved at demotion time
                    shutil.copytree(promo.sidecar_dir, dest,
                                    dirs_exist_ok=True)
                else:
                    os.makedirs(dest, exist_ok=True)
                stale = False
                with self._lock:
                    if e.state not in (PULLING, LOADING):  # raced an unload
                        stale = True
                    else:
                        e.model_dir = dest
                        e._staged = True
                        e.load_source = "tier"
                        e.to(LOADING)
                self.flightrec.record("ladder.source", model=name,
                                      source="tier", tier=promo.tier)
                if stale:
                    shutil.rmtree(dest, ignore_errors=True)
                    return
            elif e.ref:
                dest = self._staging_dir(name)
                from modelx_tpu.dl.initializer import pull_model
                from modelx_tpu.utils import trace

                with trace.span("lifecycle.pull", model=name, ref=e.ref):
                    pulled = pull_model(e.ref, dest, cache=self.blob_cache,
                                        quiet=True)
                # which ladder rung served the bytes: registry, a read
                # mirror, or (offline) the pinned manifest + blob cache
                source = pulled.get("source", "registry")
                stale = False
                with self._lock:
                    if e.state != PULLING:  # raced an unload/retry
                        stale = True
                    else:
                        e.model_dir = dest
                        e._staged = True
                        e.load_source = source
                        e.to(LOADING)
                self.flightrec.record(
                    "ladder.source", model=name, source=source,
                    cache_hits=pulled.get("cache_hits", 0))
                if source == "cache":
                    logger.warning("model %s materialized OFFLINE from the "
                                   "pinned manifest + blob cache", name)
                if stale:
                    # the multi-GB staging rmtree runs OUTSIDE the pool
                    # lock (lint: blocking-under-lock) — other tenants'
                    # admission must not stall behind this cleanup
                    shutil.rmtree(dest, ignore_errors=True)
                    return
            from modelx_tpu.dl.serve import ModelServer

            kwargs = dict(self.sset.server_defaults)

            def attempt():
                server = ModelServer(e.model_dir, name=name, **kwargs)
                with self._lock:
                    e.server = server
                if promo is not None:
                    server.load_from_tier(promo)
                else:
                    server.load()
                return server

            try:
                server = attempt()
            except Exception as exc:
                from modelx_tpu.dl import tiers as tiers_mod

                if not tiers_mod.is_resource_exhausted(exc):
                    raise
                # XLA RESOURCE_EXHAUSTED mid-load (the ledger admitted an
                # estimate the device couldn't honor): free the partial
                # shards, demote idle victims, retry ONCE — a second
                # failure surfaces as FAILED through the normal path
                partial = e.server
                if partial is not None:
                    self._free_server(name, partial)
                freed = self.shed_idle_for_bytes(
                    e.hbm_reserved_bytes, exclude=name
                )
                self.flightrec.record("pool.oom_retry", model=name,
                                      freed_bytes=freed)
                if freed <= 0:
                    raise
                logger.warning(
                    "load of %s hit RESOURCE_EXHAUSTED; demoted %d reserved "
                    "bytes of idle state, retrying once", name, freed,
                )
                server = attempt()
            aborted = False
            with self._lock:
                if e.state != LOADING:  # raced an unload/retry mid-load
                    aborted = True
                else:
                    self.sset.add_server(name, server)
                    self.mark_ready(name)
            if aborted:
                self._free_server(name, server)  # outside the lock
                return
            logger.info("model %s loaded at runtime (%s)%s", name,
                        e.ref or e.model_dir,
                        f" [promoted from {promo.tier} tier]" if promo else "")
            if self.publish_programs and e.ref:
                # after READY, off the serving path: the model is already
                # taking traffic — a publish failure only costs the next
                # puller its warm start. With an outbox attached the
                # bundle spools to disk and the drainer pushes it, so a
                # registry outage costs nothing at all (PR 19).
                from modelx_tpu.dl import program_store
                from modelx_tpu.dl.serve import compile_cache_dir

                try:
                    if self.outbox is not None:
                        data = program_store.bundle_for_server(
                            e.ref, server, compile_cache_dir()
                        )
                        if data is not None:
                            self.outbox.enqueue("programs", e.ref, data)
                            if self.outbox_drainer is not None:
                                self.outbox_drainer.kick()
                    else:
                        program_store.publish_for_server(
                            e.ref, server, compile_cache_dir()
                        )
                except Exception:
                    logger.exception("program publish for %s failed", name)
            if self.kv_fetch_through and e.ref:
                # prefix-cache misses on this model now consult the
                # registry for published KV bundles (dl/kv_store.py) —
                # off the serving path, bounded by the cache's byte cap
                from modelx_tpu.dl import kv_store

                try:
                    fetcher = kv_store.fetcher_for_server(
                        e.ref, server, blob_cache=self.blob_cache
                    )
                    if fetcher is not None:
                        with self._lock:
                            self._kv_fetchers[name] = fetcher
                        self.flightrec.record("kv.fetch_through_attached",
                                              model=name)
                except Exception:
                    logger.exception("kv fetch-through attach for %s failed",
                                     name)
            if self.kv_publisher is not None:
                self.kv_publisher.kick()
        except BaseException as exc:  # FAILED is a state, not a crash
            from modelx_tpu.dl.manifest_cache import OfflineUnavailableError

            if isinstance(exc, OfflineUnavailableError):
                # the bottom of the ladder: nothing local can serve this
                # ref until the registry recovers — FAILED with the reason,
                # slot retryable (a re-POST after recovery succeeds)
                self.flightrec.record("ladder.offline_unavailable", model=name)
            logger.warning("runtime load of %s failed: %s", name, exc)
            staged = ""
            with self._lock:
                if e._staged and e.model_dir:
                    staged = e.model_dir
                    e.model_dir = ""
                    e._staged = False
            if staged:
                # rmtree outside the pool lock, as everywhere else
                shutil.rmtree(staged, ignore_errors=True)
            self.mark_failed(name, str(exc))

    # -- admin: unload / evict ------------------------------------------------

    def request_unload(self, name: str, wait: bool = True,
                       drain_timeout_s: float | None = None) -> dict:
        """DRAIN then free one model: admission stops immediately (new
        requests 409), in-flight requests get up to ``drain_timeout_s`` to
        finish, then device + host state frees and the entry lands
        UNLOADED (the name 404s). FAILED/UNLOADED entries delete their
        record outright (freeing the name for unrelated reuse)."""
        timeout = self.drain_timeout_s if drain_timeout_s is None else drain_timeout_s
        deleted_art = None
        with self._lock:
            e = self.entries.get(name)
            if e is None:
                raise PoolError(404, f"model {name!r} not found")
            state = self._effective_state(e)
            if state in (UNLOADED, FAILED):
                # delete the record outright — INCLUDING a FAILED boot
                # tenant's zombie server, which otherwise stays in routing
                # answering 503 forever while /healthz reads healthy
                server, batcher, cb = self.sset.remove_server(name, close=False)
                staged = e.model_dir if e._staged else ""
                del self.entries[name]
                deleted_art = (name, server, batcher, cb, staged,
                               e.tier_key, e.model_dir)
            elif state == DRAINING:
                raise PoolError(409, f"model {name!r} is already draining")
            elif state in (PULLING, LOADING):
                raise PoolError(409, f"model {name!r} is {state}; "
                                     "wait for the load to finish")
            elif len(self._serving_names()) <= 1:
                raise PoolError(409, "refusing to unload the last serving "
                                     "model (delete the pod instead)")
            else:
                e.to(DRAINING)
                t0 = time.monotonic()
        if deleted_art is not None:
            self._finish_free(deleted_art)  # outside the lock, as always
            return {name: {"state": "DELETED"}}

        def _drain() -> None:
            if self.kv_publisher is not None:
                # last call before the prefix cache frees: any entry that
                # crossed the publish threshold ships now (the outbox owns
                # it from here, so a dead registry still can't block the
                # drain) — hot shared prefixes survive the pod
                try:
                    self.kv_publisher.flush()
                except Exception:
                    logger.exception("kv flush on drain of %s failed", name)
            with self._lock:
                deadline = time.monotonic() + timeout
                while e.inflight > 0 and time.monotonic() < deadline:
                    self._idle.wait(timeout=min(0.5, timeout))
                if e.inflight > 0:
                    logger.warning(
                        "drain of %s timed out with %d in flight; freeing "
                        "anyway", name, e.inflight,
                    )
                e.drain_seconds = time.monotonic() - t0
                art = self._free_entry_locked(e, evicted=False)
            # the heavy part — engine join, device-state release, staging
            # rmtree — happens OUTSIDE the lock so the other tenants'
            # admission never stalls behind this model's teardown
            self._finish_free(art)

        if wait:
            _drain()
        else:
            threading.Thread(target=_drain, daemon=True,
                             name=f"model-drain-{name}").start()
        with self._lock:
            snap = e.snapshot()
        return {name: snap}

    def _serving_names(self) -> list[str]:
        return [
            n for n, e in self.entries.items()
            if self._effective_state(e) in (READY, DRAINING)
        ]

    def _free_entry_locked(self, e: ModelEntry, evicted: bool) -> tuple:
        """Caller holds the lock. The BOOKKEEPING half of freeing a model:
        pull it out of routing, flip the entry UNLOADED, release the HBM
        reservation. Returns the heavy artifacts (server, engines, staged
        dir, tier-demotion material) for ``_finish_free`` — run it AFTER
        releasing the lock."""
        name = e.name
        server, batcher, cb = self.sset.remove_server(name, close=False)
        staged = e.model_dir if e._staged else ""
        # demotion material: the key (computed at load admission, or
        # lazily off-lock from the dir) and the dir whose sidecars —
        # tokenizer.json, config sidecars — the tier entry preserves
        sidecar_src = e.model_dir
        tier_key = e.tier_key
        if e._staged:
            e.model_dir = ""
            e._staged = False
        e.server = None
        e.hbm_reserved_bytes = 0
        e.to(UNLOADED)
        if evicted:
            e.evictions_total += 1
            self.stats["evictions_total"] += 1
        else:
            self.stats["unloads_total"] += 1
        logger.info("model %s %s", name, "evicted" if evicted else "unloaded")
        return name, server, batcher, cb, staged, tier_key, sidecar_src

    def _finish_free(self, art: tuple) -> None:
        """The HEAVY half of freeing a model (engine thread join, device
        state release, tier demotion, params drop, staging rmtree). Never
        called under the pool lock: one tenant's teardown must not stall
        admission for the others."""
        name, server, batcher, cb, staged, tier_key, sidecar_src = art
        with self._lock:
            fetcher = self._kv_fetchers.pop(name, None)
        if fetcher is not None:
            fetcher.stop()
        if batcher is not None:
            batcher.close()
        if cb is not None:
            cb.close()
            cb.release_device_state()
        if server is not None:
            # demotion instead of discard (ISSUE 18): stage the params
            # into host RAM/disk BEFORE _free_server drops them — a later
            # load of the same content is then a tier promotion
            self._demote_server(name, server, tier_key, sidecar_src)
            self._free_server(name, server)
        if staged:
            shutil.rmtree(staged, ignore_errors=True)

    def _demote_server(self, name: str, server, tier_key: str,
                       sidecar_src: str) -> None:
        """Offer a freed server's live params to the tier store (no pool
        lock held — the device->host copy is the heavy half of eviction).
        Never raises: a failed demotion degrades to the old discard."""
        if not self.tiers.enabled or server.params is None:
            return
        try:
            if not tier_key and sidecar_src:
                # boot-time entries never went through request_load: key
                # them from the checkpoint dir at first demotion
                from modelx_tpu.dl import tiers as tiers_mod

                tier_key = self.tiers.key_for(tiers_mod.dir_pairs(sidecar_src))
            if not tier_key:
                return
            self.tiers.offer(
                tier_key, name, server.params, family=server.family,
                cfg=server.cfg, param_sds=server._param_sds,
                sidecar_src=sidecar_src,
            )
            with self._lock:
                e = self.entries.get(name)
                if e is not None and not e.tier_key:
                    e.tier_key = tier_key  # a re-POST of the dir promotes
        except Exception:
            logger.exception("demotion of %s failed; state discarded", name)

    def shed_idle_for_bytes(self, need: int, exclude: str = "") -> int:
        """Demote idle READY victims (LRU-first) until ``need`` reserved
        bytes are freed — the OOM-recovery path for loads and engine
        allocations (``need <= 0`` frees one victim). Returns the
        reserved bytes freed; 0 when nothing was sheddable. Victims are
        idle by construction, so no in-flight request is ever dropped."""
        frees: list = []
        freed = 0
        with self._lock:
            victims = sorted(
                (
                    e for e in self.entries.values()
                    if self._effective_state(e) == READY
                    and e.inflight == 0 and e.name != exclude
                ),
                key=lambda e: e.last_used,
            )
            for victim in victims:
                if len(self._serving_names()) <= 1:
                    break  # never empty the node (request_unload's stance)
                freed += victim.hbm_reserved_bytes
                frees.append(self._free_entry_locked(victim, evicted=True))
                if need <= 0 or freed >= need:
                    break
        for art in frees:
            self._finish_free(art)
        return freed

    @staticmethod
    def _free_server(name: str, server) -> None:
        """Drop every device + host reference a ModelServer holds so the
        params, AOT executables, and decoder caches become collectable
        the moment the last in-flight array fetch completes."""
        server.ready = False
        server.params = None
        server._forward_aot.clear()
        server._decoders.clear()
        server._score_progs.clear()
        server._spec_decoder = None
        server._forward = None
        if server._prefix_cache is not None:
            try:
                server._prefix_cache.clear()
            except AttributeError:
                server._prefix_cache = None
