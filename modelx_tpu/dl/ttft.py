"""One deploy-latency (TTFT) measurement in a fresh process.

``python -m modelx_tpu.dl.ttft <registry> <repo> [cache_dir]`` prints one
JSON line of stage timings for: registry request -> manifest -> (AOT
compile of the first-token program from the manifest's tensor index,
overlapped with) -> registry->HBM weight load -> first decoded token.

Clock discipline: the runtime (jax backend + device handshake + mesh) is
initialized BEFORE the clock starts — the deployment being modeled boots
the pod runtime before the model request reaches the registry, and the
metric is the registry+loader+compile path this framework owns, not
interpreter startup. Each measurement must be a fresh process: the compile
caches under ``cache_dir`` (persistent XLA cache + dl/aot_cache serialized
exports) are exactly what a pre-warmed sidecar image ships, while kernel
re-execution state is not.

Why fresh-process (measured, this rig): the tunnel relay collapses a
process's host->device bandwidth ~15x after its first program execution,
so a same-process repeat TTFT measures the collapsed link, not deploy
latency. ``first_exec_ms`` stays reported separately: it is dominated by a
flat per-process relay program-setup cost on tunneled rigs (measured
~1.7-3.7 s even for an 8-element add), while on a directly-attached TPU it
is a normal dispatch.

Reference shape being beaten: cmd/modelxdl pulls to a volume and a GPU
container then mmaps + loads + compiles serially (modelxdl.go:50-98).
"""

from __future__ import annotations

import json
import sys
import threading
import time


def measure_once(base: str, repo: str, cache_dir: str = "",
                 version: str = "v1", quantize: str | None = None,
                 blob_cache_dir: str = "", publish_programs: bool = False) -> dict:
    import jax
    import numpy as np

    from modelx_tpu.client.client import Client
    from modelx_tpu.dl import blob_cache as bc
    from modelx_tpu.dl import families as fam
    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.initializer import _blob_source
    from modelx_tpu.dl.loader import fuse_expert_tensors, load_safetensors
    from modelx_tpu.dl.serve import enable_compile_cache
    from modelx_tpu.parallel.mesh import make_mesh
    from modelx_tpu.types import AnnotationTensorIndex

    if cache_dir:
        enable_compile_cache(cache_dir)
    # local blob-cache tier (dl/blob_cache.py): warm restarts of a blob the
    # node already served load via preads, zero network reads — the
    # ttft_warm_weights_ready_ms path of the bench. Explicit dir wins;
    # otherwise the process default (MODELX_BLOB_CACHE_DIR in subprocess
    # harnesses) applies.
    blob_cache = bc.BlobCache(blob_cache_dir) if blob_cache_dir else bc.default_cache()
    # pre-clock: pod runtime boot — backend init + device handshake + mesh,
    # and the serving imports a real sidecar performs at process start
    # (measured ~1.1 s of the plan leg on a 1-core host when paid lazily)
    mesh = make_mesh(f"dp={len(jax.devices())}")
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    from modelx_tpu.dl import aot_cache  # noqa: F401
    from modelx_tpu.models import bert, gpt2, llama, mixtral  # noqa: F401
    from modelx_tpu.ops import quant  # noqa: F401

    t0 = time.monotonic()
    client = Client(base, quiet=True)
    manifest = client.get_manifest(repo, version)
    # program bundles published by an earlier pod install into the AOT
    # cache BEFORE the compile thread starts — the trace+lower is then a
    # deserialize. On-the-clock on purpose: the pull+install cost is part
    # of the TTFT being measured. Never load-bearing: any failure leaves
    # the compile path cold.
    programs_installed = 0
    if cache_dir:
        from modelx_tpu.dl import program_store

        pstats = program_store.pull_and_install(
            client, repo, manifest, cache_dir, cache=blob_cache, mesh=mesh
        )
        programs_installed = pstats["installed"] + pstats["present"]
    infos: dict = {}
    blobs = []
    for blob in manifest.blobs:
        if not blob.name.endswith(".safetensors"):
            continue
        if AnnotationTensorIndex in blob.annotations:
            parsed, off = st.parse_index_annotation(blob.annotations[AnnotationTensorIndex])
        else:
            # push omits the annotation for very large tensor indexes
            # (>256 KiB payload) — fall back to two small ranged header
            # reads, like initializer.load_to_mesh does
            import struct

            source = _blob_source(client, repo, blob, cache=blob_cache)
            try:
                (hlen,) = struct.unpack("<Q", bytes(source.read_range(0, 8)))
                parsed = st.parse_header(bytes(source.read_range(8, hlen)))
                off = 8 + hlen
            finally:
                if hasattr(source, "close"):
                    source.close()
        infos.update(parsed)
        blobs.append((blob, parsed, off))
    family = fam.detect(list(infos))
    infos = fuse_expert_tensors(infos, family.rules)
    cfg = family.infer_config(fam.abstract_params(infos))
    sds = fam.abstract_params(infos, family.rules, mesh, quantize=quantize)
    t_plan = time.monotonic()

    compiled: dict = {}

    def _compile():
        tc = time.monotonic()
        try:
            compiled["fwd"] = fam.precompile_forward(
                family, cfg, sds, prompt.shape, mesh=mesh,
                mode="argmax_last", cache_dir=cache_dir,
            )
        except BaseException as e:
            compiled["error"] = e
        compiled["secs"] = time.monotonic() - tc

    th = threading.Thread(target=_compile, daemon=True)
    th.start()
    params: dict = {}
    bytes_to_device = 0
    warm_blobs = 0
    for blob, parsed, off in blobs:
        source = _blob_source(client, repo, blob, cache=blob_cache)
        if getattr(source, "cache_state", "") == "warm":
            warm_blobs += 1
        try:
            arrays, stats = load_safetensors(
                source, mesh, family.rules, tensors=parsed, data_offset=off,
                quantize=quantize,
            )
        finally:
            if hasattr(source, "close"):
                source.close()
        params.update(arrays)
        bytes_to_device += stats.bytes_to_device
    t_load = time.monotonic()
    th.join()
    if "error" in compiled:
        raise RuntimeError("ttft precompile failed") from compiled["error"]
    fwd = compiled["fwd"]
    t_join = time.monotonic()
    first = fwd(params, jax.numpy.asarray(prompt))
    np.asarray(first)
    t_token = time.monotonic()
    programs_published = 0
    if publish_programs and cache_dir:
        # off the clock: publishing is the NEXT pod's warm start, not part
        # of this one's TTFT
        from modelx_tpu.dl import program_store

        try:
            data = program_store.build_bundle(cache_dir, mesh=mesh)
            if data is not None:
                program_store.publish(client.remote, repo, version, data)
                programs_published = program_store.bundle_program_count(data)
        except Exception as e:
            import logging

            logging.getLogger("modelx.programs").warning(
                "ttft program publish failed: %s", e
            )
    return {
        "ttft_ms": round((t_token - t0) * 1e3, 1),
        "plan_ms": round((t_plan - t0) * 1e3, 1),
        "load_ms": round((t_load - t_plan) * 1e3, 1),
        "compile_join_ms": round((t_join - t_load) * 1e3, 1),
        "first_exec_ms": round((t_token - t_join) * 1e3, 1),
        "compile_thread_ms": round(compiled["secs"] * 1e3, 1),
        "weights_ready_ms": round((t_load - t0) * 1e3, 1),
        "bytes_to_device": bytes_to_device,
        # how many safetensors blobs the local blob cache served (zero
        # network reads); == len(blobs) on a fully warm restart
        "warm_blobs": warm_blobs,
        # AOT artifacts available locally after the bundle install (pulled
        # + already-present); > 0 means the compile leg warm-started
        "programs_installed": programs_installed,
        "programs_published": programs_published,
    }


def main(argv: list[str]) -> int:
    if len(argv) < 3:
        print("usage: python -m modelx_tpu.dl.ttft <registry> <repo> "
              "[cache_dir] [quantize] [blob_cache_dir] [publish]",
              file=sys.stderr)
        return 2
    out = measure_once(
        argv[1], argv[2],
        cache_dir=argv[3] if len(argv) > 3 else "",
        quantize=(argv[4] or None) if len(argv) > 4 else None,
        blob_cache_dir=argv[5] if len(argv) > 5 else "",
        # "publish" as argv[6]: after measuring, export+attach this
        # process's compiled programs (the bench's first-pod-pays leg)
        publish_programs=(len(argv) > 6 and argv[6] == "publish"),
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
