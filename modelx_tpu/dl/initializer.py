"""Deploy-time storage initializer: the modelxdl-equivalent.

Reference parity: cmd/modelxdl/modelxdl.go:30-98 (Seldon storage-initializer
contract: ``modelxdl <uri> <dest>``): pull (a subset of) a model version into
a pod volume. The ``modelFiles`` filter bug (modelxdl.go:83 used
``filepath.SplitList`` which splits on ``:`` — nested paths never matched) is
fixed with real path-prefix matching.

TPU-native extension (the north star): ``device_put=True`` continues past the
volume — safetensors blobs stream straight onto the local device mesh via
ranged reads, and the function reports GB/s into HBM.
"""

from __future__ import annotations

import logging
import os
import time

from modelx_tpu.client.model_config import ModelConfig
from modelx_tpu.client.pull import Puller
from modelx_tpu.client.reference import parse_reference
from modelx_tpu.types import (
    AnnotationShardSpec,
    AnnotationTensorIndex,
    BlobLocationPurposeDownload,
    Manifest,
    MediaTypeModelKVCache,
    MediaTypeModelProgram,
)

logger = logging.getLogger("modelx.dl")


def filter_blobs(manifest: Manifest, model_files: list[str]) -> Manifest:
    """Keep only blobs selected by modelFiles (modelxdl.go:74-90, fixed).

    A modelFiles entry matches a blob when the blob is the entry itself or
    the entry's first path element (nested files live inside dir blobs).
    Program bundles always ride along: modelFiles names weight/tokenizer
    files, and silently filtering the compiled programs out would make a
    selective pull boot cold for no reason.
    """
    if not model_files:
        return manifest
    wanted: set[str] = set()
    for entry in model_files:
        entry = entry.strip("/")
        if entry:
            wanted.add(entry)
            wanted.add(entry.split("/", 1)[0])  # top-level dir blob
    blobs = [
        b for b in manifest.blobs
        if b.name in wanted
        or b.media_type in (MediaTypeModelProgram, MediaTypeModelKVCache)
    ]
    return Manifest(
        schema_version=manifest.schema_version,
        media_type=manifest.media_type,
        config=manifest.config,
        blobs=blobs,
        annotations=manifest.annotations,
    )


def _resolve_selected(uri: str, quiet: bool):
    """Shared ref-resolution step for the boot-time initializer AND the
    runtime pull path (both must agree on config handling and blob
    filtering): parse the reference, fetch the manifest + modelx.yaml
    sidecar, apply the ``modelFiles`` filter. Returns (ref, client,
    config, selected manifest)."""
    from modelx_tpu.utils import trace

    from modelx_tpu import errors
    from modelx_tpu.utils.retry import retriable_status

    ref = parse_reference(uri)
    client = ref.client(quiet=quiet)
    with trace.span("dl.manifest", uri=uri):
        manifest = client.get_manifest(ref.repository, ref.version)
        config = ModelConfig()
        if manifest.config.digest:
            try:
                raw = client.get_config_content(ref.repository, ref.version)
            except errors.ErrorInfo as e:
                # registry down AND no cached copy of the yaml: the config
                # only drives the modelFiles filter / mesh default, so a
                # degraded resolve pulls everything rather than failing a
                # boot the blob ladder could still serve (PR 19)
                if not retriable_status(e.http_status):
                    raise
                logger.warning(
                    "modelx.yaml for %s unavailable offline; pulling everything", uri)
                raw = b""
            if raw:
                try:
                    config = ModelConfig.from_yaml(raw)
                except ValueError:
                    logger.warning("invalid modelx.yaml in %s; pulling everything", uri)
    return ref, client, config, filter_blobs(manifest, config.model_files)


def run_initializer(
    uri: str,
    dest: str,
    device_put: bool = False,
    mesh_spec: str = "",
    quiet: bool = False,
    blob_cache_dir: str = "",
    blob_cache_max_bytes: int = 0,
) -> dict:
    """modelxdl.go:50-98 Run. Returns a summary dict (timings, GB/s).

    ``blob_cache_dir`` enables the content-addressed local blob cache
    (dl/blob_cache.py) for the ``device_put`` load path: cold loads tee
    their fetched ranges to disk, warm re-deploys of a blob the node has
    already served skip the network entirely."""
    from modelx_tpu.utils import trace

    cache = None
    if device_put:
        from modelx_tpu.dl import blob_cache as bc

        cache = (
            bc.BlobCache(blob_cache_dir, max_bytes=blob_cache_max_bytes)
            if blob_cache_dir else bc.default_cache()
        )

    t0 = time.monotonic()
    ref, client, config, selected = _resolve_selected(uri, quiet)
    with trace.span("dl.pull", blobs=len(selected.blobs)):
        Puller(client.remote, quiet=quiet).pull_blobs(ref.repository, selected, dest)
    pull_seconds = time.monotonic() - t0
    summary: dict = {
        "uri": uri,
        "dest": dest,
        "blobs": len(selected.blobs),
        "bytes": sum(b.size for b in selected.blobs),
        "pull_seconds": round(pull_seconds, 3),
    }
    if device_put:
        summary["load"] = load_to_mesh(
            client, ref.repository, selected, mesh_spec or config.serving.mesh,
            quiet=quiet, cache=cache,
        )
        if cache is not None:
            summary["blob_cache"] = dict(cache.stats)
    summary["total_seconds"] = round(time.monotonic() - t0, 3)
    return summary


def pull_model(uri: str, dest: str, cache=None, quiet: bool = True) -> dict:
    """Pull a registry ref into ``dest`` THROUGH the local blob cache —
    the runtime model-load path (dl/lifecycle.py admin loads).

    Same manifest/filter flow as ``run_initializer``, but file blobs the
    node's blob cache already holds are COPIED from it (zero network
    reads; the Puller's hash-skip then confirms them up-to-date), and
    freshly pulled blobs are admitted for the next swap — a model the
    node served before reloads blob-cache-warm (``ttft_swap_warm_ms``
    in bench.py's swap leg).

    Degradation ladder (PR 19): when the manifest came off the pinned
    cache because every registry endpoint is down (``last_source ==
    "cache"``), the pull runs fully OFFLINE — every weight/tokenizer blob
    must come digest-verified out of the blob cache, program bundles are
    skipped (a cold compile beats a failed load), and a blob the node
    doesn't hold raises :class:`~modelx_tpu.dl.manifest_cache.
    OfflineUnavailableError` for the lifecycle's retryable-507 contract."""
    from modelx_tpu.dl import blob_cache as bc
    from modelx_tpu.dl.manifest_cache import OfflineUnavailableError
    from modelx_tpu.types import MediaTypeModelDirectoryTarGz
    from modelx_tpu.utils import trace

    if cache is None:
        cache = bc.default_cache()
    t0 = time.monotonic()
    ref, client, _config, selected = _resolve_selected(uri, quiet)
    # where the manifest came from: "registry" | "mirror" | "cache" —
    # "cache" means every endpoint was down and this pull must be offline
    source = getattr(client.remote, "last_source", "registry")
    offline = source == "cache"
    os.makedirs(dest, exist_ok=True)
    file_blobs = [
        b for b in selected.blobs
        if b.digest and b.media_type != MediaTypeModelDirectoryTarGz
    ]
    cache_hits = 0
    offline_skipped_programs = 0
    if cache is not None:
        import shutil as _shutil

        for blob in file_blobs:
            hit = cache.lookup(blob.digest, expected_size=blob.size or -1)
            if hit is None:
                if offline:
                    if blob.media_type in (MediaTypeModelProgram,
                                           MediaTypeModelKVCache):
                        # no derived bundle on hand: boot cold, don't fail
                        offline_skipped_programs += 1
                        continue
                    raise OfflineUnavailableError(
                        f"registry unreachable and blob {blob.name!r} "
                        f"({blob.digest}) is not in the local blob cache")
                continue
            target = os.path.join(dest, blob.name)
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            try:
                _shutil.copyfile(hit, target)
                os.chmod(target, blob.mode or 0o644)
                cache_hits += 1
            except OSError:
                if offline:
                    raise OfflineUnavailableError(
                        f"registry unreachable and cached blob {blob.name!r} "
                        "vanished mid-copy (concurrent eviction)")
                # a racing LRU eviction unlinked the entry: the Puller
                # fetches it over the network like any miss
                pass
    if offline:
        missing_dirs = [
            b.name for b in selected.blobs
            if b.digest and b.media_type == MediaTypeModelDirectoryTarGz
        ]
        if missing_dirs:
            raise OfflineUnavailableError(
                "registry unreachable and directory blobs cannot be "
                f"materialized from the blob cache: {missing_dirs}")
        if cache is None:
            raise OfflineUnavailableError(
                "registry unreachable and no local blob cache is configured")
        logger.warning("registry unreachable; %s materialized offline from "
                       "the pinned manifest + blob cache (%d blobs)",
                       uri, cache_hits)
    else:
        with trace.span("dl.pull", blobs=len(selected.blobs)):
            Puller(client.remote, quiet=quiet).pull_blobs(ref.repository, selected, dest)
    admitted = 0
    if cache is not None:
        import shutil as _shutil

        import tempfile

        for blob in file_blobs:
            target = os.path.join(dest, blob.name)
            if not os.path.isfile(target):
                continue
            if os.path.isfile(cache.entry_path(blob.digest)):
                continue  # already cached; don't churn the LRU clock
            try:
                # unique spool per admit: concurrent runtime loads in one
                # process must not overwrite each other's in-flight copies
                fd, tmp = tempfile.mkstemp(dir=cache.root, prefix=".pull-admit-")
                os.close(fd)
                _shutil.copyfile(target, tmp)
            except OSError:
                continue
            if cache.admit_file(blob.digest, tmp) is not None:
                admitted += 1
    return {
        "uri": uri,
        "dest": dest,
        "blobs": len(selected.blobs),
        "bytes": sum(b.size for b in selected.blobs),
        "program_blobs": sum(
            1 for b in selected.blobs if b.media_type == MediaTypeModelProgram
        ),
        "kv_blobs": sum(
            1 for b in selected.blobs if b.media_type == MediaTypeModelKVCache
        ),
        "cache_hits": cache_hits,
        "cache_admitted": admitted,
        "source": source,
        "offline_skipped_programs": offline_skipped_programs,
        "pull_seconds": round(time.monotonic() - t0, 3),
    }


def load_to_mesh(client, repository: str, manifest: Manifest, mesh_spec: str,
                 quiet: bool = False, cache=None) -> dict:
    """Stream every safetensors blob of the manifest onto the local mesh.

    Uses the presigned download location when the registry offers one (bytes
    come straight from object storage) and the registry's ranged blob GET
    otherwise.
    """
    import jax

    from modelx_tpu.dl import safetensors as st
    from modelx_tpu.dl.loader import HTTPSource, load_safetensors
    from modelx_tpu.dl.sharding import decode_rules, infer_family, rules_for_family
    from modelx_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(mesh_spec) if mesh_spec else make_mesh(f"dp={len(jax.devices())}")
    out: dict = {"mesh": str(dict(mesh.shape)), "tensors": 0, "bytes": 0, "gbps": 0.0}
    total_bytes = 0
    t0 = time.monotonic()
    arrays = {}
    for blob in manifest.blobs:
        if not blob.name.endswith(".safetensors"):
            continue
        tensors = data_offset = None
        if AnnotationTensorIndex in blob.annotations:
            tensors, data_offset = st.parse_index_annotation(blob.annotations[AnnotationTensorIndex])
        if AnnotationShardSpec in blob.annotations:
            rules = decode_rules(blob.annotations[AnnotationShardSpec])
        else:
            names = list(tensors) if tensors else []
            rules = rules_for_family(infer_family(names))
        source = _blob_source(client, repository, blob, cache=cache)
        try:
            loaded, stats = load_safetensors(
                source, mesh, rules, tensors=tensors, data_offset=data_offset
            )
        finally:
            if hasattr(source, "close"):
                source.close()
        arrays.update(loaded)
        out["tensors"] += stats.tensors
        total_bytes += stats.bytes_to_device
    out["bytes"] = total_bytes
    seconds = time.monotonic() - t0
    out["seconds"] = round(seconds, 3)
    out["gbps"] = round(total_bytes / max(seconds, 1e-9) / 1e9, 6)
    out["arrays"] = arrays
    return out


def _blob_source(client, repository: str, blob, cache=None,
                 prefer_local: bool | None = None):
    """Best transport for a blob, tier by tier: a readable ``file``
    location (colocated registry / shared volume) beats everything — local
    preads cost no server round-trips and no tunnel bytes; next the local
    blob cache (dl/blob_cache.py) serves a digest-verified copy with zero
    network reads; finally the remote paths (presigned URL or the direct
    blob endpoint), teed into the cache for the next deploy.

    ``prefer_local=False`` (or env MODELX_DL_NO_LOCAL_REDIRECT=1) skips the
    colocated-file redirect — the bench/test knob that models a remote pod
    against a colocated registry."""
    from modelx_tpu.client.extension import LocationUnreachable, usable_file_path
    from modelx_tpu.dl.loader import HTTPSource, LocalFileSource

    if prefer_local is None:
        prefer_local = os.environ.get("MODELX_DL_NO_LOCAL_REDIRECT", "") not in ("1", "true")
    location = client.remote.get_blob_location(repository, blob, BlobLocationPurposeDownload)
    if prefer_local and location is not None and location.provider == "file":
        try:
            return LocalFileSource(usable_file_path(location, blob.size or -1))
        except LocationUnreachable:
            pass  # advertised for a colocated client; we're not one
    if cache is not None and blob.digest:
        hit = cache.lookup(blob.digest, expected_size=blob.size or -1)
        if hit is not None:
            try:
                src = LocalFileSource(hit)
            except OSError:
                # a concurrent admit's LRU eviction can unlink the entry
                # between lookup and open — fall through to the network
                pass
            else:
                src.cache_state = "warm"
                return src
    if location is not None and location.properties.get("url"):
        src = HTTPSource(location.properties["url"], total=blob.size)
    else:
        headers = {}
        if client.remote.authorization:
            headers["Authorization"] = client.remote.authorization
        url = f"{client.remote.registry}/{repository}/blobs/{blob.digest}"
        src = HTTPSource(url, headers=headers, total=blob.size)
    if cache is not None and blob.digest:
        src = cache.wrap(src, blob.digest, blob.size or 0)
    return src
