"""Deploy-time loading: registry -> TPU HBM.

The TPU-native replacement for the reference's modelxdl (cmd/modelxdl) and
the north-star surface of this framework (BASELINE.md): manifests carry
shard-layout annotations; the loader plans per-shard byte ranges from the
safetensors tensor index, fetches exactly those bytes (ranged HTTP GETs or
local preads), and materializes `jax.Array`s directly on a
`jax.sharding.Mesh` via `jax.make_array_from_callback` — each device shard
reads only its own bytes, so a multi-host pull moves each byte once.

Loading is multi-tier (docs/loading.md): a content-addressed local blob
cache (blob_cache.py) makes warm re-deploys network-free, and the loader
(loader.py) pipelines governor-scaled ranged fetches through a reusable
host staging pool into overlapped `jax.device_put`s.
"""
