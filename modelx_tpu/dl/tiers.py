"""Multi-tier live model state (ISSUE 18): HBM -> host RAM -> local disk.

The lifecycle pool's only answers to memory pressure used to be refusal
(507) or full eviction — and an evicted model's state was discarded
wholesale, so every swap-in re-paid pull + parse + placement
(``ttft_swap_cold_ms`` ~ 479 ms best case, seconds on a cold blob
cache). ServerlessLLM's blueprint (PAPERS.md) keeps evicted models'
state STAGED instead: demotion copies the params off the device into a
bounded host-RAM tier, host-tier overflow spools decoded tensors to a
bounded local-disk tier (next to the blob cache — same disk, same
operator budget mindset), and a later load of the same content is a
tier PROMOTION — ``jax.device_put`` straight to each tensor's recorded
``NamedSharding`` placement, no fetch, no safetensors parse.

Keying: entries are addressed by a digest over the checkpoint's sorted
``(safetensors name, size, salt)`` triples plus the pool's mesh env key
(``parallel/mesh.mesh_str``). The salt is what makes the key CONTENT
identity, not shape identity — two same-architecture models have
identical names and sizes: a registry ref salts with each blob's
manifest digest (exact content, known before any byte moves), a local
dir salts with each file's mtime_ns (same unchanged dir == same key; a
rewritten file misses, which is the safe direction). The key for a
ref-loaded model is computed at admission and carried on its pool
entry, so demote-after-ref-load -> promote-on-next-ref-load round-trips
without touching the staged dir. A mesh change invalidates every entry
(the recorded shardings belong to the old mesh).

The store is process-local live state BY DESIGN: a restart falls back
to the blob cache / registry (PR 1's fast-materialization path), which
is the durable tier. Entries are kept on promotion (weights are
immutable), so re-demoting an unchanged model is free — the next
eviction finds its key already staged and only bumps the LRU clock.

Concurrency: one small lock covers the maps and byte accounting; every
heavy step — the device->host copy, the ``.npy`` spool write/read, the
sidecar copy, directory removal — runs OUTSIDE it, guarded by per-entry
busy marks (the concurrency lint's blocking-under-lock rule enforces
the split, same as ``ModelPool._free_entry_locked``/``_finish_free``).
A demotion that crashes mid-copy (the seeded ``FaultPlan`` drill, op
``tiers.demote``) unregisters its half-built entry and deletes its
partial spool: the model is either fully tiered or fully freed, never
half.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import threading
import time

import numpy as np

logger = logging.getLogger("modelx.tiers")

__all__ = [
    "TierStore", "Promotion", "content_key", "dir_pairs", "ref_pairs",
    "is_resource_exhausted",
]

# tier names as they appear in snapshots, events, and /admin/models
HOST = "host"
DISK = "disk"

# fault-plan ops (testing/faults.py): seeded crash/latency points for the
# chaos demotion drills — from_env-gated, default off, like every seam
OP_DEMOTE = "tiers.demote"
OP_PROMOTE = "tiers.promote"
OP_SPILL = "tiers.spill"


def is_resource_exhausted(exc: BaseException | None) -> bool:
    """Is this exception (or anything in its cause/context chain) an XLA
    device-allocator failure? jax spells it differently across versions —
    ``jaxlib.xla_extension.XlaRuntimeError`` with a ``RESOURCE_EXHAUSTED``
    status string is the stable signal; match by type NAME so the check
    never imports jaxlib internals (and so tests can fabricate one)."""
    seen = 0
    while exc is not None and seen < 8:
        name = type(exc).__name__
        text = str(exc)
        if "RESOURCE_EXHAUSTED" in text:
            return True
        if name in ("XlaRuntimeError", "ResourceExhausted",
                    "ResourceExhaustedError"):
            low = text.lower()
            if "out of memory" in low or "allocat" in low:
                return True
        exc = exc.__cause__ or exc.__context__
        seen += 1
    return False


def _np_dtype(name: str):
    """Resolve a dtype name to numpy, falling back to the ml_dtypes
    extension types (bfloat16, float8_*) numpy itself can't spell."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def dir_pairs(model_dir: str) -> list[tuple[str, int, str]]:
    """Sorted ``(basename, size, mtime_ns)`` of every ``*.safetensors``
    under a local checkpoint dir — the content-key material for dir
    loads. The mtime salt means an unchanged dir re-keys identically
    while a rewritten checkpoint misses (never serve stale weights)."""
    import glob

    pairs = []
    for path in glob.glob(os.path.join(model_dir, "*.safetensors")):
        try:
            st = os.stat(path)
            pairs.append((os.path.basename(path), int(st.st_size),
                          str(st.st_mtime_ns)))
        except OSError:
            logger.debug("stat %s failed for tier key", path, exc_info=True)
    return sorted(pairs)


def ref_pairs(uri: str) -> list[tuple[str, int, str]]:
    """Sorted ``(blob name, size, digest)`` of a registry ref's
    ``.safetensors`` blobs, read from the manifest — BEFORE any weight
    byte moves, so a tier hit skips the pull entirely. The digest salt
    is exact content identity: same-shaped models with different
    weights key apart."""
    from modelx_tpu.client.reference import parse_reference

    ref = parse_reference(uri)
    client = ref.client(quiet=True)
    manifest = client.get_manifest(ref.repository, ref.version)
    return sorted(
        (b.name, int(b.size or 0), str(b.digest or ""))
        for b in manifest.blobs if b.name.endswith(".safetensors")
    )


def content_key(pairs: list[tuple[str, int, str]], mesh_key: str = "") -> str:
    """Digest of sorted ``(name, size, salt)`` triples + the mesh env
    key. Empty when there is nothing to key (no safetensors)."""
    if not pairs:
        return ""
    h = hashlib.sha256()
    h.update(mesh_key.encode())
    for name, size, salt in sorted(pairs):
        h.update(b"\0")
        h.update(name.encode())
        h.update(str(int(size)).encode())
        h.update(b"\0")
        h.update(str(salt).encode())
    return h.hexdigest()[:16]


class Promotion:
    """What ``TierStore.promote`` hands the load path: materialized host
    leaves + everything needed to rebuild the server without touching
    bytes — ``ModelServer.load_from_tier`` device_puts each leaf to its
    recorded sharding and compiles as usual."""

    __slots__ = ("key", "tier", "leaves", "treedef", "shardings", "family",
                 "cfg", "param_sds", "sidecar_dir", "nbytes")

    def __init__(self, key, tier, leaves, treedef, shardings, family, cfg,
                 param_sds, sidecar_dir, nbytes) -> None:
        self.key = key
        self.tier = tier
        self.leaves = leaves
        self.treedef = treedef
        self.shardings = shardings
        self.family = family
        self.cfg = cfg
        self.param_sds = param_sds
        self.sidecar_dir = sidecar_dir
        self.nbytes = nbytes


class _Entry:
    __slots__ = ("key", "name", "state", "treedef", "shardings", "leaves",
                 "spool_dir", "sidecar_dir", "nbytes", "family", "cfg",
                 "param_sds", "last_used", "hits", "busy", "dropped")

    def __init__(self, key: str, name: str) -> None:
        self.key = key
        self.name = name            # last model name staged under this key
        self.state = "staging"      # staging -> host -> disk (or dropped)
        self.treedef = None
        self.shardings: list = []
        self.leaves: list | None = None   # host-RAM numpy arrays
        self.spool_dir = ""               # disk tier .npy spool
        self.sidecar_dir = ""             # tokenizer/config sidecars
        self.nbytes = 0
        self.family = None
        self.cfg = None
        self.param_sds = None
        self.last_used = time.monotonic()
        self.hits = 0
        self.busy = 0               # promotions/demotions in flight
        self.dropped = False        # delete deferred until busy drains


class TierStore:
    """Bounded host-RAM + local-disk staging for demoted model state.

    ``host_budget_bytes``/``disk_budget_bytes`` bound each tier (0
    disables that tier; both 0 disables the store — ``offer`` and
    ``promote`` become no-ops and the pool behaves exactly as before).
    LRU within each tier: host overflow spills the least-recently-used
    host entry to disk, disk overflow drops the oldest spool.
    """

    def __init__(self, host_budget_bytes: int = 0, disk_budget_bytes: int = 0,
                 spool_root: str = "", mesh_key: str = "",
                 recorder=None, fault_plan=None) -> None:
        self.host_budget_bytes = int(host_budget_bytes)
        self.disk_budget_bytes = int(disk_budget_bytes)
        self.spool_root = spool_root or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "modelx-state-spool"
        )
        self.mesh_key = mesh_key
        self.recorder = recorder      # utils/flightrec.FlightRecorder or None
        if fault_plan is None:
            from modelx_tpu.testing import faults

            fault_plan = faults.from_env()
        self.fault_plan = fault_plan
        self._lock = threading.Lock()
        self._entries: dict[str, _Entry] = {}
        self._order: list[str] = []   # LRU: oldest first (rebuilt on touch)
        self.stats = {
            "host_hits": 0, "disk_hits": 0, "misses": 0,
            "demotions_host": 0, "demotions_disk": 0, "demotions_dropped": 0,
            "demotion_failures": 0, "promotions_host": 0,
            "promotions_disk": 0, "spills": 0, "spill_failures": 0,
        }

    @property
    def enabled(self) -> bool:
        return self.host_budget_bytes > 0 or self.disk_budget_bytes > 0

    def key_for(self, pairs: list[tuple[str, int]]) -> str:
        return content_key(pairs, self.mesh_key)

    def _record(self, event: str, **fields) -> None:
        if self.recorder is not None:
            self.recorder.record(event, **fields)

    def _fire(self, op: str) -> None:
        if self.fault_plan is not None:
            self.fault_plan.maybe_fail(op)

    # -- accounting (caller holds the lock) -----------------------------------

    def _tier_bytes(self, state: str) -> int:
        return sum(e.nbytes for e in self._entries.values()
                   if e.state == state)

    def _touch(self, e: _Entry) -> None:
        e.last_used = time.monotonic()

    def _lru(self, state: str, exclude: str = "") -> "_Entry | None":
        live = [e for e in self._entries.values()
                if e.state == state and not e.busy and not e.dropped
                and e.key != exclude]
        return min(live, key=lambda e: e.last_used) if live else None

    # -- demotion -------------------------------------------------------------

    def offer(self, key: str, name: str, params, *, family=None, cfg=None,
              param_sds=None, sidecar_src: str = "") -> bool:
        """Stage one model's live params into the tier ladder; called by
        the pool's free path OFF the pool lock. Returns True when the
        state landed (or was already staged). Never raises: a demotion
        failure degrades to the old discard behavior."""
        if not self.enabled or not key or params is None:
            return False
        with self._lock:
            cur = self._entries.get(key)
            if cur is not None:
                if cur.state in (HOST, DISK):
                    # weights are immutable: same key == same bytes; the
                    # existing entry just gets younger
                    self._touch(cur)
                    return True
                return False  # a demotion for this key is already staging
            e = self._entries[key] = _Entry(key, name)
        try:
            return self._demote(e, params, family, cfg, param_sds, sidecar_src)
        except BaseException as exc:
            # mid-demotion crash (injected or real): fully freed, never
            # half — unregister the entry and delete any partial spool
            self._discard_partial(e)
            with self._lock:
                self.stats["demotion_failures"] += 1
            self._record("tier.demote.failed", model=name, error=str(exc))
            logger.warning("demotion of %s to tiers failed: %s", name, exc)
            return False

    def _demote(self, e: _Entry, params, family, cfg, param_sds,
                sidecar_src: str) -> bool:
        """The heavy half of a demotion (no store lock held): fault point,
        device->host copy, sidecar preservation, then finalize under the
        lock and resolve any budget overflow."""
        self._fire(OP_DEMOTE)
        t0 = time.monotonic()
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(params)
        shardings = [getattr(leaf, "sharding", None) for leaf in leaves]
        host = [np.asarray(leaf) for leaf in leaves]
        nbytes = sum(int(a.nbytes) for a in host)
        fits_host = 0 < nbytes <= self.host_budget_bytes
        fits_disk = 0 < nbytes <= self.disk_budget_bytes
        if not fits_host and not fits_disk:
            self._discard_partial(e)
            with self._lock:
                self.stats["demotions_dropped"] += 1
            self._record("tier.demote.dropped", model=e.name, bytes=nbytes)
            return False
        sidecar = self._preserve_sidecar(e.key, sidecar_src)
        e.treedef = treedef
        e.shardings = shardings
        e.nbytes = nbytes
        e.family = family
        e.cfg = cfg
        e.param_sds = param_sds
        e.sidecar_dir = sidecar
        if fits_host:
            e.leaves = host
            spill_victims = self._finalize(e, HOST, "demotions_host")
        else:
            # straight to disk: host tier too small (or disabled). A full
            # disk (ENOSPC) drops THIS entry — the demotion path itself
            # must never crash over a spool write.
            try:
                self._fire(OP_SPILL)
                self._spool(e, host)
            except OSError as exc:
                logger.warning("disk spool of %s failed (%s); dropping entry",
                               e.name, exc)
                self._discard_partial(e)
                with self._lock:
                    self.stats["spill_failures"] += 1
                    self.stats["demotions_dropped"] += 1
                self._record("tier.spill.failed", model=e.name, bytes=nbytes,
                             error=str(exc))
                return False
            spill_victims = self._finalize(e, DISK, "demotions_disk")
        self._resolve_spills(spill_victims)
        self._record(
            "tier.demote", model=e.name, tier=e.state, bytes=nbytes,
            ms=round((time.monotonic() - t0) * 1e3, 1),
        )
        logger.info("model %s demoted to %s tier (%d bytes)",
                    e.name, e.state, nbytes)
        return True

    def _finalize(self, e: _Entry, state: str, stat: str) -> list:
        """Flip a staged entry live and collect LRU overflow victims
        (returned for the caller to resolve OFF the lock)."""
        with self._lock:
            e.state = state
            self._touch(e)
            self.stats[stat] += 1
            return self._overflow_locked(exclude=e.key)

    def _overflow_locked(self, exclude: str = "") -> list:
        """Caller holds the lock: pick (victim, action) pairs until both
        tiers fit their budgets; victims are marked busy. Actions:
        ``spill`` (host -> disk) or ``drop``."""
        plan = []
        guard = 0
        while guard < 64:
            guard += 1
            host_bytes = self._tier_bytes(HOST)
            if self.host_budget_bytes and host_bytes > self.host_budget_bytes:
                v = self._lru(HOST, exclude=exclude)
                if v is None:
                    break
                v.busy += 1
                # spill when its bytes could ever fit the disk budget,
                # else drop outright
                act = "spill" if 0 < v.nbytes <= self.disk_budget_bytes else "drop"
                if act == "spill":
                    v.state = DISK  # counts against disk from now on
                plan.append((v, act))
                continue
            disk_bytes = self._tier_bytes(DISK)
            if self.disk_budget_bytes and disk_bytes > self.disk_budget_bytes:
                v = self._lru(DISK, exclude=exclude)
                if v is None:
                    break
                v.busy += 1
                plan.append((v, "drop"))
                continue
            break
        return plan

    def _resolve_spills(self, plan: list) -> None:
        """Perform overflow actions off the lock: spool host victims to
        disk, delete dropped victims' artifacts."""
        for victim, action in plan:
            if action == "spill":
                try:
                    self._fire(OP_SPILL)
                    self._spool(victim, victim.leaves or [])
                    with self._lock:
                        victim.leaves = None
                        victim.busy -= 1
                        self.stats["spills"] += 1
                        more = self._overflow_locked()
                    self._record("tier.spill", model=victim.name,
                                 bytes=victim.nbytes)
                except BaseException as exc:
                    logger.warning("spill of %s to disk failed: %s",
                                   victim.name, exc)
                    with self._lock:
                        victim.busy -= 1
                        victim.dropped = True
                        self.stats["spill_failures"] += 1
                        more = []
                    self._reap(victim)
                    self._record("tier.spill.failed", model=victim.name,
                                 bytes=victim.nbytes, error=str(exc))
                self._resolve_spills(more)
            else:
                with self._lock:
                    victim.busy -= 1
                    victim.dropped = True
                    self.stats["demotions_dropped"] += 1
                self._reap(victim)
                self._record("tier.drop", model=victim.name,
                             bytes=victim.nbytes)

    def _spool(self, e: _Entry, host_leaves: list) -> None:
        """Write leaves as ``.npy`` files under the spool root (decoded
        tensors — a disk promote skips the safetensors parse AND the
        sharding plan, it just device_puts what it reads). Extension
        dtypes (bfloat16 etc.) don't survive ``np.save`` (they land as
        void records), so those leaves spool as raw bytes and a
        ``meta.json`` records the dtype + shape to view them back."""
        import json

        spool = os.path.join(self.spool_root, e.key, "leaves")
        os.makedirs(spool, exist_ok=True)
        meta = []
        for i, arr in enumerate(host_leaves):
            path = os.path.join(spool, f"{i:05d}.npy")
            # isbuiltin == 1 for numpy's own scalar types; ml_dtypes'
            # registered extension types report 2 and np.save mangles
            # them into void records
            raw = arr.dtype.isbuiltin != 1
            if raw:
                np.save(path, np.frombuffer(arr.tobytes(), np.uint8),
                        allow_pickle=False)
            else:
                np.save(path, arr, allow_pickle=False)
            meta.append({"dtype": arr.dtype.name, "shape": list(arr.shape),
                         "raw": raw})
        with open(os.path.join(spool, "meta.json"), "w") as f:
            json.dump(meta, f)
        e.spool_dir = spool

    def _preserve_sidecar(self, key: str, src: str) -> str:
        """Copy the checkpoint dir's small non-safetensors files
        (tokenizer.json, config sidecars) so a promotion can rebuild a
        working ModelServer after the staged dir is rmtree'd. Weight
        files are NOT copied — the tiers hold those as tensors."""
        if not src or not os.path.isdir(src):
            return ""
        dest = os.path.join(self.spool_root, key, "sidecar")
        try:
            os.makedirs(dest, exist_ok=True)
            for fn in os.listdir(src):
                if fn.endswith(".safetensors"):
                    continue
                s = os.path.join(src, fn)
                if os.path.isfile(s):
                    shutil.copy2(s, os.path.join(dest, fn))
            return dest
        except OSError as exc:
            logger.warning("sidecar preserve from %s failed: %s", src, exc)
            return ""

    def _discard_partial(self, e: _Entry) -> None:
        """Unregister a half-built entry and remove anything it spooled
        (crash-consistency: fully tiered or fully gone)."""
        with self._lock:
            self._entries.pop(e.key, None)
        shutil.rmtree(os.path.join(self.spool_root, e.key),
                      ignore_errors=True)

    def _reap(self, e: _Entry) -> None:
        """Delete a dropped entry's disk artifacts and unregister it
        (leaves are freed by losing the reference)."""
        with self._lock:
            if e.busy > 0:
                return  # the busy holder reaps on release
            self._entries.pop(e.key, None)
            e.leaves = None
        if e.spool_dir or e.sidecar_dir:
            shutil.rmtree(os.path.join(self.spool_root, e.key),
                          ignore_errors=True)

    # -- promotion ------------------------------------------------------------

    def promote(self, key: str) -> Promotion | None:
        """Materialize a staged entry for the load path (host leaves
        ready for ``jax.device_put``); None on miss. The entry STAYS in
        its tier — weights are immutable, so the next demotion of the
        same content is free."""
        if not self.enabled or not key:
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.dropped or e.state not in (HOST, DISK):
                self.stats["misses"] += 1
                return None
            e.busy += 1
            self._touch(e)
            tier = e.state
        try:
            self._fire(OP_PROMOTE)
            t0 = time.monotonic()
            if tier == HOST:
                leaves = list(e.leaves or [])
            else:
                leaves = self._unspool(e)
            promo = Promotion(
                key, tier, leaves, e.treedef, list(e.shardings), e.family,
                e.cfg, e.param_sds, e.sidecar_dir, e.nbytes,
            )
            with self._lock:
                e.hits += 1
                self.stats[f"{tier}_hits"] += 1
                self.stats[f"promotions_{tier}"] += 1
            self._record(
                "tier.promote", model=e.name, tier=tier, bytes=e.nbytes,
                ms=round((time.monotonic() - t0) * 1e3, 1),
            )
            return promo
        except BaseException as exc:
            logger.warning("promotion of %s from %s tier failed: %s",
                           e.name, tier, exc)
            return None
        finally:
            dropped = False
            with self._lock:
                e.busy -= 1
                dropped = e.dropped and e.busy == 0
            if dropped:
                self._reap(e)

    def _unspool(self, e: _Entry) -> list:
        import json

        with open(os.path.join(e.spool_dir, "meta.json")) as f:
            meta = json.load(f)
        leaves = []
        for i, m in enumerate(meta):
            arr = np.load(os.path.join(e.spool_dir, f"{i:05d}.npy"),
                          allow_pickle=False)
            if m["raw"]:
                arr = arr.view(_np_dtype(m["dtype"])).reshape(m["shape"])
            leaves.append(arr)
        return leaves

    # -- operational controls -------------------------------------------------

    def spill_host(self) -> int:
        """Push every host-tier entry to disk (bench's disk leg and a
        pre-shutdown spill). Returns how many entries moved."""
        moved = 0
        while True:
            with self._lock:
                e = self._lru(HOST)
                if e is None:
                    return moved
                e.busy += 1
            try:
                self._fire(OP_SPILL)
                self._spool(e, e.leaves or [])
                with self._lock:
                    e.state = DISK
                    e.leaves = None
                    e.busy -= 1
                    self.stats["spills"] += 1
                    plan = self._overflow_locked()
                moved += 1
                self._resolve_spills(plan)
            except BaseException as exc:
                logger.warning("spill of %s failed: %s", e.name, exc)
                with self._lock:
                    e.busy -= 1
                    e.dropped = True
                    self.stats["spill_failures"] += 1
                self._reap(e)
                self._record("tier.spill.failed", model=e.name,
                             bytes=e.nbytes, error=str(exc))

    def drop(self, key: str) -> bool:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return False
            e.dropped = True
            busy = e.busy > 0
        if not busy:
            self._reap(e)
        return True

    def tier_of(self, key: str) -> str | None:
        if not key:
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.dropped or e.state not in (HOST, DISK):
                return None
            return e.state

    def close(self) -> None:
        """Drop everything (tests + shutdown): host arrays by reference,
        spools by rmtree of the whole root-owned keyspace."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            e.leaves = None
            if e.spool_dir or e.sidecar_dir:
                shutil.rmtree(os.path.join(self.spool_root, e.key),
                              ignore_errors=True)

    def snapshot(self) -> dict:
        """Per-tier budgets/bytes/entries + hit/promotion/demotion
        counters for ``pool_snapshot()`` / ``/admin/models`` /
        ``/metrics`` (numbers only: promexp renders them as gauges)."""
        with self._lock:
            host_entries = [e for e in self._entries.values()
                            if e.state == HOST]
            disk_entries = [e for e in self._entries.values()
                            if e.state == DISK]
            snap = {
                "host": {
                    "budget_bytes": self.host_budget_bytes,
                    "bytes": sum(e.nbytes for e in host_entries),
                    "entries": len(host_entries),
                    "hits": self.stats["host_hits"],
                    "demotions": self.stats["demotions_host"],
                    "promotions": self.stats["promotions_host"],
                },
                "disk": {
                    "budget_bytes": self.disk_budget_bytes,
                    "bytes": sum(e.nbytes for e in disk_entries),
                    "entries": len(disk_entries),
                    "hits": self.stats["disk_hits"],
                    "demotions": self.stats["demotions_disk"],
                    "promotions": self.stats["promotions_disk"],
                },
                "misses": self.stats["misses"],
                "spills": self.stats["spills"],
                "demotions_dropped": self.stats["demotions_dropped"],
                "demotion_failures": self.stats["demotion_failures"],
            }
        return snap
