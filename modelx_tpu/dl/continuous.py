"""Continuous (in-flight) batching: requests join a RUNNING decode.

The window batcher (dl/serve.Batcher) coalesces only requests that arrive
within a few ms of each other; anything landing mid-decode waits for the
whole previous ragged decode. This engine removes that wait: a fixed slot
array decodes forever in ``chunk_size``-step compiled chunks, and new
requests are admitted into free slots at chunk boundaries — iteration-level
scheduling (the vLLM/Orca idea), built the TPU way:

- **Static shapes, compile-once.** One KV cache of ``[max_slots, max_len]``
  per layer lives on device for the engine's lifetime (donated through
  every step, no reallocation). One chunk program serves every mix of
  requests; per-slot prompt lengths, decode depths, and sampling controls
  are traced VECTOR inputs, never shapes. Prefills compile per 16-bucketed
  prompt length, exactly like the stream/batcher paths.
- **Admission = prefill into a fresh [1, S] cache + one
  dynamic_update_slice of that cache into the slot's rows.** The running
  batch never re-prefills, and the prefill cost is one [S]-length row copy
  per layer on top of the forward itself.
- **Idle slots decode garbage harmlessly** (same trick as the ragged
  batcher's pad rows): attention per row sees only that row's cache, so an
  idle row's tokens are discarded on the host and its cache rows are
  overwritten wholesale at the next admission.

Token-exactness: a request decoded here yields EXACTLY the tokens the same
request gets from the plain paths — greedy rows by argmax determinism, and
sampled rows because the per-row (seed, step) stream (ops/sampling.py)
depends only on the row's own request seed and decode depth, both carried
per slot. Tests assert byte-equality against ragged_greedy_generate.

No reference equivalent (the reference stores models; it cannot serve
them); this is the serving half of the BASELINE north star. Bench target:
8 concurrent clients sustain >= 0.8x the batch-8 decode throughput.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from modelx_tpu.models.decode import pad_seq_len
from modelx_tpu.utils import trace

_DONE = object()  # end-of-stream sentinel on per-request output queues


class _Row:
    """One admitted request row bound to a slot."""

    __slots__ = ("slot", "budget", "emitted", "out", "skip", "stops", "closed")

    def __init__(self, slot: int, budget: int, out: "queue.Queue",
                 stops: frozenset = frozenset()) -> None:
        self.slot = slot
        self.budget = budget
        self.emitted = 0
        self.out = out
        # the chunk scan emits each step's ENTRY carry token, so a freshly
        # admitted row's first chunk re-emits the prefill token the
        # admission already delivered — skip it once
        self.skip = 1
        self.stops = stops  # stop token ids; hit = end the row early
        # set by delivery on a stop hit (value-dependent, so it lags the
        # value-independent plan by <= 1 chunk); plan retires closed rows
        self.closed = False


class ContinuousBatcher:
    """Iteration-level scheduler over a fixed slot array.

    ``submit_row`` enqueues one prompt row; the engine thread admits it into
    a free slot at the next chunk boundary and its output queue receives
    np int32 arrays of new tokens (totalling exactly ``max_new_tokens``),
    then the ``_DONE`` sentinel. ``generate`` / ``stream`` are the blocking
    conveniences the serving layer uses.
    """

    def __init__(self, server, max_slots: int = 8, chunk_size: int = 8,
                 max_len: int = 0, prefix_cache=None) -> None:
        if server.family.decode_fns is None:
            raise ValueError(f"family {server.family.name} has no cached decode")
        self.server = server
        self.max_slots = int(max_slots)
        self.chunk_size = int(chunk_size)
        self.max_len = int(max_len) or int(server.max_seq_len)
        # models/decode.PrefixKVCache: admissions whose prompt extends a
        # stored prefix prefill only the suffix (multi-turn chat fast path)
        self.prefix_cache = prefix_cache
        self._fwd, self._init_cache = server.family.decode_fns(
            server.cfg, mesh=server.mesh
        )
        # engine-owned device state: the big cache (donated through every
        # program so HBM holds exactly one copy) + last-token vector
        self._cache = self._init_cache(self.max_slots, self.max_len)
        self._tok = jnp.zeros((self.max_slots, 1), jnp.int32)
        # host-side per-slot state (tiny vectors, traced as inputs)
        self._offsets = np.zeros(self.max_slots, np.int32)
        self._steps = np.zeros(self.max_slots, np.int32)
        self._temp = np.zeros(self.max_slots, np.float32)
        self._top_k = np.zeros(self.max_slots, np.int32)
        self._top_p = np.ones(self.max_slots, np.float32)
        self._seeds = np.zeros(self.max_slots, np.int32)
        self._use_filters = np.zeros(self.max_slots, bool)
        self._rows: dict[int, _Row] = {}  # slot -> active row
        self._free = list(range(self.max_slots))
        self._first_pending: list = []  # (row, async first-token array, done)

        # admission is ONE program (prefill + first token + insert-at-slot):
        # on a tunneled device every call costs a host round-trip, so the
        # two-call prefill-then-insert shape would double admission latency.
        # Without a prefix cache the scratch KV stays internal (no output
        # buffer materialized just to be dropped on the host).
        if prefix_cache is None:
            def _admit_nosmall(params, prompt, cache, tok, row_len, slot,
                               temp, top_k, top_p, seed):
                cache, tok, first, _small = self._admit_impl(
                    params, prompt, cache, tok, row_len, slot,
                    temp, top_k, top_p, seed,
                )
                return cache, tok, first

            self._admit_prog = jax.jit(_admit_nosmall, donate_argnums=(2, 3))
        else:
            self._admit_prog = jax.jit(self._admit_impl, donate_argnums=(2, 3))
        # prefix-hit variant: stored KV rides in as an argument (never
        # donated — the cache entry outlives the admission); trim_len is
        # static so stored entries stay bucketed to the PROMPT's bucket
        # (entries must not grow by a bucket per conversation turn)
        self._admit_cached_prog = jax.jit(
            self._admit_cached_impl, static_argnums=(12,), donate_argnums=(2, 3)
        )
        self._chunk = jax.jit(self._chunk_impl, donate_argnums=(1, 2))

        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._broken: BaseException | None = None
        self._close_lock = threading.Lock()
        self.stats = {"chunks": 0, "admitted": 0, "active_peak": 0}
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- compiled programs ----------------------------------------------------

    def _finish_admit(self, small, logits, cache, tok, last_idx, slot,
                      temp, top_k, top_p, seed):
        """Shared admit tail: sample the row's first token (step 0 of its
        sample stream, matching ragged/stream decode byte-for-byte) and
        insert the scratch cache + token into ``slot`` of the donated
        engine state. Returns (cache, tok, first, small) — ``small`` goes
        back to the host for the prefix cache."""
        from modelx_tpu.ops import sampling as sampling_ops

        idx = jnp.broadcast_to(last_idx[:, None, None], (1, 1, logits.shape[-1]))
        last = jnp.take_along_axis(logits, idx, axis=1)[:, 0, :]
        first = sampling_ops.sample(
            last.astype(jnp.float32), jax.random.PRNGKey(0), temp,
            top_k=top_k, top_p=top_p, seeds=seed, step=0,
        )

        def put(big, little):
            return jax.lax.dynamic_update_slice(
                big, little, (slot,) + (0,) * (big.ndim - 1)
            )

        cache = jax.tree_util.tree_map(put, cache, small)
        tok = jax.lax.dynamic_update_slice(tok, first[:, None], (slot, 0))
        return cache, tok, first, small

    def _admit_impl(self, params, prompt, cache, tok, row_len, slot,
                    temp, top_k, top_p, seed):
        """One program per admission: prefill the [1, S] prompt into a
        scratch cache (allocated INSIDE the jit — zeros fuse, no host
        transfer), then the shared admit tail."""
        small = self._init_cache(1, prompt.shape[1])
        logits, small = self._fwd(params, prompt, kv_cache=small, cache_offset=0)
        return self._finish_admit(small, logits, cache, tok, row_len - 1, slot,
                                  temp, top_k, top_p, seed)

    def _admit_cached_impl(self, params, suffix, cache, tok, suffix_len, plen,
                           slot, stored, temp, top_k, top_p, seed,
                           trim_len: int):
        """Prefix-hit admission: the scratch cache starts as the STORED
        prefix KV (extended with zeros for the suffix bucket) and only the
        [1, Sb] suffix block prefills, at offset ``plen``. KV values are a
        deterministic function of the token prefix, so the admitted row is
        byte-identical to a full prefill. Junk in the stored bucket past
        the real prefix is overwritten by the suffix write (each layer
        writes its k/v BEFORE attending), and junk past the suffix span
        sits beyond every query position until decode overwrites it.
        ``trim_len`` (static, = the full prompt's 16-bucket) cuts the
        scratch back down before insertion/storage."""
        sb = suffix.shape[1]
        small = jax.tree_util.tree_map(
            lambda s: jnp.concatenate(
                [s, jnp.zeros((1, sb) + s.shape[2:], s.dtype)], axis=1
            ),
            stored,
        )
        logits, small = self._fwd(params, suffix, kv_cache=small, cache_offset=plen)
        small = jax.tree_util.tree_map(lambda c: c[:, :trim_len], small)
        return self._finish_admit(small, logits, cache, tok, suffix_len - 1, slot,
                                  temp, top_k, top_p, seed)

    def _chunk_impl(self, params, cache, tok, offsets, steps, temp, top_k, top_p, seeds):
        """``chunk_size`` decode steps over ALL slots; offsets/steps are
        per-row (slots joined at different times sit at different depths).
        ``top_k``/``top_p`` arrive as None when NO active row uses filters —
        the None variant compiles without the per-step full-vocab sort the
        filters need (jit caches both variants; values are identical either
        way since 0 / 1.0 mean "off" per row)."""
        from modelx_tpu.ops import sampling as sampling_ops

        def step_fn(carry, _i):
            cache, tok, offsets, steps = carry
            logits, cache = self._fwd(params, tok, kv_cache=cache, cache_offset=offsets)
            nxt = sampling_ops.sample(
                logits[:, -1, :].astype(jnp.float32), jax.random.PRNGKey(0), temp,
                top_k=top_k, top_p=top_p, seeds=seeds, step=steps,
            )
            return (cache, nxt[:, None], offsets + 1, steps + 1), tok[:, 0]

        (cache, tok, offsets, steps), toks = jax.lax.scan(
            step_fn, (cache, tok, offsets, steps), jnp.arange(self.chunk_size)
        )
        return cache, tok, toks.T  # [max_slots, chunk_size]

    # -- engine loop ----------------------------------------------------------

    def _admit(self, item) -> None:
        ids, n, samp, out = item
        stops = frozenset(samp.get("stop_token_ids") or ())
        slot = self._free.pop()
        s = len(ids)
        temp = np.asarray([samp.get("temperature", 0.0)], np.float32)
        k_val = int(samp.get("top_k", 0))
        p_val = float(samp.get("top_p", 1.0))
        filters = k_val > 0 or p_val < 1.0
        top_k = np.asarray([k_val], np.int32) if filters else None
        top_p = np.asarray([p_val], np.float32) if filters else None
        seed = np.asarray([samp.get("seed", 0)], np.int32)
        hit = None
        if self.prefix_cache is not None:
            # fit-aware lookup: entries whose bucket + suffix bucket exceed
            # the slot cache are skipped (shorter fitting prefixes still win)
            hit = self.prefix_cache.lookup(ids, max_total=self.max_len)
        if hit is not None:
            plen, stored = hit
            suffix = ids[plen:]
            sb = pad_seq_len(len(suffix))
            block = np.zeros((1, sb), np.int32)
            block[0, : len(suffix)] = suffix
            self._cache, self._tok, first, small = self._admit_cached_prog(
                self.server.params, jnp.asarray(block), self._cache, self._tok,
                jnp.asarray([len(suffix)], np.int32), jnp.int32(plen),
                jnp.int32(slot), stored, temp, top_k, top_p, seed,
                pad_seq_len(s),
            )
        else:
            pad_s = pad_seq_len(s)
            prompt = np.zeros((1, pad_s), np.int32)
            prompt[0, :s] = ids
            admitted = self._admit_prog(
                self.server.params, jnp.asarray(prompt), self._cache, self._tok,
                jnp.asarray([s], np.int32), jnp.int32(slot), temp, top_k, top_p, seed,
            )
            if self.prefix_cache is None:
                self._cache, self._tok, first = admitted
                small = None
            else:
                self._cache, self._tok, first, small = admitted
        if self.prefix_cache is not None:
            # the scratch cache IS this prompt's prefill KV (bucketed to the
            # prompt's 16-quantum): store it so the conversation's next turn
            # prefills only its new suffix
            self.prefix_cache.put(ids, small)
        self._offsets[slot] = s
        self._steps[slot] = 1  # prefill consumed step 0
        self._temp[slot] = temp[0]
        self._top_k[slot] = k_val
        self._top_p[slot] = p_val
        self._seeds[slot] = seed[0]
        self._use_filters[slot] = filters
        row = _Row(slot, n, out, stops=stops)
        # the prefill's first token is delivered ASYNC (with the next
        # delivery batch): syncing here would serialize a full dispatch
        # round-trip per admission, where dispatching N prefills
        # back-to-back pipelines them
        row.emitted = 1
        done = row.emitted >= row.budget
        self._first_pending.append((row, first, done))
        if done:
            self._free.append(slot)
        else:
            self._rows[slot] = row
        self.stats["admitted"] += 1
        self.stats["active_peak"] = max(self.stats["active_peak"], len(self._rows))

    def _dispatch_chunk(self) -> tuple:
        """Dispatch one chunk (async) and PLAN its emissions now. Take
        counts and retirements are value-independent (budgets only), so
        scheduling runs a full chunk ahead of token delivery — the host's
        dispatch round-trip (tens of ms on a tunneled rig) overlaps the
        device decoding the chunk in flight instead of serializing with it."""
        # filters only when an ACTIVE row asked: the None variant skips the
        # per-step full-vocab sort (retired slots' stale values are garbage
        # rows whose tokens are discarded anyway)
        active = list(self._rows)
        filtered = bool(self._use_filters[active].any())
        with trace.span("continuous.chunk", active=len(self._rows)):
            # .copy() is load-bearing: jax zero-copy-aliases host numpy
            # buffers (CPU backend) and transfers lazily, while this loop
            # mutates the originals (retirement resets, next admissions)
            # possibly BEFORE the in-flight chunk reads them — each dispatch
            # gets private snapshots nobody mutates
            self._cache, self._tok, toks_dev = self._chunk(
                self.server.params, self._cache, self._tok,
                jnp.asarray(self._offsets.copy()), jnp.asarray(self._steps.copy()),
                jnp.asarray(self._temp.copy()),
                jnp.asarray(self._top_k.copy()) if filtered else None,
                jnp.asarray(self._top_p.copy()) if filtered else None,
                jnp.asarray(self._seeds.copy()),
            )
        self.stats["chunks"] += 1
        self._offsets += self.chunk_size
        self._steps += self.chunk_size
        plan = []
        for slot, row in list(self._rows.items()):
            take = min(self.chunk_size - row.skip, row.budget - row.emitted)
            row.emitted += max(take, 0)
            done = row.emitted >= row.budget
            plan.append((slot, row, row.skip, take, done))
            row.skip = 0
            if done:  # slot reuse is safe: a re-admission's cache insert is
                # data-ordered after the in-flight chunk's writes
                del self._rows[slot]
                self._free.append(slot)
                self._offsets[slot] = 0  # idle rows write harmlessly at 0
        return toks_dev, plan

    def _deliver_firsts(self) -> None:
        """Hand this iteration's admitted rows their prefill tokens. Blocks
        only on the prefills (ordered before any chunk dispatched after
        them), so N admissions pay one round-trip, not N."""
        firsts, self._first_pending = self._first_pending, []
        for row, first, done in firsts:
            first_np = np.asarray(first).reshape(1, 1)
            row.out.put(first_np)
            if row.stops and int(first_np[0, 0]) in row.stops and not done:
                row.out.put(_DONE)
                row.closed = True  # plan retires the slot next dispatch
            elif done:
                row.out.put(_DONE)

    @staticmethod
    def _deliver(pending: tuple | None) -> None:
        """Block on an in-flight chunk's tokens and hand them to waiters."""
        if pending is None:
            return
        toks_dev, plan = pending
        toks = np.asarray(toks_dev)
        for slot, row, skip, take, done in plan:
            if row.closed:
                continue  # stop token already ended the row (and its queue)
            piece = toks[slot : slot + 1, skip : skip + take] if take > 0 else None
            if piece is not None and row.stops:
                from modelx_tpu.models.decode import stop_cut

                cut = stop_cut(piece[0].tolist(), row.stops)
                if cut is not None:
                    row.out.put(piece[:, :cut])  # include the stop
                    row.out.put(_DONE)
                    row.closed = True
                    continue
            if piece is not None:
                row.out.put(piece)
            if done:
                row.out.put(_DONE)

    def _sweep_closed(self) -> None:
        """Free the slots of rows a stop token ended at delivery time —
        BEFORE admission and the next dispatch, so a waiting request takes
        the slot immediately and no dead-row chunk is dispatched."""
        for slot, row in list(self._rows.items()):
            if row.closed:
                del self._rows[slot]
                self._free.append(slot)
                self._offsets[slot] = 0

    def _loop(self) -> None:
        pending: tuple | None = None  # depth-1 pipeline: one chunk in flight
        try:
            while True:
                self._sweep_closed()
                # admit everything waiting (up to free slots); block only
                # when fully idle with nothing in flight AND no admitted
                # row still owed its (async) first token — a lone budget-1
                # request admits, frees its slot, and would otherwise hang
                # its waiter by blocking here before _deliver_firsts runs
                while True:
                    block = (not self._rows and pending is None
                             and not self._first_pending)
                    try:
                        item = self._q.get(block=block)
                    except queue.Empty:
                        break
                    if item is None:
                        self._deliver_firsts()
                        self._deliver(pending)
                        self._fail_active(RuntimeError("continuous batcher closed"))
                        return
                    if not self._free:
                        # no slot free: requeue and decode on — a retire
                        # this chunk frees a slot for it
                        self._q.put(item)
                        break
                    with trace.span("continuous.admit"):
                        self._admit(item)
                nxt = self._dispatch_chunk() if self._rows else None
                # both deliveries overlap the chunk just dispatched
                self._deliver_firsts()
                self._deliver(pending)
                pending = nxt
        except BaseException as e:  # engine death must not hang waiters
            with self._close_lock:
                # under the lock: submit_row checks _broken inside the same
                # lock before enqueueing, so no request can slip into the
                # queue after the drain below and hang forever
                self._broken = e
            self._deliver_failsafe(pending, e)
            self._fail_active(e)

    def _deliver_failsafe(self, pending: tuple | None, err: BaseException) -> None:
        """On engine death, rows in an undelivered plan (or with undelivered
        prefill tokens) were possibly already removed from _rows — fail them
        directly so their waiters don't hang."""
        for row, _first, _done in self._first_pending:
            row.out.put(err)
        self._first_pending = []
        if pending is None:
            return
        for _slot, row, _skip, _take, _done in pending[1]:
            row.out.put(err)

    def _fail_active(self, err: BaseException) -> None:
        for row in self._rows.values():
            row.out.put(err)
        self._rows.clear()
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                item[3].put(err)

    # -- public API -----------------------------------------------------------

    def submit_row(self, ids: list[int], max_new_tokens: int, samp: dict) -> "queue.Queue":
        s = len(ids)
        if s < 1:
            raise ValueError("empty prompt row")
        # + chunk_size margin: the slot keeps writing to the end of its last
        # chunk even past the budget; those positions must exist
        need = pad_seq_len(s) + max_new_tokens + self.chunk_size
        if need > self.max_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds the "
                f"engine's max_len {self.max_len} (margin {self.chunk_size})"
            )
        out: "queue.Queue" = queue.Queue()
        with self._close_lock:
            if self._closed:
                raise RuntimeError("continuous batcher closed")
            if self._broken is not None:
                # checked under the SAME lock the dying engine takes before
                # its final queue drain — a put here either precedes the
                # drain (and gets failed by it) or raises
                raise RuntimeError("continuous batcher is broken") from self._broken
            self._q.put((list(ids), int(max_new_tokens), dict(samp), out))
        return out

    def _drain_row(self, out: "queue.Queue") -> Iterator[np.ndarray]:
        while True:
            item = out.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise RuntimeError("continuous decode failed") from item
            yield item

    def generate(self, tokens: np.ndarray, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
                 seed: int = 0, stop_token_ids=None) -> np.ndarray:
        """[B, S + m], matching ModelServer.generate: rows of a multi-row
        request become independent slots with seeds seed+i (the same
        per-row streams the ragged path derives). With ``stop_token_ids``,
        every row's SLOT frees at its stop (concurrent requests stop
        starving behind rows that already finished); m is the longest
        row's emitted length, shorter rows padded by repeating their stop
        token — the serving layer's inclusive-trim cuts at the FIRST stop,
        so padding is invisible in responses."""
        tokens = np.asarray(tokens, np.int32)
        b, s = tokens.shape
        stops = list(stop_token_ids or ())
        outs = [
            self.submit_row(
                tokens[i].tolist(), max_new_tokens,
                {"temperature": temperature, "top_k": top_k, "top_p": top_p,
                 "seed": (seed + i) % (2**31), "stop_token_ids": stops},
            )
            for i in range(b)
        ]
        rows = []
        emitted = 0
        for out in outs:
            pieces = list(self._drain_row(out))
            row = np.concatenate(pieces, axis=1)
            emitted += int(row.size)
            rows.append(row)
        width = max(r.shape[1] for r in rows)
        rows = [
            r if r.shape[1] == width else np.pad(
                r, ((0, 0), (0, width - r.shape[1])), constant_values=int(r[0, -1])
            )
            for r in rows
        ]
        gen = np.concatenate(rows, axis=0)
        self.server.stats["tokens_generated"] += emitted
        return np.concatenate([tokens, gen], axis=1)

    def stream(self, tokens: np.ndarray, max_new_tokens: int = 16,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, chunk_size: int = 0,
               stop_token_ids=None) -> Iterator[np.ndarray]:
        """Single-row streaming: yields [1, k] arrays of new tokens as the
        engine decodes them (k == 1 for the prefill token, then up to the
        ENGINE's chunk size — the per-request chunk_size arg is accepted for
        interface parity and ignored). A stop-token hit ends the stream
        early and frees the slot."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.shape[0] != 1:
            raise ValueError("continuous stream is single-row")
        out = self.submit_row(
            tokens[0].tolist(), max_new_tokens,
            {"temperature": temperature, "top_k": top_k, "top_p": top_p,
             "seed": seed, "stop_token_ids": list(stop_token_ids or ())},
        )
        for piece in self._drain_row(out):
            self.server.stats["tokens_generated"] += int(piece.size)
            yield piece

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)
        self._thread.join(timeout=30)
