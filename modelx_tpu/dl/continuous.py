"""Continuous (in-flight) batching: requests join a RUNNING decode.

The window batcher (dl/serve.Batcher) coalesces only requests that arrive
within a few ms of each other; anything landing mid-decode waits for the
whole previous ragged decode. This engine removes that wait: a fixed slot
array decodes forever in ``chunk_size``-step compiled chunks, and new
requests are admitted into free slots at chunk boundaries — iteration-level
scheduling (the vLLM/Orca idea), built the TPU way:

- **Static shapes, compile-once.** One KV cache of ``[max_slots, max_len]``
  per layer lives on device for the engine's lifetime (donated through
  every step, no reallocation). One chunk program serves every mix of
  requests; per-slot prompt lengths, decode depths, and sampling controls
  are traced VECTOR inputs, never shapes. Prefills compile per 16-bucketed
  prompt length, exactly like the stream/batcher paths.
- **Paged KV (``page_size`` > 0)**: the per-layer state becomes a POOL of
  fixed-size pages plus a host-managed block table, so HBM scales with
  LIVE tokens instead of ``max_slots x max_len`` — slot count can grow
  (32+) without a quadratic HBM bill, admissions reserve their span's
  pages up front (waiting FIFO when the pool is full), retirements recycle
  them. Still one compiled chunk program: the table is a traced input.
- **Admission = prefill into a fresh [1, S] cache + one
  dynamic_update_slice of that cache into the slot's rows.** The running
  batch never re-prefills, and the prefill cost is one [S]-length row copy
  per layer on top of the forward itself.
- **Chunked prefill (``prefill_chunk`` > 0, the Sarathi-Serve idea):** a
  long prompt no longer admits as ONE monolithic prefill that stalls
  every active decode row for its whole length. Instead the prompt splits
  into fixed-size pieces (``prefill_chunk`` tokens, 16-bucketed) and the
  scheduler interleaves them with decode chunks at boundaries under a
  per-boundary token budget (``prefill_budget``): decode rows spend
  their ``chunk_size`` tokens first, then prefill pieces pack into the
  remainder (the head piece always lands so fills can't starve). A
  filling row occupies its slot but emits nothing; each piece runs
  against the slot's own cache rows at the row's running offset and the
  LAST piece samples the row's first token from its final-position
  logits (step 0 of the row's (seed, step) stream — token-exact vs the
  single-program admission). Short prompts (<= one piece) keep the
  single-program fast path; prefix-cache hits seed the filling row's
  offset so only the suffix is chunk-prefilled; in paged mode a filling
  row reserves its pages INCREMENTALLY per piece (not the whole span up
  front), so long prompts stop serializing behind the pool-full FIFO —
  a fill that cannot get its next piece's pages simply waits a boundary,
  and if every fill is page-blocked with no decode rows left to retire,
  the youngest fill is preempted back to the arrival queue (it has
  emitted nothing, so the restart is exact).
- **Idle slots decode garbage harmlessly** (same trick as the ragged
  batcher's pad rows): attention per row sees only that row's cache, so an
  idle row's tokens are discarded on the host and its cache rows are
  overwritten wholesale at the next admission.

- **Pipelined dispatch (ISSUE 7):** the chunk boundary is built so the
  host's job per boundary is ASYNCHRONOUS. Three pieces compose: (1)
  dispatch-ahead — the decode carry (cache, tok, offsets) lives on
  device, so the loop keeps up to ``pipeline_depth`` chunk programs in
  flight and starts each result's device→host copy at dispatch time
  (``copy_to_host_async``); the oldest chunk's tokens are fetched one
  boundary LATE, while a younger chunk runs, so EOS/stop/cancel/deadline
  detection lags bounded in-flight work but token values never change.
  (2) multi-chunk decode programs — ``dispatch_depth`` (0 = auto): when
  every slot is in steady decode (no admission, fill piece, or flush
  due), one program scans D x ``chunk_size`` steps, amortizing the fixed
  per-dispatch cost D-fold; depth snaps back to 1 the moment any
  boundary event is pending, and D is capped so no row's writes pass its
  validated ``_overrun`` span. (3) boundary-prep overlap — while chunks
  execute, the loop drains the submit queue and pre-computes the
  expensive admission prep (poison fingerprint, prefix-cache lookup) for
  the backlog head, so an admission boundary is "swap prepared inputs +
  dispatch", not serial host work. Per-boundary host time (minus the
  token-fetch wait) feeds a histogram surfaced as
  ``boundary_host_ms_p50/p99`` in ``snapshot()``.

Token-exactness: a request decoded here yields EXACTLY the tokens the same
request gets from the plain paths — greedy rows by argmax determinism, and
sampled rows because the per-row (seed, step) stream (ops/sampling.py)
depends only on the row's own request seed and decode depth, both carried
per slot — which also makes token sequences DISPATCH-SCHEDULE-INVARIANT:
depth-D programs and deep pipelines replay the identical (seed, step)
sequence, so pipelined output is byte-equal to serial output. Tests assert
byte-equality against ragged_greedy_generate and across dispatch depths.

No reference equivalent (the reference stores models; it cannot serve
them); this is the serving half of the BASELINE north star. Bench target:
8 concurrent clients sustain >= 0.8x the batch-8 decode throughput.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from modelx_tpu.dl.serving_errors import (
    DeadlineExceededError,
    EngineBrokenError,
    PoisonedRequestError,
    QueueFullError,
    ServingError,
)
from modelx_tpu.models.decode import SEQ_BUCKET, pad_seq_len
from modelx_tpu.testing import faults as _faults
from modelx_tpu.utils import devmem, flightrec, promexp, trace, tswheel
from modelx_tpu.utils.jax_compat import copy_to_host_async, step_trace_annotation

_DONE = object()  # end-of-stream sentinel on per-request output queues
_NO_HIT = object()  # "no memoized prefix-cache lookup" sentinel (None = a miss)


def _fingerprint(ids, n: int) -> tuple:
    """Identity of one request for poison quarantine: cheap, deterministic,
    and content-addressed (two submissions of the same prompt+budget hash
    alike whatever objects carried them)."""
    import zlib

    return (int(zlib.crc32(np.asarray(ids, np.int32).tobytes())), len(ids), int(n))


class _Ticket:
    """One submitted request: its output queue + a cancellation flag.
    ``cancel()`` (idempotent, any thread) tells the engine the consumer is
    gone — the row's slot frees at the next chunk boundary instead of
    decoding to its full budget into a queue nobody drains (ADVICE r4).
    ``deadline`` (monotonic seconds, None = none) is set at submit from the
    engine's --request-timeout CLAMPED by any per-request budget the
    transport propagated (the router's ``X-ModelX-Deadline-Ms``): the loop
    expires the request at the next chunk boundary once passed, whatever
    state it is in; ``timeout_s`` records the effective budget so the 504
    names the number that actually applied."""

    __slots__ = ("out", "cancelled", "deadline", "timeout_s", "restart",
                 "request_id", "t_submit", "t_admit", "t_first",
                 "prefill_pieces", "preempts", "resume_step")

    def __init__(self) -> None:
        self.out: "queue.Queue" = queue.Queue()
        self.cancelled = False
        self.deadline: float | None = None
        self.timeout_s: float = 0.0
        # set when a preempted fill re-enters the backlog: its exact
        # restart goes ahead of newer arrivals (re-grab livelock guard),
        # so priority-aware inserts must never cut in front of it
        self.restart = False
        # per-request phase timeline (ISSUE 13): monotonic stamps written
        # by the one thread that owns each transition — submit() on the
        # caller's thread, slot claim + first-token delivery on the engine
        # thread — so no stamp needs a lock. t_admit/t_first stick at
        # their FIRST write: a preempted fill's restart re-claims a slot
        # but the request queued only once.
        self.request_id = ""
        self.t_submit = 0.0
        self.t_admit = 0.0
        self.t_first = 0.0
        self.prefill_pieces = 0
        self.preempts = 0
        self.resume_step = 0

    def cancel(self) -> None:
        self.cancelled = True

    def timing(self) -> dict:
        """The phase breakdown this ticket observed (ms, monotonic-clock
        deltas); phases that never happened (no slot claimed, no first
        token) are simply absent, so a shed/expired request still reports
        what it DID spend."""
        t: dict = {}
        if self.t_submit and self.t_admit:
            t["queue_ms"] = round((self.t_admit - self.t_submit) * 1e3, 3)
        if self.t_admit and self.t_first:
            t["prefill_ms"] = round((self.t_first - self.t_admit) * 1e3, 3)
        if self.t_submit and self.t_first:
            t["ttft_ms"] = round((self.t_first - self.t_submit) * 1e3, 3)
        if self.prefill_pieces:
            t["prefill_pieces"] = self.prefill_pieces
        if self.preempts:
            t["preempts"] = self.preempts
        if self.resume_step:
            t["resume_step"] = self.resume_step
        return t


class _Row:
    """One admitted request row bound to a slot."""

    __slots__ = ("slot", "budget", "emitted", "ticket", "skip", "stops",
                 "closed", "seq", "greedy", "ngram", "ng_len", "tok_pending")

    def __init__(self, slot: int, budget: int, ticket: _Ticket,
                 stops: frozenset = frozenset(), seq: list | None = None,
                 greedy: bool = True) -> None:
        self.slot = slot
        self.budget = budget
        self.emitted = 0
        self.ticket = ticket
        # the chunk scan emits each step's ENTRY carry token, so a freshly
        # admitted row's first chunk re-emits the prefill token the
        # admission already delivered — skip it once
        self.skip = 1
        self.stops = stops  # stop token ids; hit = end the row early
        # set by delivery on a stop hit (value-dependent, so it lags the
        # value-independent plan by <= 1 chunk); plan retires closed rows
        self.closed = False
        # speculation bookkeeping (engine speculative_k > 0): the row's
        # full token history + a lazily built n-gram index over it
        self.seq = seq
        self.greedy = greedy
        self.ngram = None
        self.ng_len = 0
        # True when the engine's tok vector holds this row's NEXT token,
        # computed by a chunk but not yet delivered (chunks emit entry
        # carries, so the freshest token always rides in tok). The spec
        # step must emit it before verifying past it.
        self.tok_pending = False

    @property
    def out(self) -> "queue.Queue":
        return self.ticket.out


class _Fill:
    """A slot mid-chunked-prefill: the prompt lands piece by piece at
    boundaries; the row emits nothing until the last piece flips it to a
    decoding _Row. ``filled`` is the count of REAL prompt tokens whose KV
    is resident (a prefix-cache hit starts it at the stored prefix len)."""

    __slots__ = ("slot", "ids", "n", "samp", "ticket", "filled", "fp")

    def __init__(self, slot: int, ids: list, n: int, samp: dict,
                 ticket: _Ticket, filled: int = 0,
                 fp: tuple | None = None) -> None:
        self.slot = slot
        self.ids = ids
        self.n = n
        self.samp = samp
        self.ticket = ticket
        self.filled = filled
        # the request's poison-quarantine fingerprint, computed once at
        # preparation (pieces dispatch per boundary; re-hashing the whole
        # prompt per piece would be O(prompt) work on the loop's hot path)
        self.fp = fp


class ContinuousBatcher:
    """Iteration-level scheduler over a fixed slot array.

    ``submit_row`` enqueues one prompt row; the engine thread admits it into
    a free slot at the next chunk boundary and its output queue receives
    np int32 arrays of new tokens (totalling exactly ``max_new_tokens``),
    then the ``_DONE`` sentinel. ``generate`` / ``stream`` are the blocking
    conveniences the serving layer uses.
    """

    def __init__(self, server, max_slots: int = 8, chunk_size: int = 8,
                 max_len: int = 0, prefix_cache=None, page_size: int = 0,
                 max_live_tokens: int = 0, speculative_k: int = 0,
                 max_ngram: int = 3, paged_attention: str = "gather",
                 pipeline_depth: int = 2,
                 dispatch_depth: int = 0,
                 burst_window_ms: float = 1.0,
                 prefill_chunk: int = 0,
                 prefill_budget: int = 0,
                 max_queue_depth: int = 0,
                 request_timeout_s: float = 0.0,
                 supervise: bool = True,
                 restart_backoff_s: float = 0.25,
                 max_crashes: int = 5,
                 crash_window_s: float = 60.0,
                 boundary_watchdog_s: float = 0.0,
                 flight_recorder: bool = True,
                 flightrec_capacity: int = 0,
                 flight_dump_dir: str = "",
                 device_telemetry: bool = True) -> None:
        if server.family.decode_fns is None:
            raise ValueError(f"family {server.family.name} has no cached decode")
        self.server = server
        self.max_slots = int(max_slots)
        self.chunk_size = int(chunk_size)
        self.max_len = int(max_len) or int(server.max_seq_len)
        # chunked prefill: prompts longer than one piece land piece by
        # piece at boundaries instead of as one monolithic admission
        # prefill (0 = off, today's single-program admission for every
        # prompt). Pieces are 16-bucketed like every compiled prompt shape.
        self.prefill_chunk = pad_seq_len(int(prefill_chunk)) if prefill_chunk else 0
        # per-boundary token budget: decode rows spend chunk_size each
        # first, prefill pieces pack into the remainder (0 = uncapped —
        # every filling row lands one piece per boundary). The HEAD piece
        # always lands regardless, so fills can't starve under a budget
        # smaller than the decode spend.
        self.prefill_budget = int(prefill_budget)
        # prompt-lookup speculation INSIDE the engine (speculative_k > 0):
        # whenever exactly one greedy row is active, the loop swaps the
        # chunk program for a [max_slots, k+1] verify step — propose k
        # tokens from the row's own n-gram history, verify them in ONE
        # device call, accept the agreeing prefix (token-exact by argmax
        # determinism, like models/speculative.py). More than one active
        # row (or a sampled one) falls back to pipelined chunks, where
        # cross-row batching is the better use of each weight read.
        self.speculative_k = int(speculative_k)
        self.max_ngram = int(max_ngram)
        # a verify block writes up to k+1 positions past a row's offset;
        # the per-row cache span must cover whichever engine writes deepest
        self._overrun = max(self.chunk_size, self.speculative_k + 1)
        # models/decode.PrefixKVCache: admissions whose prompt extends a
        # stored prefix prefill only the suffix (multi-turn chat fast path)
        self.prefix_cache = prefix_cache
        self._fwd, self._init_cache = server.family.decode_fns(
            server.cfg, mesh=server.mesh
        )
        # paged chunk attention: "gather" (default) rebuilds a dense view
        # per step — bit-identical logits to every other decode path, so
        # the engine's cross-engine token-exactness guarantee holds
        # unconditionally; "in-place" reads the page pools directly
        # (ops/paged_attention.py, per-step transient = one page block —
        # the long-context/HBM-bound deployment shape) at the cost of
        # blockwise-softmax numerics: greedy matches in practice, sampled
        # rows can flip at bf16 near-boundaries (measured on v5e). The
        # operator picks the trade (--kv-attention).
        if paged_attention not in ("gather", "in-place"):
            raise ValueError(f"unknown paged_attention mode {paged_attention!r}")
        self._fwd_paged = (
            server.family.paged_decode_fns(server.cfg, mesh=server.mesh)
            if (
                page_size > 0
                and paged_attention == "in-place"
                and server.family.paged_decode_fns is not None
            )
            else None
        )
        if (
            page_size > 0
            and paged_attention == "in-place"
            and self._fwd_paged is None
        ):
            # an operator asking for in-place did so for the HBM budget;
            # a silent fallback would surface only as an OOM later
            logging.getLogger("modelx.serve").warning(
                "--kv-attention in-place: family %s has no paged decode; "
                "falling back to the dense-gather chunk (higher per-step "
                "transient HBM)", server.family.name,
            )
        # -- paged KV (page_size > 0): HBM scales with LIVE tokens ----------
        # The dense engine state is [max_slots, max_len] per layer whether a
        # slot is used or not, so slot count multiplies straight into HBM.
        # Paged mode replaces it with a POOL of fixed-size pages
        # ([num_pages, page_size, ...] per layer) plus a host-managed block
        # table [max_slots, max_len/page_size]: each admission reserves
        # exactly the pages its prompt+budget span needs and returns them at
        # retirement, so 32 slots cost the pool's token budget, not
        # 32 x max_len. Page 0 is a TRASH page no slot owns: idle table
        # entries point there, so idle rows' writes land harmlessly and
        # their reads sit beyond the causal horizon (the dense engine's
        # idle-row trick, relocated). One chunk program serves every mix of
        # lengths — the table is a traced input, never a shape.
        self.page_size = int(page_size)
        try:
            self._alloc_device_state(max_live_tokens)
        except BaseException:
            # a RESOURCE_EXHAUSTED here may leave SOME per-layer pools
            # already allocated: drop the partial tree before re-raising
            # so the caller's demote-and-retry (ServerSet.continuous_for)
            # sees those bytes actually returned to the device
            self._cache = None
            self._tok = None
            raise
        # host-side per-slot state (tiny vectors, traced as inputs)
        self._offsets = np.zeros(self.max_slots, np.int32)
        self._steps = np.zeros(self.max_slots, np.int32)
        self._temp = np.zeros(self.max_slots, np.float32)
        self._top_k = np.zeros(self.max_slots, np.int32)
        self._top_p = np.ones(self.max_slots, np.float32)
        self._seeds = np.zeros(self.max_slots, np.int32)
        self._use_filters = np.zeros(self.max_slots, bool)
        self._rows: dict[int, _Row] = {}  # slot -> active row
        self._free = list(range(self.max_slots))
        self._first_pending: list = []  # (row, async first-token array, done)
        self._filling: dict[int, _Fill] = {}  # slot -> chunk-prefilling row
        self._fill_order: list[int] = []  # fill slots, arrival order (FIFO)
        # fills preempted for pages: parked (not re-queued) until a fill
        # flips or dies, else their restart would re-grab the very pages
        # the older fill is blocked on (admit/preempt livelock)
        self._preempted: list = []
        self._last_chunk_t: float | None = None  # stall_ms_max tracking
        # -- pipelined-dispatch bookkeeping ---------------------------------
        # boundary-prep overlap memo: ticket -> (fingerprint, prefix hit),
        # computed by _overlap_prep while chunks execute, consumed (popped)
        # by _gather_prep/_prepare_admit at the admission boundary
        self._prep_memo: dict = {}
        # host copy of the device tok vector's LOOKAHEAD tokens: every
        # chunk program returns its final carry as an extra token column,
        # so the spec-mode transition reads the value from the already-
        # fetched block instead of a blocking device sync. None = stale
        # (a dispatch/admission has advanced tok since the last delivery).
        self._tok_host: np.ndarray | None = None
        from collections import deque as _deque

        # per-boundary host time (dispatch-to-dispatch gap minus the time
        # blocked fetching tokens) — snapshot() serves p50/p99 off this
        self._boundary_host_ms: "_deque[float]" = _deque(maxlen=512)
        self._sync_wait_s = 0.0  # blocking-fetch time since the last dispatch
        self._boundary_syncs = 0  # device->host syncs since the last dispatch
        self._steady = False  # True = no admission/fill/spec since dispatch
        self._tokens_in_flight = 0  # planned-but-undelivered tokens
        self._inflight_chunks = 0  # dispatched-but-unsynced chunk equivalents
        self._depth_last = 1

        # admission is ONE program (prefill + first token + insert-at-slot):
        # on a tunneled device every call costs a host round-trip, so the
        # two-call prefill-then-insert shape would double admission latency.
        # Without a prefix cache the scratch KV stays internal (no output
        # buffer materialized just to be dropped on the host). Dense and
        # paged wire identically — only the impls (and the cached variant's
        # extra page_ids arg before its static trim_len) differ.
        paged = self.page_size > 0
        admit_impl = self._admit_paged_impl if paged else self._admit_impl
        if prefix_cache is None:
            def _admit_nosmall(*args):
                return admit_impl(*args)[:3]  # drop the scratch KV output

            self._admit_prog = jax.jit(_admit_nosmall, donate_argnums=(2, 3))
        else:
            self._admit_prog = jax.jit(admit_impl, donate_argnums=(2, 3))
        # prefix-hit variant: stored KV rides in as an argument (never
        # donated — the cache entry outlives the admission); trim_len is
        # static so stored entries stay bucketed to the PROMPT's bucket
        # (entries must not grow by a bucket per conversation turn)
        self._admit_cached_prog = jax.jit(
            self._admit_cached_paged_impl if paged else self._admit_cached_impl,
            static_argnums=(13 if paged else 12,), donate_argnums=(2, 3),
        )
        # batched admission (same-bucket burst arrivals -> one program);
        # engaged only without a prefix cache — the cached path's per-row
        # scratch-KV returns would cost k x leaves slice dispatches, and
        # multi-turn conversations rarely arrive as same-instant bursts
        self._admit_many_prog = jax.jit(
            self._admit_many_paged_impl if paged else self._admit_many_impl,
            donate_argnums=(2, 3),
        )
        # ONE chunk callable for every dispatch depth: n_steps is a STATIC
        # argument (jit caches one compiled variant per depth actually
        # used), so the fault-injection seam (tests/bench wrap self._chunk)
        # and the env-gated chaos wrap below cover deep programs too
        self._chunk = jax.jit(
            self._chunk_paged_impl if paged else self._chunk_impl,
            donate_argnums=(1, 2), static_argnames=("n_steps",),
        )
        # chunked-prefill piece programs: a mid piece only advances the
        # slot's KV (no logits output -> XLA drops the lm_head matmul);
        # the flip (last) piece also samples the row's first token.
        # Compiled once per piece bucket, like every other prompt shape.
        self._piece_prog = jax.jit(
            self._piece_paged_impl if paged else self._piece_impl,
            donate_argnums=(2,),
        )
        self._piece_flip_prog = jax.jit(
            self._piece_flip_paged_impl if paged else self._piece_flip_impl,
            donate_argnums=(2, 3),
        )
        # prefix-hit fill seeding: copy a stored prefix KV into the slot's
        # rows/pages so only the suffix chunk-prefills (stored entry never
        # donated — it outlives the admission)
        self._seed_prog = jax.jit(
            self._seed_paged_impl if paged else self._seed_impl,
            static_argnums=(3,) if paged else (),
            donate_argnums=(0,),
        )
        # flip-time prefix store: slice the freshly filled prompt KV back
        # out of the slot (a copy — the live row decodes on)
        self._snap_prog = jax.jit(
            self._snap_paged_impl if paged else self._snap_impl,
            static_argnums=(2,),
        )
        # chunks the loop keeps in flight before syncing the oldest: plans
        # are value-independent (budgets only), so depth-D dispatch is
        # exact; it hides the per-chunk fetch round-trip behind device
        # compute. Value-DEPENDENT row exits (stop tokens, client cancels)
        # lag by up to depth chunks of wasted compute, never wrong tokens.
        self.pipeline_depth = max(1, int(pipeline_depth))
        # decode steps per device program, in CHUNKS: when every slot is in
        # steady decode (nothing queued/waiting/filling, no first token
        # owed) one program scans depth x chunk_size steps, amortizing the
        # fixed per-dispatch cost depth-fold. 0 = auto (AUTO_DISPATCH_DEPTH
        # in steady decode); 1 = classic per-chunk dispatch. Stop/cancel/
        # deadline detection lags by the program's span (wasted compute,
        # never wrong tokens: the (seed, step) streams are schedule-
        # invariant); _pick_depth also caps depth at every row's remaining
        # budget so writes stay inside the validated _overrun span.
        self.dispatch_depth = int(dispatch_depth)
        if self.dispatch_depth < 0:
            raise ValueError("dispatch_depth must be >= 0 (0 = auto)")
        self._depth_cap = self.dispatch_depth or self.AUTO_DISPATCH_DEPTH
        # idle-burst gather window: when the first request hits an IDLE
        # engine, wait this long for co-arrivals before admitting (burst ->
        # one admit program + aligned decode depths). 0 disables.
        self.burst_window_ms = float(burst_window_ms)
        self._spec_prog = jax.jit(
            self._spec_verify_paged_impl if paged else self._spec_verify_impl,
            donate_argnums=(1,),
        )

        self._q: "queue.Queue" = queue.Queue()
        # FIFO admission backlog: items popped from the queue while no slot
        # was free wait HERE (in arrival order) — re-putting them at the
        # back of the queue would let later arrivals jump them under slot
        # contention (ADVICE r4)
        self._waiting: list = []
        self._closed = False
        self._broken: BaseException | None = None
        self._close_lock = threading.Lock()
        # -- bounded admission + deadlines ----------------------------------
        # max_queue_depth > 0: submits past this many not-yet-admitted rows
        # shed with QueueFullError (429 + Retry-After on the wire) instead
        # of queueing into unbounded latency. _backlog counts rows in _q +
        # _waiting + _preempted, maintained under _close_lock.
        self.max_queue_depth = int(max_queue_depth)
        # request_timeout_s > 0: every submit gets a deadline; the loop
        # expires past-deadline rows at chunk boundaries (waiting, filling,
        # or decoding) with DeadlineExceededError (504 on the wire)
        self.request_timeout_s = float(request_timeout_s)
        self._backlog = 0
        # -- supervision ----------------------------------------------------
        # a crashed loop no longer bricks the engine: after the death path
        # drains every waiter, the supervisor (_run's outer loop) rebuilds
        # the device state and restarts, with exponential crash-loop
        # backoff; more than max_crashes crashes inside crash_window_s
        # opens the circuit (stay broken — something is systematically
        # wrong and restart livelock would just burn the device)
        self.supervise = bool(supervise)
        self.restart_backoff_s = float(restart_backoff_s)
        self.max_crashes = int(max_crashes)
        self.crash_window_s = float(crash_window_s)
        self._crash_times: list[float] = []
        self._restarts = 0
        self._state = "running"  # running | restarting | broken | stopped
        self._closed_ev = threading.Event()  # interrupts the backoff sleep
        # poison quarantine: fingerprint -> count of loop crashes that
        # happened while dispatching THAT request's admission/fill work; at
        # POISON_CRASHES the request is rejected at submit with 400 instead
        # of being re-admitted into another crash
        self._poison: dict[tuple, int] = {}
        self._suspect_fp: tuple | None = None
        # -- hang watchdog --------------------------------------------------
        # boundary_watchdog_s > 0: a monitor thread treats a boundary that
        # makes no progress for this long (while rows are active) as a
        # crash — the supervisor only heals crashes, and a WEDGED device
        # dispatch (real on TPU: a hung transfer or collective) would
        # otherwise hold the loop, and every waiter, forever. Off by
        # default: first-touch XLA compiles legitimately take seconds, so
        # the operator picks a window that clears them.
        self.boundary_watchdog_s = float(boundary_watchdog_s)
        self._watch_stall: BaseException | None = None
        self._progress_t: float | None = None
        # -- flight recorder (ISSUE 15) -------------------------------------
        # bounded ring of boundary-granularity engine events (admission,
        # fill piece, dispatch, readback, preemption, EOS, expiry, stall,
        # crash) — the black box the supervisor dumps on crash/watchdog/
        # circuit-break so healing stops destroying the evidence. On by
        # default: the per-boundary cost is a few dict stores (the bench's
        # flightrec_overhead_pct leg holds the tax under 2%).
        self.flight_dump_dir = str(flight_dump_dir or "")
        self.flightrec = (
            flightrec.FlightRecorder(
                int(flightrec_capacity) or flightrec.DEFAULT_CAPACITY)
            if flight_recorder else None
        )
        # the request whose admission/fill dispatch is in flight, for crash
        # attribution in the dump (the id twin of _suspect_fp)
        self._suspect_rid = ""
        # measured device telemetry (utils/devmem) sampled into snapshot()
        self.device_telemetry = bool(device_telemetry)
        # windowed token rate (tokens/s over 1m/5m) fed at delivery time
        self.rate_tokens = tswheel.Wheel()
        self.stats = {"chunks": 0, "admitted": 0, "active_peak": 0,
                      "prefill_pieces": 0, "stall_ms_max": 0.0,
                      "engine_restarts": 0, "shed": 0, "expired": 0,
                      # admissions decoded from registry-installed prefix
                      # KV (dl/kv_store.py) rather than local prefill
                      "prefix_hits_installed": 0,
                      # pipelined dispatch: device programs launched
                      # ("chunks" stays chunk-EQUIVALENTS — a depth-D
                      # program counts D), the deepest program used, the
                      # worst steady-decode boundary's blocking sync count
                      # (must stay <= 1: the one lagged token readback),
                      # and the high-water planned-but-undelivered tokens
                      "dispatches": 0, "dispatch_depth_max": 1,
                      "host_syncs_per_boundary": 0,
                      "tokens_in_flight_peak": 0, "sync_lag_chunks_max": 0,
                      # pad accounting (ISSUE 17): every dispatched decode
                      # program computes max_slots rows regardless of how
                      # many are live — decode_pad_rows / decode_rows is
                      # the row-padding tax snapshot() exposes as
                      # pad_fraction (admit_pad_rows covers the admit-side
                      # pow2 burst rounding separately)
                      "decode_rows": 0, "decode_pad_rows": 0}
        # per-request latency histograms (ISSUE 13): fed at first-token
        # delivery from the ticket's phase stamps; snapshot() exposes them
        # once populated and the Prometheus exposition renders them as
        # explicit-bucket histogram families
        self.hist_queue_ms = promexp.Histogram()
        self.hist_ttft_ms = promexp.Histogram()
        # env-gated chaos drills (default off): MODELX_FAULT_PLAN schedules
        # deterministic dispatch faults against the running engine
        env_plan = _faults.from_env()
        if env_plan is not None and env_plan.has("engine.dispatch"):
            self._chunk = _faults.wrap_dispatch(self._chunk, env_plan)
        if self.prefill_chunk > 0:
            self.stats["prefill_chunk"] = self.prefill_chunk
            self.stats["fill_waits"] = 0  # page-blocked boundaries
            self.stats["fill_preempts"] = 0  # fills restarted for pages
        if self.page_size > 0:
            self.stats["page_size"] = self.page_size
            self.stats["pages_total"] = self.num_pages - 1  # excl. trash
            self.stats["pages_free"] = len(self._free_pages)
            self.stats["paged_attention"] = (
                "in-place" if self._fwd_paged is not None else "gather"
            )
        if self.boundary_watchdog_s > 0:
            self.stats["watchdog_stalls"] = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if self.boundary_watchdog_s > 0:
            self._watch_thread = threading.Thread(
                target=self._watchdog, daemon=True
            )
            self._watch_thread.start()

    # a request is quarantined once this many loop crashes are attributed
    # to dispatching its admission/fill work
    POISON_CRASHES = 2

    # dispatch_depth=0 resolves to this in steady decode: deep enough to
    # amortize the fixed dispatch round-trip, shallow enough that a
    # streaming client's flush cadence (delivery still splits into
    # chunk_size pieces) and the stop-detection lag stay bounded
    AUTO_DISPATCH_DEPTH = 4

    def _alloc_device_state(self, max_live_tokens: int) -> None:
        """The engine's big device allocations — the KV page pool (or
        dense cache), its mesh placement, and the sampled-token buffer —
        split out of ``__init__`` so a mid-allocation RESOURCE_EXHAUSTED
        has one cleanup point there (partial per-layer pools are dropped
        before the error propagates to the demote-and-retry path)."""
        if self.page_size > 0:
            if self.max_len % self.page_size:
                raise ValueError(
                    f"max_len {self.max_len} must be a multiple of "
                    f"page_size {self.page_size}"
                )
            budget = int(max_live_tokens) or max(
                self.max_len + self.chunk_size + self.page_size,
                self.max_slots * self.max_len // 4,
            )
            self.num_pages = 1 + -(-budget // self.page_size)  # +1: trash
            self._pages_per_slot = self.max_len // self.page_size
            self._free_pages = list(range(1, self.num_pages))
            self._table = np.zeros(
                (self.max_slots, self._pages_per_slot), np.int32
            )
            self._row_pages: dict[int, list[int]] = {}  # slot -> owned pages
            self._cache = jax.tree_util.tree_map(
                lambda leaf: jnp.zeros(
                    (self.num_pages, self.page_size) + leaf.shape[2:], leaf.dtype
                ),
                self._init_cache(1, self.page_size),
            )
        else:
            self.num_pages = 0
            # engine-owned device state: the big cache (donated through
            # every program so HBM holds exactly one copy)
            self._cache = self._init_cache(self.max_slots, self.max_len)
        # -- mesh placement (tensor-parallel continuous decode) -------------
        # On a >1-device mesh the engine's KV state gets an explicit GSPMD
        # layout before the first program closes over it: dense caches
        # shard slots over dp and kv heads over tp; the paged pool shards
        # kv heads over tp only (its leading dim is a global page index no
        # axis may split). Every program the engine compiles then inherits
        # these input layouts, so decode math runs tensor-parallel instead
        # of congealing on device 0. A single-device mesh skips this block
        # entirely — the dp=1 engine stays byte-identical to before.
        self.mesh = self.server.mesh
        self.mesh_devices = int(self.mesh.size)
        self._cache = self._place_cache(self._cache)
        self._tok = jnp.zeros((self.max_slots, 1), jnp.int32)

    def _place_cache(self, cache):
        """Lay the engine's KV state out on the serving mesh (no-op on a
        single device — the dp=1 engine stays byte-identical to before).
        Dense caches shard slots over dp and kv heads over tp; the paged
        pool shards kv heads over tp only, because its leading dim is a
        global page index no axis may split. Every program the engine
        compiles inherits these input layouts, so decode math runs
        tensor-parallel instead of congealing on device 0."""
        if self.mesh_devices <= 1:
            return cache
        from modelx_tpu.dl.sharding import cache_sharding

        pool_batch_dim = -1 if self.page_size > 0 else 0
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(
                leaf,
                cache_sharding(
                    self.mesh, leaf.shape, batch_dim=pool_batch_dim,
                    head_dim=len(leaf.shape) - 2,
                ),
            ),
            cache,
        )

    # -- flight recorder ------------------------------------------------------

    def _rec(self, event: str, slot: int = -1, request_id: str = "",
             **fields) -> None:
        """Record one engine event into the flight ring (no-op when the
        recorder is disabled)."""
        fr = self.flightrec
        if fr is not None:
            fr.record(event, slot=slot, request_id=request_id, **fields)

    def _slot_states(self) -> list[dict]:
        """Per-slot occupancy for the black-box dump: who held which slot
        (and how far along) when the engine died."""
        out = []
        for slot, row in list(self._rows.items()):
            out.append({"slot": slot, "state": "decoding",
                        "request_id": row.ticket.request_id,
                        "emitted": row.emitted, "budget": row.budget})
        for slot, fill in list(self._filling.items()):
            out.append({"slot": slot, "state": "filling",
                        "request_id": fill.ticket.request_id,
                        "filled": fill.filled,
                        "prompt_len": len(fill.ids)})
        return out

    def _flight_dump(self, reason: str, err: BaseException | None) -> str:
        """Write the black-box file (crash / watchdog / circuit-break).
        Best-effort by design: the engine is already dying, and the dump
        path must never add a failure mode of its own."""
        if self.flightrec is None or not self.flight_dump_dir:
            return ""
        meta = {
            "model": str(getattr(self.server, "name", "") or ""),
            "engine_state": self._state,
            "restarts": self._restarts,
        }
        if err is not None:
            meta["error"] = repr(err)[:300]
        path = self.flightrec.dump(
            self.flight_dump_dir, reason, meta=meta,
            slots=self._slot_states(),
        )
        if path:
            logging.getLogger("modelx.serve").warning(
                "flight recorder dumped %s black box to %s", reason, path
            )
        return path

    # -- compiled programs ----------------------------------------------------

    def _sample_first(self, logits, last_idx, temp, top_k, top_p, seed,
                      step=0):
        """Each row's first token: step ``step`` of its sample stream (0
        for a fresh request; a RESUMED request that re-prefilled
        prompt + k emitted tokens continues at step k, so the token is
        byte-identical to the one the interrupted stream would have
        emitted next). Row-wise: works for the [1, S] single admission
        and the [k, S] batched admission alike."""
        from modelx_tpu.ops import sampling as sampling_ops

        idx = jnp.broadcast_to(
            last_idx[:, None, None], (logits.shape[0], 1, logits.shape[-1])
        )
        last = jnp.take_along_axis(logits, idx, axis=1)[:, 0, :]
        return sampling_ops.sample(
            last.astype(jnp.float32), jax.random.PRNGKey(0), temp,
            top_k=top_k, top_p=top_p, seeds=seed, step=step,
        )

    def _admit_many_impl(self, params, prompts, cache, tok, row_lens, slots,
                         temp, top_k, top_p, seeds, first_steps):
        """A burst of same-bucket admissions as ONE program: prefill the
        [max_slots, Sb] block into a fresh scratch cache, sample every
        row's first token (step 0 of its own seed stream — identical to k
        single admits), and scatter the scratch rows into their slots. On
        a tunneled device each program dispatch costs a host round-trip,
        so k arrivals admitted one-by-one pay k round-trips where this
        pays one. The host pads the burst to the next POWER OF TWO of its
        size (pad rows carry an out-of-bounds slot index whose scatter
        ``mode="drop"`` discards), so small bursts don't pay a full
        max_slots-row prefill and compiles stay bounded at
        log2(max_slots) sizes per prompt bucket."""
        small = self._init_cache(prompts.shape[0], prompts.shape[1])
        logits, small = self._fwd(params, prompts, kv_cache=small, cache_offset=0)
        firsts = self._sample_first(logits, row_lens - 1, temp, top_k, top_p,
                                    seeds, step=first_steps)
        cache = jax.tree_util.tree_map(
            lambda big, lit: big.at[slots, : lit.shape[1]].set(lit, mode="drop"),
            cache, small,
        )
        tok = tok.at[slots, 0].set(firsts, mode="drop")
        return cache, tok, firsts

    def _admit_many_paged_impl(self, params, prompts, pool, tok, row_lens,
                               slots, page_ids, temp, top_k, top_p, seeds,
                               first_steps):
        """Paged batched admission: same one-program shape, writing each
        row's scratch rows into its reserved pages (``page_ids`` is
        [max_slots, n_prompt_pages] — same bucket means the same page
        count, so every page column scatters all rows at once). Pad rows'
        page ids point at the trash page (their writes land harmlessly);
        their tok scatter drops on the out-of-bounds slot index."""
        sb = prompts.shape[1]
        small = self._init_cache(prompts.shape[0], sb)
        logits, small = self._fwd(params, prompts, kv_cache=small, cache_offset=0)
        firsts = self._sample_first(logits, row_lens - 1, temp, top_k, top_p,
                                    seeds, step=first_steps)
        ps = self.page_size

        def write(pool_leaf, small_leaf):
            out = pool_leaf
            for j in range(0, sb, ps):
                w = min(j + ps, sb) - j
                blk = jax.lax.slice_in_dim(small_leaf, j, j + w, axis=1)
                out = out.at[page_ids[:, j // ps], :w].set(blk)
            return out

        pool = jax.tree_util.tree_map(write, pool, small)
        tok = tok.at[slots, 0].set(firsts, mode="drop")
        return pool, tok, firsts

    def _finish_admit(self, small, logits, cache, tok, last_idx, slot,
                      temp, top_k, top_p, seed, first_step):
        """Shared admit tail: sample the row's first token and insert the
        scratch cache + token into ``slot`` of the donated engine state.
        Returns (cache, tok, first, small) — ``small`` goes back to the
        host for the prefix cache."""
        first = self._sample_first(logits, last_idx, temp, top_k, top_p, seed,
                                   step=first_step)

        def put(big, little):
            return jax.lax.dynamic_update_slice(
                big, little, (slot,) + (0,) * (big.ndim - 1)
            )

        cache = jax.tree_util.tree_map(put, cache, small)
        tok = jax.lax.dynamic_update_slice(tok, first[:, None], (slot, 0))
        return cache, tok, first, small

    def _finish_admit_paged(self, small, logits, pool, tok, last_idx, slot,
                            page_ids, temp, top_k, top_p, seed, first_step,
                            span: int):
        """Paged admit tail: sample the first token, then write the scratch
        cache's first ``span`` rows into the slot's reserved pages. ``span``
        is STATIC (the prompt bucket / trim length), so the write unrolls
        to ceil(span/page_size) dynamic_update_slices — compiled once per
        prompt bucket, exactly like the prefill itself."""
        first = self._sample_first(logits, last_idx, temp, top_k, top_p, seed,
                                   step=first_step)
        tok = jax.lax.dynamic_update_slice(tok, first[:, None], (slot, 0))
        ps = self.page_size

        def write(pool_leaf, small_leaf):
            out = pool_leaf
            for j in range(0, span, ps):
                # the final block may be a partial page (span need not be a
                # page multiple): the page's tail stays junk past every
                # query position until decode overwrites it
                blk = jax.lax.slice_in_dim(small_leaf, j, min(j + ps, span), axis=1)
                out = jax.lax.dynamic_update_slice(
                    out, blk, (page_ids[j // ps],) + (0,) * (out.ndim - 1)
                )
            return out

        pool = jax.tree_util.tree_map(write, pool, small)
        return pool, tok, first, small

    def _admit_paged_impl(self, params, prompt, pool, tok, row_len, slot,
                          page_ids, temp, top_k, top_p, seed, first_step):
        """Paged admission: prefill into a [1, Sb] scratch cache, then the
        paged admit tail (pages instead of a slot-row insert)."""
        small = self._init_cache(1, prompt.shape[1])
        logits, small = self._fwd(params, prompt, kv_cache=small, cache_offset=0)
        return self._finish_admit_paged(
            small, logits, pool, tok, row_len - 1, slot, page_ids,
            temp, top_k, top_p, seed, first_step, span=prompt.shape[1],
        )

    def _admit_cached_paged_impl(self, params, suffix, pool, tok, suffix_len,
                                 plen, slot, stored, page_ids, temp, top_k,
                                 top_p, seed, trim_len: int, first_step=0):
        """Prefix-hit paged admission: stored KV + suffix prefill (the
        dense cached-admit's semantics, see _admit_cached_impl), written
        out page by page."""
        sb = suffix.shape[1]
        small = jax.tree_util.tree_map(
            lambda s: jnp.concatenate(
                [s, jnp.zeros((1, sb) + s.shape[2:], s.dtype)], axis=1
            ),
            stored,
        )
        logits, small = self._fwd(params, suffix, kv_cache=small, cache_offset=plen)
        small = jax.tree_util.tree_map(lambda c: c[:, :trim_len], small)
        return self._finish_admit_paged(
            small, logits, pool, tok, suffix_len - 1, slot, page_ids,
            temp, top_k, top_p, seed, first_step, span=trim_len,
        )

    def _admit_impl(self, params, prompt, cache, tok, row_len, slot,
                    temp, top_k, top_p, seed, first_step):
        """One program per admission: prefill the [1, S] prompt into a
        scratch cache (allocated INSIDE the jit — zeros fuse, no host
        transfer), then the shared admit tail."""
        small = self._init_cache(1, prompt.shape[1])
        logits, small = self._fwd(params, prompt, kv_cache=small, cache_offset=0)
        return self._finish_admit(small, logits, cache, tok, row_len - 1, slot,
                                  temp, top_k, top_p, seed, first_step)

    def _admit_cached_impl(self, params, suffix, cache, tok, suffix_len, plen,
                           slot, stored, temp, top_k, top_p, seed,
                           trim_len: int, first_step=0):
        """Prefix-hit admission: the scratch cache starts as the STORED
        prefix KV (extended with zeros for the suffix bucket) and only the
        [1, Sb] suffix block prefills, at offset ``plen``. KV values are a
        deterministic function of the token prefix, so the admitted row is
        byte-identical to a full prefill. Junk in the stored bucket past
        the real prefix is overwritten by the suffix write (each layer
        writes its k/v BEFORE attending), and junk past the suffix span
        sits beyond every query position until decode overwrites it.
        ``trim_len`` (static, = the full prompt's 16-bucket) cuts the
        scratch back down before insertion/storage."""
        sb = suffix.shape[1]
        small = jax.tree_util.tree_map(
            lambda s: jnp.concatenate(
                [s, jnp.zeros((1, sb) + s.shape[2:], s.dtype)], axis=1
            ),
            stored,
        )
        logits, small = self._fwd(params, suffix, kv_cache=small, cache_offset=plen)
        small = jax.tree_util.tree_map(lambda c: c[:, :trim_len], small)
        return self._finish_admit(small, logits, cache, tok, suffix_len - 1, slot,
                                  temp, top_k, top_p, seed, first_step)

    # -- chunked prefill piece programs ---------------------------------------

    def _gather_row(self, cache, slot):
        """The slot's own [1, max_len] cache rows, sliced out of the
        engine state — a mid-prompt piece needs the row's earlier KV as
        attention context, unlike admission's fresh offset-0 scratch."""
        return jax.tree_util.tree_map(
            lambda big: jax.lax.dynamic_slice(
                big, (slot,) + (0,) * (big.ndim - 1), (1,) + big.shape[1:]
            ),
            cache,
        )

    def _scatter_row(self, cache, row, slot):
        return jax.tree_util.tree_map(
            lambda big, little: jax.lax.dynamic_update_slice(
                big, little, (slot,) + (0,) * (big.ndim - 1)
            ),
            cache, row,
        )

    def _gather_pages(self, pool, table_row):
        """One slot's pages as a dense [1, max_len] view (``table_row`` is
        the slot's block-table row; unreserved entries point at trash)."""
        return jax.tree_util.tree_map(
            lambda p: p[table_row].reshape(1, self.max_len, *p.shape[2:]),
            pool,
        )

    def _piece_impl(self, params, piece, cache, filled, slot):
        """One mid-prompt prefill piece: gather the slot's row, run the
        [1, Sb] block at offset ``filled`` (positions/causality follow the
        decode contract, so the landed KV is byte-identical to the same
        span of a monolithic prefill), write the row back. Logits are not
        an output — XLA drops the lm_head matmul for mid pieces."""
        row = self._gather_row(cache, slot)
        _logits, row = self._fwd(params, piece, kv_cache=row, cache_offset=filled)
        return self._scatter_row(cache, row, slot)

    def _piece_flip_impl(self, params, piece, cache, tok, filled, slot,
                         last_idx, temp, top_k, top_p, seed, first_step):
        """The LAST piece: land its KV and sample the row's first token
        from the piece's final real position — step ``first_step`` of the
        row's (seed, step) stream (0 fresh, k on resume), byte-identical
        to single-program admission."""
        row = self._gather_row(cache, slot)
        logits, row = self._fwd(params, piece, kv_cache=row, cache_offset=filled)
        cache = self._scatter_row(cache, row, slot)
        first = self._sample_first(logits, last_idx, temp, top_k, top_p, seed,
                                   step=first_step)
        tok = jax.lax.dynamic_update_slice(tok, first[:, None], (slot, 0))
        return cache, tok, first

    def _scatter_piece_pages(self, pool, dense, write_page_ids, page_start):
        """Write back ONLY the pages a piece touched: the forward modifies
        [filled, filled + Sb), i.e. at most Sb/page_size + 1 pages —
        scattering the slot's whole max_len span per piece would pay
        ~max_len/Sb x the useful copy traffic on exactly the long-context
        shapes chunked prefill targets. ``write_page_ids`` is the touched
        table entries (STATIC count — compiles per piece bucket x two
        alignments), ``page_start`` the first touched page's token offset."""
        ps = self.page_size
        n_touch = write_page_ids.shape[0]

        def put_back(p, d):
            out = p
            for j in range(n_touch):
                blk = jax.lax.dynamic_slice_in_dim(
                    d, page_start + j * ps, ps, axis=1
                )
                out = jax.lax.dynamic_update_slice(
                    out, blk, (write_page_ids[j],) + (0,) * (out.ndim - 1)
                )
            return out

        return jax.tree_util.tree_map(put_back, pool, dense)

    def _piece_paged_impl(self, params, piece, pool, table_row, filled,
                          write_page_ids, page_start):
        dense = self._gather_pages(pool, table_row)
        _logits, dense = self._fwd(params, piece, kv_cache=dense, cache_offset=filled)
        return self._scatter_piece_pages(pool, dense, write_page_ids, page_start)

    def _piece_flip_paged_impl(self, params, piece, pool, tok, table_row,
                               filled, slot, last_idx, temp, top_k, top_p,
                               seed, write_page_ids, page_start, first_step):
        dense = self._gather_pages(pool, table_row)
        logits, dense = self._fwd(params, piece, kv_cache=dense, cache_offset=filled)
        pool = self._scatter_piece_pages(pool, dense, write_page_ids, page_start)
        first = self._sample_first(logits, last_idx, temp, top_k, top_p, seed,
                                   step=first_step)
        tok = jax.lax.dynamic_update_slice(tok, first[:, None], (slot, 0))
        return pool, tok, first

    def _seed_impl(self, cache, stored, slot):
        """Prefix-hit fill seeding: the stored [1, plen-bucket] prefix KV
        lands at the slot's offset 0. Bucket junk past the real prefix is
        overwritten by the first suffix piece (each layer writes its k/v
        before attending, and piece >= 16 > bucket - plen)."""
        return jax.tree_util.tree_map(
            lambda big, s: jax.lax.dynamic_update_slice(
                big, s, (slot,) + (0,) * (big.ndim - 1)
            ),
            cache, stored,
        )

    def _seed_paged_impl(self, pool, stored, page_ids, span: int):
        """Paged fill seeding: the stored prefix writes into the slot's
        first reserved pages (``span`` static = the prefix's bucket)."""
        ps = self.page_size

        def write(pool_leaf, s):
            out = pool_leaf
            for j in range(0, span, ps):
                blk = jax.lax.slice_in_dim(s, j, min(j + ps, span), axis=1)
                out = jax.lax.dynamic_update_slice(
                    out, blk, (page_ids[j // ps],) + (0,) * (out.ndim - 1)
                )
            return out

        return jax.tree_util.tree_map(write, pool, stored)

    def _snap_impl(self, cache, slot, bucket: int):
        """Copy the slot's freshly filled prompt KV back out (prefix-cache
        store at flip time; the live row decodes on, so this is a copy)."""
        return jax.tree_util.tree_map(
            lambda big: jax.lax.dynamic_slice(
                big, (slot,) + (0,) * (big.ndim - 1),
                (1, bucket) + big.shape[2:],
            ),
            cache,
        )

    def _snap_paged_impl(self, pool, table_row, bucket: int):
        # gather only the prompt span's pages (``bucket`` is static, so
        # the page count is too) — densifying the whole max_len row here
        # would pay ~max_len/bucket x the needed copy at flip time
        n_pg = -(-bucket // self.page_size)
        return jax.tree_util.tree_map(
            lambda p: p[table_row[:n_pg]].reshape(
                1, n_pg * self.page_size, *p.shape[2:]
            )[:, :bucket],
            pool,
        )

    def _chunk_impl(self, params, cache, tok, offsets, steps, temp, top_k,
                    top_p, seeds, n_steps=None):
        """``n_steps`` decode steps over ALL slots (``n_steps`` is STATIC —
        the default is one ``chunk_size`` chunk, a depth-D dispatch passes
        D x chunk_size); offsets/steps are per-row (slots joined at
        different times sit at different depths). ``top_k``/``top_p``
        arrive as None when NO active row uses filters — the None variant
        compiles without the per-step full-vocab sort the filters need
        (jit caches both variants; values are identical either way since
        0 / 1.0 mean "off" per row). The token block carries one EXTRA
        trailing column: the scan's final carry (each row's next,
        not-yet-delivered token), so the host's lagged readback also
        learns the lookahead value without a second device sync."""
        from modelx_tpu.ops import sampling as sampling_ops

        def step_fn(carry, _i):
            cache, tok, offsets, steps = carry
            logits, cache = self._fwd(params, tok, kv_cache=cache, cache_offset=offsets)
            nxt = sampling_ops.sample(
                logits[:, -1, :].astype(jnp.float32), jax.random.PRNGKey(0), temp,
                top_k=top_k, top_p=top_p, seeds=seeds, step=steps,
            )
            return (cache, nxt[:, None], offsets + 1, steps + 1), tok[:, 0]

        (cache, tok, offsets, steps), toks = jax.lax.scan(
            step_fn, (cache, tok, offsets, steps),
            jnp.arange(n_steps or self.chunk_size),
        )
        return cache, tok, jnp.concatenate([toks.T, tok], axis=1)

    def _chunk_paged_impl(self, params, pool, tok, table, offsets, steps,
                          temp, top_k, top_p, seeds, n_steps=None):
        """Paged chunk: each step gathers every slot's pages into a dense
        [max_slots, max_len] view (a TRANSIENT the scheduler frees layer by
        layer — the persistent state is only the pool), runs the family
        forward against it unchanged, then scatters the one row each slot
        wrote back into its current page. Idle slots' table rows are all
        zeros, so their writes land on the trash page and their reads sit
        beyond the causal horizon. The table is a traced input: one
        compiled program serves every page assignment."""
        from modelx_tpu.ops import sampling as sampling_ops

        def step_fn(carry, _i):
            pool, tok, offsets, steps = carry
            if self._fwd_paged is not None:
                # fast path: the family forward scatters this step's k/v
                # into the pools and attends over them IN PLACE
                logits, pool = self._fwd_paged(
                    params, tok, kv_cache=pool, cache_offset=offsets, table=table
                )
            else:
                dense = jax.tree_util.tree_map(
                    lambda p: p[table].reshape(
                        self.max_slots, self.max_len, *p.shape[2:]
                    ),
                    pool,
                )
                logits, dense = self._fwd(
                    params, tok, kv_cache=dense, cache_offset=offsets
                )
                from modelx_tpu.ops.paged_attention import write_token_kv

                def put_back(p, d):
                    rows = jax.vmap(
                        lambda row, o: jax.lax.dynamic_slice_in_dim(row, o, 1, axis=0)
                    )(d, offsets)  # [slots, 1, ...] — the row each slot wrote
                    return write_token_kv(p, rows, table, offsets)

                pool = jax.tree_util.tree_map(put_back, pool, dense)
            nxt = sampling_ops.sample(
                logits[:, -1, :].astype(jnp.float32), jax.random.PRNGKey(0), temp,
                top_k=top_k, top_p=top_p, seeds=seeds, step=steps,
            )
            return (pool, nxt[:, None], offsets + 1, steps + 1), tok[:, 0]

        (pool, tok, offsets, steps), toks = jax.lax.scan(
            step_fn, (pool, tok, offsets, steps),
            jnp.arange(n_steps or self.chunk_size),
        )
        # extra trailing column = the lookahead carry, see _chunk_impl
        return pool, tok, jnp.concatenate([toks.T, tok], axis=1)

    # -- speculative verify (single-occupied greedy slot) ---------------------

    def _spec_verify_impl(self, params, cache, block, offsets):
        """One verify step over the engine's FULL slot array: ``block`` is
        [max_slots, k+1] (the active slot carries last-token + proposals;
        idle slots carry zeros whose writes land at their offset-0 garbage
        rows). Returns the model's argmax at every position — position i is
        its pick for the token AFTER block[:, :i+1]. Rejected positions
        leave garbage KV; the host rewinds offsets past them, and the
        causal mask (kpos <= qpos) hides them until overwritten."""
        logits, cache = self._fwd(params, block, kv_cache=cache, cache_offset=offsets)
        return cache, jnp.argmax(logits, axis=-1)  # [max_slots, k+1]

    def _spec_verify_paged_impl(self, params, pool, block, table, offsets):
        """Paged verify: gather -> forward -> scatter each of the k+1
        written rows back to its page (static unroll over the block width,
        like the admit tail's page writes)."""
        from modelx_tpu.ops.paged_attention import write_token_kv

        dense = jax.tree_util.tree_map(
            lambda p: p[table].reshape(self.max_slots, self.max_len, *p.shape[2:]),
            pool,
        )
        logits, dense = self._fwd(params, block, kv_cache=dense, cache_offset=offsets)
        width = block.shape[1]

        def put_back(p, d):
            for j in range(width):
                off = offsets + j
                rows = jax.vmap(
                    lambda row, o: jax.lax.dynamic_slice_in_dim(row, o, 1, axis=0)
                )(d, off)
                p = write_token_kv(p, rows, table, off)
            return p

        pool = jax.tree_util.tree_map(put_back, pool, dense)
        return pool, jnp.argmax(logits, axis=-1)

    def _spec_ok(self) -> bool:
        """Speculate iff exactly one greedy row is active and nothing is
        waiting for a slot (admissions beat speculation — cross-row
        batching uses each weight read better than lookahead does). A
        filling row also disqualifies: its pieces need boundaries."""
        if (self.speculative_k <= 0 or len(self._rows) != 1
                or self._waiting or self._filling):
            return False
        row = next(iter(self._rows.values()))
        return (row.greedy and not row.closed and not row.ticket.cancelled
                and row.seq is not None)

    def _spec_step(self) -> None:
        """Propose -> verify -> accept -> deliver, synchronously (the spec
        regime trades the chunk pipeline's depth for fewer device steps per
        token; it only runs when there is no other row to pipeline with).

        Block convention: the engine invariant says the cache holds
        [0, offsets) and ``tok`` carries the next token to CONSUME. After
        admission that token (the prefill's first) is already delivered;
        after a chunk it is the chunk's lookahead token, not yet delivered
        (``row.tok_pending``) — the step emits it as part of this round's
        piece. Either way the verify block is [that token, proposals...] at
        the row's offset, exactly models/speculative.py's layout."""
        from modelx_tpu.models.speculative import _NgramIndex

        slot, row = next(iter(self._rows.items()))
        prefix_emit: list[int] = []
        if row.tok_pending:
            # the lookahead token rides in the last delivered chunk's extra
            # carry column (_tok_host) — the chunk->spec transition costs
            # NO extra device sync. The fallback sync only fires when no
            # delivery refreshed the host copy (shouldn't happen: the loop
            # drains every in-flight chunk before entering spec mode).
            if self._tok_host is not None:
                tok_val = int(self._tok_host[slot])
            else:
                t0 = time.monotonic()
                tok_val = int(np.asarray(self._tok)[slot, 0])
                self._sync_wait_s += time.monotonic() - t0
                self._boundary_syncs += 1
            row.seq.append(tok_val)
            prefix_emit = [tok_val]
        else:
            tok_val = row.seq[-1]
        if row.ngram is None:
            row.ngram = _NgramIndex(self.max_ngram)
        row.ngram.extend(row.seq, row.ng_len)
        row.ng_len = len(row.seq)
        k = self.speculative_k
        prop = row.ngram.propose(row.seq, k)
        block = np.zeros((self.max_slots, k + 1), np.int32)
        block[slot, 0] = tok_val
        if prop:
            block[slot, 1:1 + len(prop)] = prop
        args = [jnp.asarray(block)]
        if self.page_size > 0:
            args.append(jnp.asarray(self._table.copy()))
        args.append(jnp.asarray(self._offsets.copy()))
        with trace.span("continuous.spec_verify", proposed=len(prop)):
            self._cache, argm_dev = self._spec_prog(
                self.server.params, self._cache, *args
            )
        # THE spec boundary's one blocking readback (verify is inherently
        # synchronous: acceptance decides the next proposal)
        t0 = time.monotonic()
        argm = np.asarray(argm_dev)[slot]
        self._sync_wait_s += time.monotonic() - t0
        self._boundary_syncs += 1
        self.stats["spec_steps"] = self.stats.get("spec_steps", 0) + 1
        self.stats["spec_proposed"] = self.stats.get("spec_proposed", 0) + len(prop)
        # accept while the model agrees, then its own token at the first
        # disagreement (exactly models/speculative.py's greedy rule)
        a = 0
        while a < len(prop) and int(argm[a]) == prop[a]:
            a += 1
        room = row.budget - row.emitted
        new = (prefix_emit + prop[:a] + [int(argm[a])])[:room]
        verified = new[len(prefix_emit):]  # tokens the verify itself emitted
        self.stats["spec_accepted"] = (
            self.stats.get("spec_accepted", 0) + min(a, len(verified))
        )
        # rewind past rejected/padded positions; only verified history stays
        self._offsets[slot] += a + 1
        self._steps[slot] += a + 1
        row.seq.extend(verified)
        row.emitted += len(new)
        # engine state for a possible fall-back to chunk mode: tok carries
        # the row's last DELIVERED token, whose chunk-entry re-emission the
        # skip swallows
        tok_np = np.zeros((self.max_slots, 1), np.int32)
        tok_np[slot, 0] = row.seq[-1]
        self._tok = jnp.asarray(tok_np)
        self._tok_host = tok_np[:, 0].copy()  # spec knows tok on the host
        self._steady = False  # spec rounds aren't steady-decode boundaries
        row.skip = 1
        row.tok_pending = False
        piece = np.asarray([new], np.int32)
        done = row.emitted >= row.budget
        if row.stops:
            from modelx_tpu.models.decode import stop_cut

            cut = stop_cut(new, row.stops)
            if cut is not None:
                piece = piece[:, :cut]
                done = True
        row.out.put(piece)
        if done:
            row.out.put(_DONE)
            row.closed = True  # sweep frees the slot before the next step

    # -- engine loop ----------------------------------------------------------

    def _need_pages(self, ids, n: int) -> int:
        """Pages covering the row's full write span (prompt bucket + budget
        + the overrun margin — the same ``need`` submit validates)."""
        need = pad_seq_len(len(ids)) + n + self._overrun
        return -(-need // self.page_size)

    def _admits_now(self, item) -> bool:
        """A free slot — and, in paged mode, enough free pages. A prompt
        that will single-program-admit needs its whole span up front (a
        mid-decode pool exhaustion must not strand a half-decoded row); a
        prompt that will CHUNK-fill needs only its first piece's pages —
        the rest reserve incrementally as decode rows retire, so a long
        prompt's admission no longer serializes behind the pool-full FIFO
        for its full span."""
        if not self._free:
            return False
        if self.page_size > 0 and not item[3].cancelled:
            ids, n = item[0], item[1]
            if self.prefill_chunk > 0 and pad_seq_len(len(ids)) > self.prefill_chunk:
                need = -(-self.prefill_chunk // self.page_size)
            else:
                need = self._need_pages(ids, n)
            if need > len(self._free_pages):
                return False
        return True

    def _release_slot(self, slot: int) -> None:
        """Return a retired row's slot (and, paged, its pages) to the free
        sets. Table zeroing points the slot's entries back at the trash
        page; the chunk possibly still in flight dispatched with a
        SNAPSHOT of the table, so reuse stays data-ordered."""
        self._free.append(slot)
        self._offsets[slot] = 0
        if self.page_size > 0:
            self._free_pages.extend(self._row_pages.pop(slot, ()))
            self._table[slot, :] = 0
            self.stats["pages_free"] = len(self._free_pages)

    def _gather_prep(self, item, to_admit: list) -> None:
        """Prepare one admissible item into ``to_admit``. If preparation
        itself dies, every waiter gathered so far (plus this item's) is
        failed before the engine unwinds — their preps live only in the
        loop-local list, out of reach of the generic death failsafes."""
        self._backlog_sub(1)  # leaving the not-yet-admitted set, whatever happens
        # consume the boundary-prep overlap memo (fingerprint + prefix
        # lookup computed while the previous chunks executed); fall back to
        # computing inline for items the overlap pass hadn't reached
        memo = self._prep_memo.pop(item[3], None)
        fp = memo[0] if memo is not None else _fingerprint(item[0], item[1])
        self._suspect_fp = fp
        self._suspect_rid = item[3].request_id
        try:
            prep = self._prepare_admit(
                item, memo_hit=memo[1] if memo is not None else _NO_HIT
            )
        except BaseException as e:
            item[3].out.put(e)
            for p in to_admit:
                p["ticket"].out.put(e)
            raise
        self._suspect_fp = None
        self._suspect_rid = ""
        if prep is not None:
            prep["fp"] = fp  # reused by the admit/fill dispatch attribution
            to_admit.append(prep)

    def _prepare_admit(self, item, memo_hit=_NO_HIT) -> dict | None:
        """Claim a slot (and, paged, reserve the row's pages) for one
        admissible item and resolve its prefix-cache hit. Pure host-side
        bookkeeping — the device dispatch happens in ``_admit_one`` /
        ``_admit_group`` so a burst of preparations can share a program.

        With chunked prefill on, a prompt whose to-prefill span exceeds
        one piece becomes a FILL preparation instead: the slot is
        claimed but nothing dispatches now — pieces land at boundaries
        (prefix hits seed the fill's offset so only the suffix chunks)."""
        ids, n, samp, ticket = item
        if ticket.cancelled:  # consumer left while the request queued
            ticket.out.put(_DONE)
            return None
        if ticket.deadline is not None and time.monotonic() > ticket.deadline:
            # expired while queued: 504 BEFORE occupying a slot
            self.stats["expired"] += 1
            self._rec("deadline", request_id=ticket.request_id,
                      state="queued")
            ticket.out.put(self._deadline_error(ticket, "waiting for a slot"))
            return None
        slot = self._free.pop()
        if not ticket.t_admit:  # first claim only: restarts re-enter here
            ticket.t_admit = time.monotonic()
        s = len(ids)
        hit = None
        if self.prefix_cache is not None:
            if memo_hit is not _NO_HIT:
                # boundary-prep overlap memoized this lookup while the
                # previous chunks executed (a store racing in since then is
                # only a missed optimization, never a correctness issue)
                hit = memo_hit
            else:
                # fit-aware lookup: entries whose bucket + suffix bucket
                # exceed the slot cache are skipped (shorter fitting
                # prefixes still win)
                hit = self.prefix_cache.lookup(ids, max_total=self.max_len)
        if self.prefill_chunk > 0:
            to_fill = s - (hit[0] if hit is not None else 0)
            use_fill = pad_seq_len(to_fill) > self.prefill_chunk
            if (not use_fill and self.page_size > 0
                    and self._need_pages(ids, n) > len(self._free_pages)):
                # the single-program span's pages aren't free (a hit can
                # shrink a long prompt under one piece after _admits_now
                # gated on the first-piece estimate): fill incrementally
                use_fill = True
            if use_fill:
                if self.page_size > 0:
                    self._row_pages[slot] = []
                    self._table[slot, :] = 0
                return {"ids": ids, "n": n, "samp": samp, "ticket": ticket,
                        "slot": slot, "s": s, "hit": hit, "fill": True,
                        "finished": False}
        prompt_pages = None
        if self.page_size > 0:
            # reserve the row's WHOLE span now; the admit program only
            # writes the prompt-bucket pages, decode fills the rest
            need_pages = self._need_pages(ids, n)
            pages = [self._free_pages.pop() for _ in range(need_pages)]
            self._row_pages[slot] = pages
            self._table[slot, :] = 0
            self._table[slot, :need_pages] = pages
            self.stats["pages_free"] = len(self._free_pages)
            n_prompt = -(-pad_seq_len(s) // self.page_size)
            prompt_pages = np.asarray(pages[:n_prompt], np.int32)
        return {"ids": ids, "n": n, "samp": samp, "ticket": ticket,
                "slot": slot, "s": s, "prompt_pages": prompt_pages,
                "hit": hit, "bucket": pad_seq_len(s), "fill": False,
                "finished": False}

    def _finish_admit_host(self, prep: dict, first_ref) -> None:
        """Shared post-dispatch bookkeeping: per-slot vectors, the row
        object, and its async first-token delivery. ``first_ref`` is a
        zero-arg callable yielding the row's first token as np [1, 1]."""
        slot, s, samp = prep["slot"], prep["s"], prep["samp"]
        k_val = int(samp.get("top_k", 0))
        p_val = float(samp.get("top_p", 1.0))
        self._offsets[slot] = s
        # a resumed request re-prefilled prompt + k emitted tokens and its
        # first token here was sampled at step k — the row continues the
        # original (seed, step) stream, not a fresh one
        self._steps[slot] = int(samp.get("resume_step", 0)) + 1
        self._temp[slot] = float(samp.get("temperature", 0.0))
        self._top_k[slot] = k_val
        self._top_p[slot] = p_val
        self._seeds[slot] = int(samp.get("seed", 0))
        self._use_filters[slot] = k_val > 0 or p_val < 1.0
        row = _Row(
            slot, prep["n"], prep["ticket"],
            stops=frozenset(samp.get("stop_token_ids") or ()),
            seq=list(prep["ids"]) if self.speculative_k > 0 else None,
            greedy=float(samp.get("temperature", 0.0)) <= 0.0,
        )
        # the prefill's first token is delivered ASYNC (with the next
        # delivery batch): syncing here would serialize a full dispatch
        # round-trip per admission, where dispatching N prefills
        # back-to-back pipelines them
        row.emitted = 1
        done = row.emitted >= row.budget
        self._first_pending.append((row, first_ref, done))
        if done:
            self._release_slot(slot)
        else:
            self._rows[slot] = row
        prep["finished"] = True
        self._steady = False  # an admission boundary, not steady decode
        self.stats["admitted"] += 1
        self.stats["active_peak"] = max(self.stats["active_peak"], len(self._rows))
        self._rec("admit", slot=slot, request_id=prep["ticket"].request_id,
                  prompt_len=s, budget=prep["n"])

    def _admit_all(self, preps: list) -> None:
        """Dispatch a boundary's worth of prepared admissions: same-bucket
        prefix-cache-free preparations share ONE [k, Sb] program, the rest
        go one-by-one. If a dispatch dies mid-batch, every not-yet-finished
        preparation's waiter is failed before the engine unwinds."""
        self._tok_host = None  # admit programs advance the device tok
        try:
            singles: list = []
            groups: dict[int, list] = {}
            for p in preps:
                if p["fill"]:
                    # chunked prefill: no admit program — the fill's
                    # pieces land at boundaries from the engine loop
                    self._start_fill(p)
                elif self.prefix_cache is not None:
                    # single path stores each row's scratch KV (hit or miss)
                    singles.append(p)
                else:
                    groups.setdefault(p["bucket"], []).append(p)
            for group in groups.values():
                if len(group) == 1:
                    singles.append(group[0])
                    continue
                with trace.span("continuous.admit_many", rows=len(group)):
                    self._admit_group(group)
                self.stats["admit_batches"] = (
                    self.stats.get("admit_batches", 0) + 1
                )
            for p in singles:
                with trace.span("continuous.admit"):
                    self._admit_one(p)
        except BaseException as e:
            for p in preps:
                if not p["finished"]:
                    p["ticket"].out.put(e)
            raise

    def _admit_group(self, preps: list) -> None:
        """One program admits the whole same-bucket group as [m, Sb], with
        m the burst size rounded UP to the next power of two (clamped to
        max_slots): a 2-row burst on a max_slots=16 engine used to prefill
        a full [16, Sb] block — up to max_slots/2 x wasted prefill FLOPs
        on small bursts. Pow2 rounding keeps compiles bounded at
        log2(max_slots) sizes per prompt bucket (burst size itself never
        retraces). Rows past the real burst are padded with row_len 1 and
        an out-of-bounds slot index (scatter ``mode="drop"`` discards)."""
        sb = preps[0]["bucket"]
        m = min(self.max_slots, 1 << max(len(preps) - 1, 0).bit_length())
        self.stats["admit_pad_rows"] = (
            self.stats.get("admit_pad_rows", 0) + m - len(preps)
        )
        prompts = np.zeros((m, sb), np.int32)
        row_lens = np.ones(m, np.int32)  # pad rows: last_idx 0 stays valid
        # pad rows: max_slots is ALWAYS out of bounds for the [max_slots,..]
        # engine state -> scatter drop (m itself can be a valid slot now
        # that m may sit below max_slots)
        slots = np.full(m, self.max_slots, np.int32)
        temp = np.zeros(m, np.float32)
        top_k = np.zeros(m, np.int32)
        top_p = np.ones(m, np.float32)
        seeds = np.zeros(m, np.int32)
        first_steps = np.zeros(m, np.int32)
        for i, p in enumerate(preps):
            prompts[i, : p["s"]] = p["ids"]
            row_lens[i] = p["s"]
            slots[i] = p["slot"]
            temp[i] = float(p["samp"].get("temperature", 0.0))
            top_k[i] = int(p["samp"].get("top_k", 0))
            top_p[i] = float(p["samp"].get("top_p", 1.0))
            seeds[i] = int(p["samp"].get("seed", 0))
            first_steps[i] = int(p["samp"].get("resume_step", 0))
        args = [self.server.params, jnp.asarray(prompts), self._cache,
                self._tok, jnp.asarray(row_lens), jnp.asarray(slots)]
        if self.page_size > 0:
            n_prompt = len(preps[0]["prompt_pages"])
            page_ids = np.zeros((m, n_prompt), np.int32)  # pads -> trash page
            for i, p in enumerate(preps):
                page_ids[i] = p["prompt_pages"]
            args.append(jnp.asarray(page_ids))
        # top_k/top_p always ride as ARRAYS here (0 / 1.0 = off per row):
        # a None variant would mean two compiles per bucket, and the admit
        # program samples once — the chunk scan's per-step sort-skip
        # optimization has nothing to save on a one-shot program
        args += [jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
                 jnp.asarray(seeds), jnp.asarray(first_steps)]
        self._cache, self._tok, firsts = self._admit_many_prog(*args)
        block = {"dev": firsts, "np": None}

        def first_ref(i: int, block=block):
            if block["np"] is None:
                block["np"] = np.asarray(block["dev"])
            return block["np"][i].reshape(1, 1)

        for i, p in enumerate(preps):
            self._finish_admit_host(p, lambda i=i: first_ref(i))

    def _admit_one(self, prep: dict) -> None:
        ids, samp, slot, s = prep["ids"], prep["samp"], prep["slot"], prep["s"]
        # this dispatch is attributable to ONE request: a loop death here
        # counts against its poison-quarantine budget
        self._suspect_fp = prep["fp"]
        self._suspect_rid = prep["ticket"].request_id
        # registry-installed prefix KV (dl/kv_store.py): count and mark the
        # dispatch when this admit decodes from fleet-shared state — the
        # observable proof a fresh pod skipped a shared-prefix prefill
        installed = False
        if prep["hit"] is not None and self.prefix_cache is not None:
            installed = (
                self.prefix_cache.entry_origin(ids[: prep["hit"][0]])
                == "installed"
            )
            if installed:
                self.stats["prefix_hits_installed"] += 1
        self._rec("dispatch_admit", slot=slot,
                  request_id=prep["ticket"].request_id,
                  prompt_len=s, cached=prep["hit"] is not None,
                  installed_kv=installed)
        hit = prep["hit"]
        prompt_pages = (
            jnp.asarray(prep["prompt_pages"])
            if prep["prompt_pages"] is not None else None
        )
        temp = np.asarray([samp.get("temperature", 0.0)], np.float32)
        k_val = int(samp.get("top_k", 0))
        p_val = float(samp.get("top_p", 1.0))
        filters = k_val > 0 or p_val < 1.0
        top_k = np.asarray([k_val], np.int32) if filters else None
        top_p = np.asarray([p_val], np.float32) if filters else None
        seed = np.asarray([samp.get("seed", 0)], np.int32)
        first_step = np.asarray([samp.get("resume_step", 0)], np.int32)
        if hit is not None:
            plen, stored = hit
            suffix = ids[plen:]
            sb = pad_seq_len(len(suffix))
            block = np.zeros((1, sb), np.int32)
            block[0, : len(suffix)] = suffix
            if self.page_size > 0:
                self._cache, self._tok, first, small = self._admit_cached_prog(
                    self.server.params, jnp.asarray(block), self._cache,
                    self._tok, jnp.asarray([len(suffix)], np.int32),
                    jnp.int32(plen), jnp.int32(slot), stored, prompt_pages,
                    temp, top_k, top_p, seed, pad_seq_len(s), first_step,
                )
            else:
                self._cache, self._tok, first, small = self._admit_cached_prog(
                    self.server.params, jnp.asarray(block), self._cache, self._tok,
                    jnp.asarray([len(suffix)], np.int32), jnp.int32(plen),
                    jnp.int32(slot), stored, temp, top_k, top_p, seed,
                    pad_seq_len(s), first_step,
                )
        else:
            pad_s = pad_seq_len(s)
            prompt = np.zeros((1, pad_s), np.int32)
            prompt[0, :s] = ids
            if self.page_size > 0:
                admitted = self._admit_prog(
                    self.server.params, jnp.asarray(prompt), self._cache,
                    self._tok, jnp.asarray([s], np.int32), jnp.int32(slot),
                    prompt_pages, temp, top_k, top_p, seed, first_step,
                )
            else:
                admitted = self._admit_prog(
                    self.server.params, jnp.asarray(prompt), self._cache, self._tok,
                    jnp.asarray([s], np.int32), jnp.int32(slot), temp, top_k, top_p,
                    seed, first_step,
                )
            if self.prefix_cache is None:
                self._cache, self._tok, first = admitted
                small = None
            else:
                self._cache, self._tok, first, small = admitted
        if self.prefix_cache is not None:
            # the scratch cache IS this prompt's prefill KV (bucketed to the
            # prompt's 16-quantum): store it so the conversation's next turn
            # prefills only its new suffix
            self.prefix_cache.put(ids, small)
        self._finish_admit_host(
            prep, lambda first=first: np.asarray(first).reshape(1, 1)
        )
        self._suspect_fp = None
        self._suspect_rid = ""

    # -- chunked prefill scheduling -------------------------------------------

    def _reserve_upto(self, slot: int, tokens: int) -> bool:
        """Grow a filling slot's page reservation to cover ``tokens``
        positions (incremental per-piece reservation). False = pool
        short: the caller waits a boundary (retirements free pages) or,
        if every fill is wedged, preempts the youngest."""
        need = -(-tokens // self.page_size)
        pages = self._row_pages.setdefault(slot, [])
        if need <= len(pages):
            return True
        if need - len(pages) > len(self._free_pages):
            return False
        for j in range(len(pages), need):
            pg = self._free_pages.pop()
            pages.append(pg)
            self._table[slot, j] = pg
        self.stats["pages_free"] = len(self._free_pages)
        return True

    def _start_fill(self, prep: dict) -> None:
        """Begin a chunked prefill on a claimed slot. A prefix hit seeds
        the slot with the stored KV (one insert program) so only the
        suffix lands piece by piece; everything else is host bookkeeping
        — the pieces themselves dispatch from the boundary scheduler."""
        slot, ids = prep["slot"], prep["ids"]
        plen = 0
        if prep["hit"] is not None:
            plen_real, stored = prep["hit"]
            # the fill frontier starts at the stored prefix ROUNDED DOWN
            # to the bucket quantum: every piece then lands 16-aligned,
            # so no piece's bucket can spill past pad16(s) (an unaligned
            # last piece near max_len would make its dynamic_update_slice
            # clamp the write window back over live KV). The <= 15 tokens
            # between the aligned frontier and the real prefix simply
            # re-prefill as part of the first suffix piece, overwriting
            # the seeded bucket's junk span on the way.
            plen = plen_real // SEQ_BUCKET * SEQ_BUCKET
            bucket = pad_seq_len(plen_real)
            if plen == 0:
                pass  # sub-bucket prefix: seeding buys nothing
            elif self.page_size > 0 and not self._reserve_upto(slot, bucket):
                # a concurrent preparation raced the seed's pages away:
                # fall back to filling the whole prompt incrementally
                plen = 0
            elif self.page_size > 0:
                n_pg = -(-bucket // self.page_size)
                page_ids = jnp.asarray(
                    np.asarray(self._row_pages[slot][:n_pg], np.int32)
                )
                with trace.span("continuous.fill_seed", prefix=plen):
                    self._cache = self._seed_prog(
                        self._cache, stored, page_ids, bucket
                    )
            else:
                with trace.span("continuous.fill_seed", prefix=plen):
                    self._cache = self._seed_prog(
                        self._cache, stored, jnp.int32(slot)
                    )
        fill = _Fill(slot, list(ids), prep["n"], dict(prep["samp"]),
                     prep["ticket"], filled=plen, fp=prep.get("fp"))
        # the fill's offset is its KV frontier: decode chunks run over
        # every slot, so this keeps the slot's garbage writes beyond the
        # real prefix (the next piece overwrites them)
        self._offsets[slot] = plen
        self._steps[slot] = 0
        self._filling[slot] = fill
        self._fill_order.append(slot)
        prep["finished"] = True
        self._steady = False  # a fill started: not a steady-decode boundary

    def _fill_piece(self, rem: int) -> tuple[int, int, bool]:
        """(bucketed piece length, real tokens taken, is-last) for a fill
        with ``rem`` prompt tokens outstanding."""
        if rem <= self.prefill_chunk:
            return pad_seq_len(rem), rem, True
        return self.prefill_chunk, self.prefill_chunk, False

    def _dispatch_pieces(self, decode_spend: int) -> bool:
        """Land this boundary's prefill pieces: FIFO over filling rows,
        one piece each, packed into the boundary budget after the decode
        rows' spend. The head piece is exempt from the budget — a budget
        smaller than the decode spend must bound prefill work per
        boundary, not starve fills outright. Returns True when at least
        one piece landed (False = every fill is page-blocked)."""
        spent = decode_spend
        landed = 0
        for slot in list(self._fill_order):
            fill = self._filling.get(slot)
            if fill is None:
                continue
            if fill.ticket.cancelled:
                # retire NOW, not at the next sweep: a cancelled lone
                # fill skipped here would read as "every fill is
                # page-blocked" and trip the preempt wedge check
                self._drop_fill(slot)
                continue
            rem = len(fill.ids) - fill.filled
            piece_len, take, last = self._fill_piece(rem)
            if (landed and self.prefill_budget > 0
                    and spent + piece_len > self.prefill_budget):
                break  # budget spent: later fills wait for the next boundary
            if self.page_size > 0:
                # the last piece also reserves the decode span — the flip
                # must never strand a row that cannot decode
                upto = (
                    pad_seq_len(len(fill.ids)) + fill.n + self._overrun
                    if last else fill.filled + piece_len
                )
                if not self._reserve_upto(slot, upto):
                    self.stats["fill_waits"] += 1
                    continue
            self._land_piece(fill, piece_len, take, last)
            spent += piece_len
            landed += 1
        return landed > 0

    def _land_piece(self, fill: _Fill, piece_len: int, take: int,
                    last: bool) -> None:
        """Dispatch one prefill piece (async). The last piece samples the
        row's first token and flips the slot from filling to decoding."""
        slot = fill.slot
        # piece dispatches are attributable to the filling request (poison
        # quarantine): a prompt that crashes the loop mid-fill must not be
        # re-admitted forever
        self._suspect_fp = fill.fp
        self._suspect_rid = fill.ticket.request_id
        self._steady = False  # a fill boundary, not steady decode
        if last:
            self._tok_host = None  # the flip program advances the device tok
        block = np.zeros((1, piece_len), np.int32)
        block[0, :take] = fill.ids[fill.filled: fill.filled + take]
        piece = jnp.asarray(block)
        offset = jnp.int32(fill.filled)
        table_row = write_page_ids = page_start = None
        if self.page_size > 0:
            table_row = jnp.asarray(self._table[slot].copy())
            # pages the piece's writes touch — [filled, filled+Sb) spans
            # at most Sb/ps + 1 of them (all reserved by _reserve_upto);
            # the touched count is static per (bucket, alignment) pair
            ps = self.page_size
            start_pg = fill.filled // ps
            end_pg = (fill.filled + piece_len - 1) // ps
            write_page_ids = jnp.asarray(
                self._table[slot, start_pg: end_pg + 1].copy()
            )
            page_start = jnp.int32(start_pg * ps)
        self.stats["prefill_pieces"] += 1
        fill.ticket.prefill_pieces += 1
        self._rec("fill_piece", slot=slot, request_id=fill.ticket.request_id,
                  tokens=take, last=last)
        if not last:
            # the fill's spans run on the ENGINE thread where the
            # transport's request context isn't set: re-bind the ticket's
            # id so the piece timeline joins the request's trace
            with trace.request_context(fill.ticket.request_id), \
                    trace.span("continuous.prefill_piece", tokens=take):
                if self.page_size > 0:
                    self._cache = self._piece_prog(
                        self.server.params, piece, self._cache,
                        table_row, offset, write_page_ids, page_start,
                    )
                else:
                    self._cache = self._piece_prog(
                        self.server.params, piece, self._cache,
                        offset, jnp.int32(slot),
                    )
            fill.filled += take
            self._offsets[slot] = fill.filled
            self._suspect_fp = None
            self._suspect_rid = ""
            return
        samp = fill.samp
        # filters ride as arrays (0 / 1.0 = off): a one-shot program has
        # no per-step sort to save, same rationale as the batched admit
        temp = np.asarray([samp.get("temperature", 0.0)], np.float32)
        top_k = np.asarray([samp.get("top_k", 0)], np.int32)
        top_p = np.asarray([samp.get("top_p", 1.0)], np.float32)
        seed = np.asarray([samp.get("seed", 0)], np.int32)
        first_step = np.asarray([samp.get("resume_step", 0)], np.int32)
        last_idx = jnp.asarray([take - 1], jnp.int32)
        with trace.request_context(fill.ticket.request_id), \
                trace.span("continuous.prefill_flip", tokens=take):
            if self.page_size > 0:
                self._cache, self._tok, first = self._piece_flip_prog(
                    self.server.params, piece, self._cache, self._tok,
                    table_row, offset, jnp.int32(slot), last_idx,
                    temp, top_k, top_p, seed, write_page_ids, page_start,
                    first_step,
                )
            else:
                self._cache, self._tok, first = self._piece_flip_prog(
                    self.server.params, piece, self._cache, self._tok,
                    offset, jnp.int32(slot), last_idx,
                    temp, top_k, top_p, seed, first_step,
                )
        del self._filling[slot]
        self._fill_order.remove(slot)
        if self.prefix_cache is not None:
            # store the freshly landed prompt KV so the conversation's
            # next turn prefills only its new suffix — parity with the
            # single-program admission paths
            bucket = pad_seq_len(len(fill.ids))
            if self.page_size > 0:
                snap = self._snap_prog(self._cache, table_row, bucket)
            else:
                snap = self._snap_prog(self._cache, jnp.int32(slot), bucket)
            self.prefix_cache.put(fill.ids, snap)
        prep = {"slot": slot, "s": len(fill.ids), "samp": fill.samp,
                "n": fill.n, "ticket": fill.ticket, "ids": fill.ids,
                "finished": False}
        self._finish_admit_host(
            prep, lambda first=first: np.asarray(first).reshape(1, 1)
        )
        self._suspect_fp = None
        self._suspect_rid = ""
        self._requeue_preempted()

    def _requeue_preempted(self) -> None:
        """A fill flipped or died: parked preempted fills may now restart
        (FIFO, ahead of newer arrivals)."""
        if self._preempted:
            self._waiting[:0] = self._preempted
            self._preempted.clear()

    def _drop_fill(self, slot: int, err: BaseException | None = None) -> None:
        """Retire a filling row early: end its stream (_DONE for a gone
        consumer, ``err`` for a deadline expiry) and free the slot and
        pages; nothing was emitted, so nothing else unwinds. The single
        early-fill-retirement path — the sweep, the piece scheduler, the
        preempt guard, and deadline expiry all route here so the
        semantics can't diverge."""
        fill = self._filling.pop(slot, None)
        if fill is not None:
            fill.ticket.out.put(_DONE if err is None else err)
        if slot in self._fill_order:
            self._fill_order.remove(slot)
        self._release_slot(slot)
        self._requeue_preempted()

    def _preempt_fill(self) -> None:
        """Every fill is page-blocked and no decode row is left to free
        pages by retiring: restart the YOUNGEST fill (it has emitted
        nothing, so a restart is exact) — its pages unblock the older
        fills. Parked, not re-queued: an immediate re-admission would
        re-grab the very pages the head fill needs (livelock)."""
        dropped = False
        for slot, fill in list(self._filling.items()):
            if fill.ticket.cancelled:
                # a disconnect racing this boundary (cancel() runs on the
                # consumer's thread) is a retirement, not pool pressure
                self._drop_fill(slot)
                dropped = True
        if dropped or not self._filling:
            return  # freed slots/pages; the next boundary progresses
        if len(self._filling) < 2:
            # cannot happen: the pool holds any single validated row's
            # whole span, so a lone fill always has its remaining pages
            raise RuntimeError(
                "page pool wedged: a lone filling row cannot reserve its "
                "next piece (pool smaller than a validated request?)"
            )
        slot = self._fill_order[-1]
        fill = self._filling.pop(slot)
        self._fill_order.remove(slot)
        self._release_slot(slot)
        self.stats["fill_preempts"] += 1
        self._rec("preempt", slot=slot, request_id=fill.ticket.request_id,
                  filled=fill.filled)
        fill.ticket.restart = True  # head-of-backlog pin: see _Ticket
        fill.ticket.preempts += 1
        self._preempted.append((fill.ids, fill.n, fill.samp, fill.ticket))
        self._backlog_add(1)  # back in the not-yet-admitted set

    def _overlap_prep(self) -> None:
        """Boundary-prep overlap: called while dispatched programs are
        executing, BEFORE the loop blocks on the oldest result. Drains the
        submit queue into the FIFO backlog (same arrival order the main
        pop preserves) and pre-computes the expensive host-side admission
        prep — the poison fingerprint (an O(prompt) hash) and the
        prefix-cache lookup — for the backlog's head, so the next
        admission boundary swaps prepared inputs and dispatches instead of
        doing that work serially between device programs. A lookup
        memoized here can go stale against a store that lands afterwards;
        that misses an optimization, never correctness (the admission
        paths are exact with or without a hit)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                # the close sentinel is strictly last (close() enqueues it
                # under the same lock submits take): hand it back for the
                # main pop's close path
                self._q.put(None)
                break
            if isinstance(item, list):
                for row_item in item:
                    self._backlog_insert(row_item)
            else:
                self._backlog_insert(item)
        # only the head can admit next boundary; +2 covers slots that the
        # in-flight programs' plans just freed
        limit = len(self._free) + 2
        for item in self._waiting[:limit]:
            ticket = item[3]
            if ticket.cancelled or ticket in self._prep_memo:
                continue
            fp = _fingerprint(item[0], item[1])
            hit = None
            if self.prefix_cache is not None:
                hit = self.prefix_cache.lookup(item[0], max_total=self.max_len)
            self._prep_memo[ticket] = (fp, hit)

    def _pick_depth(self) -> int:
        """Chunks per device program for THIS dispatch. Depth > 1 only in
        steady decode: any pending boundary event (a fill piece due, a
        backlog/queue item wanting admission, a first token owed) snaps
        back to per-chunk dispatch so that event isn't delayed by a deep
        program's span. The cap at every row's remaining budget keeps the
        deepest write inside the validated ``_overrun`` span (a row that
        finishes mid-program keeps writing to the program's end, exactly
        like the existing mid-chunk finish — never more than one
        chunk_size past its budget).

        Depth walks a POWER-OF-TWO ladder (1, 2, 4, ... cap), not every
        integer: each distinct depth is a separate compiled ``n_steps``
        variant, and an arbitrary-depth tail (rem 3 chunks -> depth 3,
        rem 2 -> depth 2...) would pay a fresh XLA compile MID-LOAD the
        first time every tail size appears — measured as hundreds of ms
        landing in the steady-decode boundary histogram. The ladder
        bounds the variant count at log2(cap)+1 while keeping the deep
        steady-state program."""
        if self._depth_cap <= 1 or not self._rows:
            return 1
        if (self._filling or self._waiting or self._preempted
                or self._first_pending or not self._q.empty()):
            return 1
        rem_min = min(r.budget - r.emitted for r in self._rows.values())
        fit = min(self._depth_cap, rem_min // self.chunk_size)
        if fit <= 1:
            return 1
        depth = 1
        while depth * 2 <= fit:
            depth *= 2
        return depth

    def _dispatch_chunk(self) -> tuple:
        """Dispatch one decode program (async) and PLAN its emissions now.
        Take counts and retirements are value-independent (budgets only),
        so scheduling runs a full program ahead of token delivery — the
        host's dispatch round-trip (tens of ms on a tunneled rig) overlaps
        the device decoding the chunks in flight instead of serializing
        with it. In steady decode the program scans ``depth`` chunks
        (_pick_depth), amortizing the fixed dispatch cost, and the token
        block's device->host copy STARTS here so the lagged readback in
        ``_deliver`` finds the bytes already on their way."""
        depth = self._pick_depth()
        n_steps = depth * self.chunk_size
        # filters only when an ACTIVE row asked: the None variant skips the
        # per-step full-vocab sort (retired slots' stale values are garbage
        # rows whose tokens are discarded anyway)
        active = list(self._rows)
        filtered = bool(self._use_filters[active].any())
        self._rec("dispatch", depth=depth, n_steps=n_steps,
                  active=len(self._rows), devices=self.mesh_devices)
        # the step annotation names this dispatch in an on-demand profiler
        # capture (POST /admin/profile) with the SAME ordinal the flight
        # ring records, so XLA timeline steps join engine events 1:1
        with trace.span("continuous.chunk", active=len(self._rows),
                        depth=depth), \
                step_trace_annotation("continuous.chunk",
                                      step_num=self.stats["dispatches"]):
            # .copy() is load-bearing: jax zero-copy-aliases host numpy
            # buffers (CPU backend) and transfers lazily, while this loop
            # mutates the originals (retirement resets, next admissions)
            # possibly BEFORE the in-flight chunk reads them — each dispatch
            # gets private snapshots nobody mutates
            args = [
                jnp.asarray(self._offsets.copy()), jnp.asarray(self._steps.copy()),
                jnp.asarray(self._temp.copy()),
                jnp.asarray(self._top_k.copy()) if filtered else None,
                jnp.asarray(self._top_p.copy()) if filtered else None,
                jnp.asarray(self._seeds.copy()),
            ]
            if self.page_size > 0:
                args.insert(0, jnp.asarray(self._table.copy()))
            self._cache, self._tok, toks_dev = self._chunk(
                self.server.params, self._cache, self._tok, *args,
                n_steps=n_steps,
            )
        # start the device->host token copy NOW: it streams back while the
        # device runs the next program, so the lagged _deliver sync finds
        # the bytes resident instead of paying the full fetch round-trip
        copy_to_host_async(toks_dev)
        self._tok_host = None  # the in-flight program advances tok
        self.stats["chunks"] += depth
        self.stats["dispatches"] += 1
        # pad accounting: live rows (decoding + filling) vs the program's
        # static max_slots row dimension, weighted by chunk-equivalents
        n_live = len(self._rows) + len(self._filling)
        self.stats["decode_rows"] += self.max_slots * depth
        self.stats["decode_pad_rows"] += (
            max(self.max_slots - n_live, 0) * depth
        )
        if self.page_size > 0 and self._table is not None:
            # ragged paged sweep: the in-place kernel stops at the batch's
            # actual max page (ops/paged_attention), so the interesting
            # number is how much of the static table width a dispatch
            # really walks — pages_swept / pages_swept_possible
            pps = int(self._table.shape[1])
            blocks = int(
                min(pps, (int(self._offsets.max()) + n_steps)
                    // self.page_size + 1)
            )
            self.stats["pages_swept"] = (
                self.stats.get("pages_swept", 0) + blocks
            )
            self.stats["pages_swept_possible"] = (
                self.stats.get("pages_swept_possible", 0) + pps
            )
        self._depth_last = depth
        if depth > self.stats["dispatch_depth_max"]:
            self.stats["dispatch_depth_max"] = depth
        self._inflight_chunks += depth
        if self._inflight_chunks > self.stats["sync_lag_chunks_max"]:
            self.stats["sync_lag_chunks_max"] = self._inflight_chunks
        now = time.monotonic()
        if self._last_chunk_t is not None:
            # decode-boundary cadence: the max gap between consecutive
            # chunk dispatches while rows were active IS the admission
            # stall a decoding client can observe (monolithic prefills
            # used to sit here for the whole prompt)
            gap_ms = (now - self._last_chunk_t) * 1e3
            if gap_ms > self.stats["stall_ms_max"]:
                self.stats["stall_ms_max"] = round(gap_ms, 3)
            # the boundary's HOST cost: the dispatch-to-dispatch gap minus
            # the time spent blocked on device results — what the pipelined
            # scheduler is supposed to keep off the critical path
            host_ms = max(0.0, gap_ms - self._sync_wait_s * 1e3)
            self._boundary_host_ms.append(host_ms)
            if (self._steady
                    and self._boundary_syncs
                    > self.stats["host_syncs_per_boundary"]):
                # steady decode must cost at most ONE blocking sync per
                # boundary (the lagged token readback) — tests assert this
                self.stats["host_syncs_per_boundary"] = self._boundary_syncs
        self._sync_wait_s = 0.0
        self._boundary_syncs = 0
        self._steady = True
        self._last_chunk_t = now
        self._offsets += n_steps
        self._steps += n_steps
        for slot, fill in self._filling.items():
            # filling slots don't decode: their offsets stay pinned at the
            # fill frontier (the chunk's garbage writes land beyond it and
            # the next piece overwrites them)
            self._offsets[slot] = fill.filled
            self._steps[slot] = 0
        plan = []
        taken = 0
        for slot, row in list(self._rows.items()):
            # the chunk's final carry is this row's next (undelivered)
            # token — the spec step must emit it before verifying onward
            row.tok_pending = True
            take = min(n_steps - row.skip, row.budget - row.emitted)
            row.emitted += max(take, 0)
            taken += max(take, 0)
            done = row.emitted >= row.budget
            plan.append((slot, row, row.skip, take, done))
            row.skip = 0
            if done:  # slot reuse is safe: a re-admission's cache insert is
                # data-ordered after the in-flight chunk's writes
                del self._rows[slot]
                self._release_slot(slot)  # idle rows write harmlessly at 0
        self._tokens_in_flight += taken
        if self._tokens_in_flight > self.stats["tokens_in_flight_peak"]:
            self.stats["tokens_in_flight_peak"] = self._tokens_in_flight
        return toks_dev, plan, depth

    def _deliver_firsts(self) -> None:
        """Hand this iteration's admitted rows their prefill tokens. Blocks
        only on the prefills (ordered before any chunk dispatched after
        them), so N admissions pay one round-trip, not N."""
        firsts, self._first_pending = self._first_pending, []
        for row, first_ref, done in firsts:
            if row.ticket.cancelled:  # consumer gone: free the slot, no put
                row.out.put(_DONE)
                row.closed = True
                continue
            t0 = time.monotonic()
            first_np = first_ref()
            # device-wait, not host work: keep it out of boundary_host_ms
            self._sync_wait_s += time.monotonic() - t0
            ticket = row.ticket
            if not ticket.t_first:
                ticket.t_first = time.monotonic()
                # the histograms feed HERE, once per request, from the
                # same stamps the client's timing block reports
                if ticket.t_submit:
                    if ticket.t_admit:
                        self.hist_queue_ms.observe(
                            (ticket.t_admit - ticket.t_submit) * 1e3)
                    self.hist_ttft_ms.observe(
                        (ticket.t_first - ticket.t_submit) * 1e3)
            if row.seq is not None:
                row.seq.append(int(first_np[0, 0]))
            row.out.put(first_np)
            self.rate_tokens.add(1)
            if row.stops and int(first_np[0, 0]) in row.stops and not done:
                row.out.put(_DONE)
                row.closed = True  # plan retires the slot next dispatch
                self._rec("eos", slot=row.slot,
                          request_id=ticket.request_id,
                          reason="stop", emitted=row.emitted)
            elif done:
                row.out.put(_DONE)
                self._rec("eos", slot=row.slot,
                          request_id=ticket.request_id,
                          reason="budget", emitted=row.emitted)

    def _put_pieces(self, row: _Row, arr: np.ndarray) -> None:
        """Hand a row its tokens in flush-cadence pieces: a depth-D
        program's take splits into <= chunk_size slices so streaming
        clients keep the per-chunk flush granularity the serial path had
        (serve.py writes one SSE flush per queue item)."""
        cs = self.chunk_size
        for j in range(0, arr.shape[1], cs):
            row.out.put(arr[:, j:j + cs])

    def _deliver(self, pending: tuple | None) -> None:
        """Block on an in-flight program's tokens and hand them to waiters.
        This is the boundary's ONE lagged device sync: the async copy
        started at dispatch, so in steady pipelined decode this wait is
        the residue the device hasn't streamed back yet, not a full
        round-trip. The block's extra trailing column is the lookahead
        carry (each row's next, undelivered token) — cached host-side for
        the spec-mode transition. Stop hits here lag dispatch by the
        in-flight span; the row just closes and its slot frees at the next
        sweep (its offsets die with the slot — the overrun rewind is the
        slot release, exactly like the speculative path's rejected tail)."""
        if pending is None:
            return
        toks_dev, plan, depth = pending
        t0 = time.monotonic()
        toks = np.asarray(toks_dev)
        wait_s = time.monotonic() - t0
        self._sync_wait_s += wait_s
        self._boundary_syncs += 1
        self._inflight_chunks = max(0, self._inflight_chunks - depth)
        self._rec("readback", depth=depth, rows=len(plan),
                  wait_ms=round(wait_s * 1e3, 3))
        self.rate_tokens.add(sum(max(take, 0) for _, _, _, take, _ in plan))
        # valid until the next dispatch/admission advances the device tok
        # (the dispatch path resets it to None first)
        self._tok_host = toks[:, -1].copy()
        for slot, row, skip, take, done in plan:
            self._tokens_in_flight = max(0, self._tokens_in_flight - max(take, 0))
            if row.closed:
                continue  # stop token already ended the row (and its queue)
            if row.ticket.cancelled:
                # client disconnected mid-stream: stop piling tokens into a
                # queue nobody drains; the sweep frees the slot next round
                row.out.put(_DONE)
                row.closed = True
                continue
            piece = toks[slot : slot + 1, skip : skip + take] if take > 0 else None
            if piece is not None and row.seq is not None:
                row.seq.extend(piece[0].tolist())
            if piece is not None and row.stops:
                from modelx_tpu.models.decode import stop_cut

                cut = stop_cut(piece[0].tolist(), row.stops)
                if cut is not None:
                    self._put_pieces(row, piece[:, :cut])  # include the stop
                    row.out.put(_DONE)
                    row.closed = True
                    self._rec("eos", slot=slot,
                              request_id=row.ticket.request_id,
                              reason="stop", emitted=row.emitted)
                    continue
            if piece is not None:
                self._put_pieces(row, piece)
            if done:
                row.out.put(_DONE)
                self._rec("eos", slot=slot, request_id=row.ticket.request_id,
                          reason="budget", emitted=row.emitted)

    @staticmethod
    def _deadline_passed(ticket: _Ticket, now: float) -> bool:
        return (not ticket.cancelled and ticket.deadline is not None
                and now > ticket.deadline)

    def _sweep_backlog(self) -> None:
        """Purge dead backlog entries at EVERY boundary, deadline knob or
        not: cancelled rows (the client is gone — their corpses must not
        occupy --max-queue-depth budget and shed live traffic with 429s)
        end with _DONE, past-deadline rows with the 504 error — both
        without ever taking a slot."""
        now = time.monotonic()
        for lst, state in ((self._waiting, "waiting for a slot"),
                           (self._preempted, "waiting for pages")):
            keep = []
            for item in lst:
                ticket = item[3]
                if ticket.cancelled:
                    self._backlog_sub(1)
                    self._prep_memo.pop(ticket, None)
                    ticket.out.put(_DONE)
                elif self._deadline_passed(ticket, now):
                    self.stats["expired"] += 1
                    self._rec("deadline", request_id=ticket.request_id,
                              state=state)
                    self._backlog_sub(1)
                    self._prep_memo.pop(ticket, None)
                    ticket.out.put(self._deadline_error(ticket, state))
                else:
                    keep.append(item)
            lst[:] = keep

    def _expire_deadlines(self) -> None:
        """Expire past-deadline ADMITTED requests at the chunk boundary:
        filling rows release their slot and pages (nothing was emitted),
        decoding rows fail mid-stream and their slot frees at this sweep.
        Overload turns into fast, observable 504s instead of requests that
        finish long after their caller gave up."""
        now = time.monotonic()
        for slot, fill in list(self._filling.items()):
            if self._deadline_passed(fill.ticket, now):
                self.stats["expired"] += 1
                self._rec("deadline", slot=slot,
                          request_id=fill.ticket.request_id,
                          state="prefilling")
                self._drop_fill(
                    slot, self._deadline_error(fill.ticket, "prefilling")
                )
        for row in self._rows.values():
            if not row.closed and self._deadline_passed(row.ticket, now):
                self.stats["expired"] += 1
                self._rec("deadline", slot=row.slot,
                          request_id=row.ticket.request_id,
                          state="decoding")
                row.out.put(self._deadline_error(row.ticket, "decoding"))
                row.closed = True  # the sweep below frees the slot

    def _sweep_closed(self) -> None:
        """Free the slots of rows a stop token ended at delivery time or a
        client abandoned (ticket.cancelled) — BEFORE admission and the next
        dispatch, so a waiting request takes the slot immediately and no
        dead-row chunk is dispatched."""
        self._sweep_backlog()
        self._expire_deadlines()
        for slot, row in list(self._rows.items()):
            if row.ticket.cancelled and not row.closed:
                row.out.put(_DONE)  # unblock any racing drain
                row.closed = True
            if row.closed:
                del self._rows[slot]
                self._release_slot(slot)
        for slot, fill in list(self._filling.items()):
            if fill.ticket.cancelled:  # consumer gone mid-fill: nothing
                # was emitted, so the slot and pages just free
                self._drop_fill(slot)

    def _run(self) -> None:
        """The engine thread: run the loop, and — supervision — restart it
        after a crash. ``_loop`` itself drains every waiter on death (no
        request ever hangs); this outer loop decides whether the engine
        comes back: exponential crash-loop backoff between restarts, and a
        circuit breaker (``max_crashes`` within ``crash_window_s``) that
        leaves the engine broken when restarting clearly isn't helping."""
        while True:
            verdict = self._loop()
            if verdict != "crashed":
                return
            # backoff grows with the number of recent crashes: one isolated
            # crash restarts almost immediately, a crash loop slows down
            delay = self.restart_backoff_s * (2 ** max(0, len(self._crash_times) - 1))
            self._closed_ev.wait(delay)
            with self._close_lock:
                bail = self._closed
                if bail and self._broken is None:
                    self._broken = EngineBrokenError("closed during restart")
            if bail:
                # requests enqueued during the backoff must not hang
                self._drain_queue(EngineBrokenError("continuous batcher closed"))
                self._state = "stopped"
                return
            self._rebuild()
            with self._close_lock:
                self._restarts += 1
                self.stats["engine_restarts"] = self._restarts
                self._state = "running"
            logging.getLogger("modelx.serve").warning(
                "continuous engine restarted (restart #%d)", self._restarts
            )

    def _watchdog(self) -> None:
        """Hang monitor (``boundary_watchdog_s`` > 0): the supervisor only
        heals CRASHES — a device dispatch that never returns (real on TPU:
        a wedged transfer or a hung collective) would hold the loop, and
        every waiter, forever. This thread watches the loop's per-boundary
        progress stamp; a stall past the window with rows active fails
        every waiter NOW (the ticket queues are thread-safe, and the
        wedged loop is inside a device call, not mutating row state),
        flips the state to "restarting" so /healthz drains, and leaves a
        pending error the loop raises the moment the dispatch returns —
        the stall then feeds the ordinary crash/restart/breaker path. A
        second put from that path is harmless: consumers stop at their
        first error item. The poll is window/4 but capped at 250ms — the
        check is a handful of attribute reads, and a short cadence keeps
        detection prompt even under a large warm-up-safe window (or one
        an operator tightens on a live engine once compiles clear)."""
        while not self._closed_ev.wait(
                max(0.01, min(0.25, self.boundary_watchdog_s / 4))):
            if self._watch_stall is not None or self._state != "running":
                continue
            last = self._progress_t
            busy = bool(self._rows or self._filling or self._first_pending)
            if not busy or last is None:
                continue
            stalled_s = time.monotonic() - last
            if stalled_s <= self.boundary_watchdog_s:
                continue
            err = EngineBrokenError(
                f"boundary watchdog: no dispatch progress in "
                f"{stalled_s:.2f}s (window {self.boundary_watchdog_s}s)"
            )
            self._watch_stall = err
            self.stats["watchdog_stalls"] += 1
            self._rec("watchdog_stall", stalled_s=round(stalled_s, 3),
                      window_s=self.boundary_watchdog_s)
            self._state = "restarting"  # readiness drains while wedged
            # the wedged loop cannot dump for itself (it is inside a device
            # call): the watchdog writes the black box NOW, while the
            # evidence — ring + per-slot state — still describes the stall
            self._flight_dump("watchdog", err)
            logging.getLogger("modelx.serve").error(
                "continuous engine stalled: no boundary progress in %.2fs "
                "(watchdog %.2fs) — failing %d active row(s)",
                stalled_s, self.boundary_watchdog_s,
                len(self._rows) + len(self._filling),
            )
            for row in list(self._rows.values()):
                row.out.put(err)
            for fill in list(self._filling.values()):
                fill.ticket.out.put(err)

    def _rebuild(self) -> None:
        """Fresh engine state after a crash: new KV cache (or page pool),
        zeroed host vectors, every slot free. The compiled programs are
        pure functions of their inputs and are REUSED — restart cost is one
        cache allocation, not a recompile. The prefix cache is preserved:
        its entries are keyed by token prefix and independent of slot
        state, so multi-turn conversations keep their fast path across a
        restart."""
        if self.page_size > 0:
            self._free_pages = list(range(1, self.num_pages))
            self._table = np.zeros(
                (self.max_slots, self._pages_per_slot), np.int32
            )
            self._row_pages = {}
            self._cache = jax.tree_util.tree_map(
                lambda leaf: jnp.zeros(
                    (self.num_pages, self.page_size) + leaf.shape[2:], leaf.dtype
                ),
                self._init_cache(1, self.page_size),
            )
            self.stats["pages_free"] = len(self._free_pages)
        else:
            self._cache = self._init_cache(self.max_slots, self.max_len)
        self._cache = self._place_cache(self._cache)
        self._tok = jnp.zeros((self.max_slots, 1), jnp.int32)
        self._offsets[:] = 0
        self._steps[:] = 0
        self._temp[:] = 0.0
        self._top_k[:] = 0
        self._top_p[:] = 1.0
        self._seeds[:] = 0
        self._use_filters[:] = False
        self._rows = {}
        self._free = list(range(self.max_slots))
        self._first_pending = []
        self._filling = {}
        self._fill_order = []
        self._preempted = []
        self._suspect_fp = None
        self._suspect_rid = ""
        self._last_chunk_t = None
        self._prep_memo = {}
        self._tok_host = None
        self._watch_stall = None
        self._progress_t = None
        if self.flightrec is not None:
            # fresh flight: the rebuilt engine must not replay the dead
            # engine's timeline into its next black box
            self.flightrec.reset()
            self._rec("rebuild", restarts=self._restarts + 1)
        self._sync_wait_s = 0.0
        self._boundary_syncs = 0
        self._steady = False
        self._tokens_in_flight = 0
        self._inflight_chunks = 0
        self._depth_last = 1

    def _loop(self) -> str:
        from collections import deque

        pending: "deque[tuple]" = deque()  # in-flight chunks, oldest first
        try:
            while True:
                if self._watch_stall is not None:
                    # the watchdog declared this boundary stalled while a
                    # dispatch was wedged; it already failed the waiters —
                    # unwind into the supervisor so the state rebuilds
                    raise self._watch_stall
                self._progress_t = time.monotonic()
                self._sweep_closed()
                if not self._rows:
                    # idle (or fill-only) gaps between chunks aren't
                    # decode stalls — don't let them pollute stall_ms_max
                    # (or the boundary host-time histogram)
                    self._last_chunk_t = None
                    self._sync_wait_s = 0.0
                    self._boundary_syncs = 0
                # gather everything admissible (up to free slots), FIFO: the
                # backlog of earlier arrivals that found no slot goes first.
                # Preparation claims the slot/pages immediately so the
                # admissibility check for the NEXT item sees true capacity;
                # the device dispatches happen together below so same-bucket
                # bursts share one program. Block on the queue only when
                # fully idle with nothing in flight AND no admitted row
                # still owed its (async) first token — a lone budget-1
                # request admits, frees its slot, and would otherwise hang
                # its waiter by blocking here before _deliver_firsts runs
                to_admit: list = []
                while True:
                    if self._waiting:
                        if not self._admits_now(self._waiting[0]):
                            break  # still contended: decode on, retry later
                        self._gather_prep(self._waiting.pop(0), to_admit)
                        continue
                    block = (not self._rows and not self._filling
                             and not pending
                             and not self._first_pending and not to_admit)
                    try:
                        item = self._q.get(block=block)
                    except queue.Empty:
                        break
                    if (block and self.burst_window_ms > 0
                            and item is not None and self.max_slots > 1):
                        # the engine was fully idle and one request just
                        # arrived: wait a beat for its co-arrivals so a
                        # burst admits as ONE program and decodes in step
                        # (independent clients racing this loop otherwise
                        # split across admission boundaries — each straggler
                        # group then costs whole extra chunks). A lone
                        # request pays ~1 ms against a ~50+ ms admission
                        # dispatch; requests landing mid-decode never wait.
                        # Applies to submit_many lists too: a single-row
                        # generate IS a 1-row list, and independent clients'
                        # lists co-arrive exactly like tuples do.
                        time.sleep(self.burst_window_ms / 1e3)
                    if isinstance(item, list):
                        # a submit_many burst: route through the FIFO backlog
                        # so the whole burst hits ONE admission boundary
                        # (and shares an admit program) regardless of how
                        # fast this loop drains the queue
                        for row_item in item:
                            self._backlog_insert(row_item)
                        continue
                    if item is None:
                        err = RuntimeError("continuous batcher closed")
                        for prep in to_admit:  # claimed a slot, never decoded
                            prep["ticket"].out.put(err)
                        self._deliver_firsts()
                        while pending:
                            # deliver-then-pop: a chunk that raises stays in
                            # the deque so the except-path failsafe fails its
                            # plan rows (they may already be out of _rows)
                            self._deliver(pending[0])
                            pending.popleft()
                        self._fail_active(err)
                        self._state = "stopped"
                        return "closed"
                    if not self._admits_now(item):
                        # no slot (or, paged, not enough free pages): hold in
                        # the FIFO backlog and decode on — a retire this
                        # chunk frees capacity for it
                        self._backlog_insert(item)
                        break
                    self._gather_prep(item, to_admit)
                if to_admit:
                    self._admit_all(to_admit)
                if self._spec_ok():
                    # single greedy row: switch to speculative verify steps
                    # (fewer device steps per token beats pipeline depth
                    # when there is nothing to pipeline WITH). Drain all
                    # in-flight chunks + first tokens so the row's history
                    # is complete, then run one verify round.
                    self._deliver_firsts()
                    while pending:
                        self._deliver(pending[0])  # deliver-then-pop: see above
                        pending.popleft()
                    self._sweep_closed()  # a stop may just have closed it
                    if self._spec_ok():
                        self._spec_step()
                    continue
                n_decode = len(self._rows)
                if self._rows:
                    # keep up to pipeline_depth chunks in flight: plans are
                    # value-independent, so deeper dispatch is exact, and the
                    # oldest chunk's fetch below overlaps the younger chunks'
                    # device time. Go deep only when nothing is waiting for
                    # a slot, nothing new sits in the queue, and no fill
                    # wants its piece interleaved at every boundary.
                    pending.append(self._dispatch_chunk())
                    while (len(pending) < self.pipeline_depth and self._rows
                           and not self._filling
                           and not self._waiting and self._q.empty()):
                        pending.append(self._dispatch_chunk())
                if self._filling:
                    # prefill pieces ride the boundary AFTER the decode
                    # chunk: decode rows spend first, pieces pack into the
                    # budget's remainder — a long admission can no longer
                    # freeze the running batch for its whole prompt
                    landed = self._dispatch_pieces(n_decode * self.chunk_size)
                    if (not landed and self._filling and not self._rows
                            and not pending and not self._first_pending):
                        # every fill is page-blocked and nothing is left
                        # to retire: restart the youngest to break the tie
                        self._preempt_fill()
                # deliveries overlap the chunks just dispatched.
                # Deliver-then-pop: a chunk whose fetch raises must stay in
                # the deque so _deliver_failsafe fails its plan rows (plan
                # retirees are already out of _rows and _fail_active's reach)
                self._deliver_firsts()
                if pending:
                    # the dispatched programs are executing: do the NEXT
                    # admissions' host prep now (queue drain, fingerprint,
                    # prefix lookup), THEN block on the oldest result —
                    # boundary prep rides inside device time
                    self._overlap_prep()
                    self._deliver(pending[0])
                    pending.popleft()
        except BaseException as e:  # engine death must not hang waiters
            logging.getLogger("modelx.serve").exception(
                "continuous engine loop died"
            )
            now = time.monotonic()
            err = (
                e if isinstance(e, ServingError)
                else EngineBrokenError(f"engine loop died: {e!r}")
            )
            if err is not e:
                err.__cause__ = e
            with self._close_lock:
                # circuit breaker: crashes inside the window beyond the
                # budget mean restarting isn't helping — stay broken so
                # /healthz flips and the orchestrator replaces the pod.
                # Decided (and _broken published) under the SAME lock
                # submit checks, so no request can slip into the queue
                # after the broken drain below and hang forever.
                self._crash_times = [
                    t for t in self._crash_times if now - t < self.crash_window_s
                ]
                self._crash_times.append(now)
                broken = (
                    not self.supervise
                    or self._closed
                    or len(self._crash_times) > self.max_crashes
                )
                if broken:
                    self._broken = err
                    self._state = "broken"
                else:
                    self._state = "restarting"
            if self._suspect_fp is not None:
                # the death happened while dispatching ONE request's
                # admission/fill work: charge its quarantine budget
                self._poison[self._suspect_fp] = (
                    self._poison.get(self._suspect_fp, 0) + 1
                )
                self._suspect_fp = None
            self._rec("crash", request_id=self._suspect_rid,
                      error=repr(e)[:200],
                      verdict="broken" if broken else "crashed")
            if e is not self._watch_stall:
                # a watchdog stall already dumped mid-wedge, with the
                # pre-unwind slot state; don't overwrite that evidence
                self._flight_dump("circuit-break" if broken else "crash", err)
            self._suspect_rid = ""
            self._deliver_failsafe(pending, err)
            self._fail_active(err, drain_queue=broken)
            return "broken" if broken else "crashed"

    def _deliver_failsafe(self, pending, err: BaseException) -> None:
        """On engine death, rows in an undelivered plan (or with undelivered
        prefill tokens) were possibly already removed from _rows — fail them
        directly so their waiters don't hang."""
        for row, _first, _done in self._first_pending:
            row.out.put(err)
        self._first_pending = []
        for _toks_dev, plan, _depth in pending:
            for _slot, row, _skip, _take, _done in plan:
                row.out.put(err)
        self._tokens_in_flight = 0
        self._inflight_chunks = 0

    @staticmethod
    def _is_batch(item) -> bool:
        samp = item[2]
        return isinstance(samp, dict) and samp.get("priority") == "batch"

    def _backlog_insert(self, item) -> None:
        """Priority-aware FIFO: an interactive item queues ahead of the
        TRAILING run of batch items, FIFO within each class — when the
        backlog is mixed, the boundary scheduler admits interactive work
        first (the router's shed-batch-first contract, continued inside
        the engine). Two bounds on the cut-in: a restart-pinned ticket
        (a preempted fill spliced at the head — its exact restart must
        stay ahead of newer arrivals) is never crossed, and the backward
        scan touches only the trailing batch run, so with no batch work
        queued (the universal case) this IS a plain O(1) append."""
        if not self._is_batch(item):
            i = len(self._waiting)
            while i > 0:
                queued = self._waiting[i - 1]
                if not self._is_batch(queued) or queued[3].restart:
                    break
                i -= 1
            if i < len(self._waiting):
                self._waiting.insert(i, item)
                return
        self._waiting.append(item)

    def _backlog_add(self, n: int) -> None:
        with self._close_lock:
            self._backlog += n

    def _backlog_sub(self, n: int) -> None:
        with self._close_lock:
            self._backlog = max(0, self._backlog - n)

    def _drain_queue(self, err: BaseException) -> None:
        """Fail every row still sitting in the submit queue (crash, close,
        or closed-during-restart paths)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is None:
                continue
            rows = item if isinstance(item, list) else [item]
            self._backlog_sub(len(rows))
            for row_item in rows:
                row_item[3].out.put(err)

    def _fail_active(self, err: BaseException, drain_queue: bool = True) -> None:
        for row in self._rows.values():
            row.out.put(err)
        self._rows.clear()
        for fill in self._filling.values():  # mid-fill rows have waiters
            fill.ticket.out.put(err)
        self._filling.clear()
        self._fill_order.clear()
        for item in self._preempted:  # parked fills too
            item[3].out.put(err)
        self._backlog_sub(len(self._preempted))
        self._preempted.clear()
        for item in self._waiting:  # FIFO backlog items have waiters too
            item[3].out.put(err)
        self._backlog_sub(len(self._waiting))
        self._waiting.clear()
        self._prep_memo.clear()  # memoized prep died with its backlog
        if drain_queue:
            # broken/close: nothing will ever serve the queue — fail it.
            # A supervised restart SKIPS this: queued rows were never
            # touched by the engine, so they survive intact and admit
            # normally once the rebuilt loop comes back up.
            self._drain_queue(err)

    # -- public API -----------------------------------------------------------

    def snapshot(self) -> dict:
        """Counters + live gauges for the metrics endpoint and bench:
        cumulative stats (chunks/admitted/active_peak, prefill_pieces,
        stall_ms_max, spec_* when speculating, pages_* when paged) plus
        the instantaneous active/filling/waiting row counts — operators
        and the bench read THIS, not engine internals."""
        snap = dict(self.stats)
        snap["active"] = len(self._rows)
        snap["filling"] = len(self._filling)
        snap["waiting"] = len(self._waiting) + len(self._preempted)
        # pipelined-dispatch surface: the effective depth of the last
        # program, instantaneous in-flight gauges, and the per-boundary
        # host-overhead histogram (dispatch-to-dispatch gap minus the
        # blocking token-fetch wait) — the observable the ISSUE 7 win is
        # measured by
        snap["dispatch_depth"] = self._depth_last
        snap["tokens_in_flight"] = self._tokens_in_flight
        snap["sync_lag_chunks"] = self._inflight_chunks
        # snapshot() runs on HTTP handler threads while the engine loop
        # appends: list(deque) is one C-level copy (atomic under the GIL,
        # no Python re-entry for float elements); the retry covers any
        # interpreter where a concurrent append still surfaces as the
        # "deque mutated during iteration" RuntimeError
        try:
            hist_list = list(self._boundary_host_ms)
        except RuntimeError:
            hist_list = list(self._boundary_host_ms)
        if hist_list:
            hist = np.asarray(hist_list, np.float64)
            snap["boundary_host_ms_p50"] = round(float(np.percentile(hist, 50)), 3)
            snap["boundary_host_ms_p99"] = round(float(np.percentile(hist, 99)), 3)
            snap["boundary_host_ms_count"] = int(hist.size)
        # padding tax (ISSUE 17): fraction of dispatched decode row-chunks
        # that carried no live request, plus — paged in-place mode — how
        # much of the static page-table width the ragged sweep actually
        # walked (1.0 would mean the pow2 bucket was always full)
        if self.stats.get("decode_rows"):
            snap["pad_fraction"] = round(
                self.stats["decode_pad_rows"] / self.stats["decode_rows"], 4
            )
        if self.stats.get("pages_swept_possible"):
            snap["pages_swept_fraction"] = round(
                self.stats["pages_swept"]
                / self.stats["pages_swept_possible"], 4
            )
        # per-request latency histograms (ISSUE 13): present once a first
        # token delivered — the gate mirrors boundary_host_ms_*, so an
        # idle engine's snapshot keeps its pre-PR shape
        qh = self.hist_queue_ms.snapshot()
        if qh["count"]:
            snap["queue_ms_hist"] = qh
        th = self.hist_ttft_ms.snapshot()
        if th["count"]:
            snap["ttft_ms_hist"] = th
        # supervision + bounded-admission surface: the operator's view of
        # the self-healing layer (engine_restarts rides in from stats)
        snap["engine_state"] = self._state
        # serving topology: the mesh the engine's programs compiled under
        # and the device count its chunk work spreads over — the labels a
        # fleet dashboard joins per-device throughput against
        from modelx_tpu.parallel.mesh import mesh_str

        snap["mesh"] = mesh_str(self.mesh)
        snap["mesh_devices"] = self.mesh_devices
        snap["quarantined"] = sum(
            1 for c in self._poison.values() if c >= self.POISON_CRASHES
        )
        snap["queue_depth"] = self._backlog
        if self.max_queue_depth > 0:
            snap["max_queue_depth"] = self.max_queue_depth
        if self.request_timeout_s > 0:
            snap["request_timeout_s"] = self.request_timeout_s
        # windowed rates (ISSUE 15): recent-rate truth without a scraper —
        # tokens delivered per second over the 1m/5m trailing windows
        snap["tokens_per_s_1m"] = round(self.rate_tokens.rate(60), 4)
        snap["tokens_per_s_5m"] = round(self.rate_tokens.rate(300), 4)
        if self.flightrec is not None:
            snap["flightrec_events"] = self.flightrec.total
        if self.device_telemetry:
            # measured device occupancy (utils/devmem): accountant truth
            # (or the live-buffer census on backends without one) next to
            # the engine's own estimates; `source` says which it was
            dm = devmem.sample()
            snap["hbm_bytes_in_use"] = dm["hbm_bytes_in_use"]
            snap["hbm_bytes_reservable"] = dm["hbm_bytes_reservable"]
            snap["hbm_source"] = dm["source"]
        return snap

    @property
    def engine_state(self) -> str:
        """running | restarting | broken | stopped — what /healthz reads."""
        return self._state

    def _validate(self, ids: list[int], max_new_tokens: int) -> None:
        s = len(ids)
        if s < 1:
            raise ValueError("empty prompt row")
        # + overrun margin: the slot keeps writing to the end of its last
        # chunk (or speculative verify block) even past the budget; those
        # positions must exist
        need = pad_seq_len(s) + max_new_tokens + self._overrun
        if need > self.max_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds the "
                f"engine's max_len {self.max_len} (margin {self._overrun})"
            )
        if self.page_size > 0 and self._need_pages(ids, max_new_tokens) > self.num_pages - 1:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) needs more "
                f"pages than the engine's pool holds "
                f"({self.num_pages - 1} x {self.page_size} tokens)"
            )

    def _check_quarantine(self, ids, n: int) -> None:
        if not self._poison:
            return  # the universal case: no crash ever attributed — free
        crashes = self._poison.get(_fingerprint(ids, n), 0)
        if crashes >= self.POISON_CRASHES:
            raise PoisonedRequestError(crashes)

    def _enqueue(self, payload, rows: int) -> None:
        with self._close_lock:
            if self._closed:
                raise RuntimeError("continuous batcher closed")
            if self._broken is not None:
                # checked under the SAME lock the dying engine takes before
                # its final queue drain — a put here either precedes the
                # drain (and gets failed by it) or raises
                raise EngineBrokenError(
                    f"continuous batcher is broken: {self._broken}"
                ) from self._broken
            if (self.max_queue_depth > 0
                    and self._backlog + rows > self.max_queue_depth):
                # bounded admission: shed NOW (429 + Retry-After) — the
                # backlog must never grow without bound under overload
                self.stats["shed"] += rows
                raise QueueFullError(
                    self._backlog, self.max_queue_depth,
                    retry_after=1 + self._backlog // max(1, self.max_slots),
                )
            self._backlog += rows
            self._q.put(payload)

    def _stamp_deadline(self, ticket: _Ticket, timeout_s: float | None = None) -> None:
        """Effective budget = min(engine --request-timeout, the caller's
        propagated remainder). A failover hop that re-submits therefore
        never re-grants a fresh full timeout: the engine stops working
        for a caller whose original budget is gone."""
        eff = self.request_timeout_s if self.request_timeout_s > 0 else 0.0
        if timeout_s is not None and timeout_s > 0:
            eff = min(eff, float(timeout_s)) if eff > 0 else float(timeout_s)
        if eff > 0:
            ticket.deadline = time.monotonic() + eff
            ticket.timeout_s = eff

    def _deadline_error(self, ticket: _Ticket, state: str) -> DeadlineExceededError:
        return DeadlineExceededError(
            state, ticket.timeout_s or self.request_timeout_s
        )

    def submit(self, ids: list[int], max_new_tokens: int, samp: dict,
               timeout_s: float | None = None,
               request_id: str = "") -> _Ticket:
        """Enqueue one prompt row; the returned ticket carries the output
        queue and a ``cancel()`` the transport calls when its client goes
        away (the engine then frees the slot at the next chunk boundary).
        ``timeout_s`` clamps the engine deadline to a propagated
        per-request remainder (deadline propagation, ISSUE 9);
        ``request_id`` threads the transport's end-to-end id into the
        ticket so the engine's per-request timeline is joinable with the
        router's and pod's view of the same request (ISSUE 13)."""
        self._validate(ids, max_new_tokens)
        self._check_quarantine(ids, max_new_tokens)
        ticket = _Ticket()
        ticket.request_id = str(request_id or "")
        ticket.resume_step = int(samp.get("resume_step", 0) or 0)
        ticket.t_submit = time.monotonic()
        self._stamp_deadline(ticket, timeout_s)
        self._enqueue((list(ids), int(max_new_tokens), dict(samp), ticket), 1)
        return ticket

    def submit_many(self, rows: list[tuple[list[int], int, dict]],
                    timeout_s: float | None = None) -> list[_Ticket]:
        """Enqueue several rows as ONE burst: the engine admits them at the
        same chunk boundary, so same-bucket rows share an admit program
        deterministically (a loop of ``submit`` calls races the engine
        thread for that grouping). Used by multi-row ``generate``."""
        for ids, n, _samp in rows:
            self._validate(ids, n)
            self._check_quarantine(ids, n)
        tickets = [_Ticket() for _ in rows]
        now = time.monotonic()
        for t, (_ids, _n, samp) in zip(tickets, rows):
            t.t_submit = now
            t.resume_step = int(samp.get("resume_step", 0) or 0)
            self._stamp_deadline(t, timeout_s)
        self._enqueue([
            (list(ids), int(n), dict(samp), t)
            for (ids, n, samp), t in zip(rows, tickets)
        ], len(rows))
        return tickets

    def submit_row(self, ids: list[int], max_new_tokens: int, samp: dict) -> "queue.Queue":
        return self.submit(ids, max_new_tokens, samp).out

    def _drain_row(self, out: "queue.Queue") -> Iterator[np.ndarray]:
        while True:
            item = out.get()
            if item is _DONE:
                return
            if isinstance(item, ServingError):
                # typed failures (engine death, deadline, shed) surface
                # as-is: one exception class = one HTTP mapping, identical
                # between the streaming and non-streaming paths
                raise item
            if isinstance(item, BaseException):
                raise EngineBrokenError(
                    f"continuous decode failed: {item}"
                ) from item
            yield item

    def generate(self, tokens: np.ndarray, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
                 seed: int = 0, stop_token_ids=None,
                 timeout_s: float | None = None,
                 priority: str = "interactive",
                 timing: dict | None = None) -> np.ndarray:
        """[B, S + m], matching ModelServer.generate: rows of a multi-row
        request become independent slots with seeds seed+i (the same
        per-row streams the ragged path derives). With ``stop_token_ids``,
        every row's SLOT frees at its stop (concurrent requests stop
        starving behind rows that already finished); m is the longest
        row's emitted length, shorter rows padded by repeating their stop
        token — the serving layer's inclusive-trim cuts at the FIRST stop,
        so padding is invisible in responses."""
        tokens = np.asarray(tokens, np.int32)
        b, s = tokens.shape
        stops = list(stop_token_ids or ())
        tickets = self.submit_many([
            (tokens[i].tolist(), max_new_tokens,
             {"temperature": temperature, "top_k": top_k, "top_p": top_p,
              "seed": (seed + i) % (2**31), "stop_token_ids": stops,
              "priority": priority})
            for i in range(b)
        ], timeout_s=timeout_s)
        outs = [t.out for t in tickets]
        rows = []
        emitted = 0
        try:
            for out in outs:
                pieces = list(self._drain_row(out))
                row = np.concatenate(pieces, axis=1)
                emitted += int(row.size)
                rows.append(row)
        finally:
            if timing is not None and tickets:
                # a multi-row request reports the WORST row per phase:
                # the client-visible latency is bounded by the slowest
                for t in tickets:
                    for k, v in t.timing().items():
                        timing[k] = max(timing.get(k, 0), v) \
                            if isinstance(v, (int, float)) else v
        width = max(r.shape[1] for r in rows)
        rows = [
            r if r.shape[1] == width else np.pad(
                r, ((0, 0), (0, width - r.shape[1])), constant_values=int(r[0, -1])
            )
            for r in rows
        ]
        gen = np.concatenate(rows, axis=0)
        self.server.stats["tokens_generated"] += emitted
        return np.concatenate([tokens, gen], axis=1)

    def stream(self, tokens: np.ndarray, max_new_tokens: int = 16,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, chunk_size: int = 0,
               stop_token_ids=None, timeout_s: float | None = None,
               priority: str = "interactive",
               resume_step: int = 0, request_id: str = "",
               timing: dict | None = None) -> Iterator[np.ndarray]:
        """Single-row streaming: yields [1, k] arrays of new tokens as the
        engine decodes them (k == 1 for the prefill token, then up to the
        ENGINE's chunk size — the per-request chunk_size arg is accepted for
        interface parity and ignored). A stop-token hit ends the stream
        early and frees the slot.

        ``resume_step`` = k > 0 CONTINUES an interrupted stream: the caller
        passes ``tokens`` = original prompt + the k tokens already emitted,
        ``max_new_tokens`` = the ORIGINAL budget minus k, and the original
        ``seed`` — the row re-prefills (chunked prefill and prefix-cache
        seeding apply unchanged) and its first token is sampled at step k
        of the original (seed, step) stream, so the continuation is
        byte-identical to the tokens the interrupted stream would have
        emitted (schedule-invariance, see the module docstring)."""
        tokens = np.asarray(tokens, np.int32)
        if tokens.shape[0] != 1:
            raise ValueError("continuous stream is single-row")
        resume_step = int(resume_step)
        if resume_step < 0:
            raise ValueError("resume_step must be >= 0")
        if resume_step >= tokens.shape[1]:
            # ids = prompt + emitted, so a valid resume always leaves at
            # least the original prompt's first token ahead of the frontier
            raise ValueError(
                f"resume_step {resume_step} >= row length {tokens.shape[1]} "
                "(pass prompt + emitted tokens)"
            )
        samp = {"temperature": temperature, "top_k": top_k, "top_p": top_p,
                "seed": seed, "stop_token_ids": list(stop_token_ids or ()),
                "priority": priority}
        if resume_step:
            samp["resume_step"] = resume_step
        ticket = self.submit(
            tokens[0].tolist(), max_new_tokens, samp, timeout_s=timeout_s,
            request_id=request_id,
        )
        try:
            for piece in self._drain_row(ticket.out):
                self.server.stats["tokens_generated"] += int(piece.size)
                yield piece
        finally:
            # a consumer that stops early (client disconnect closes the
            # generator) cancels the row so its slot frees at the next
            # chunk boundary; after a full drain this is a no-op
            ticket.cancel()
            if timing is not None:
                # the caller's out-param: filled HERE (generator close or
                # exhaustion) so the transport reads a complete breakdown
                # exactly when the stream ends
                timing.update(ticket.timing())

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)
        self._closed_ev.set()  # interrupt any restart-backoff sleep
        self._thread.join(timeout=30)

    def release_device_state(self) -> None:
        """Drop the engine's device allocations — the KV cache / page pool
        (the big one: [max_slots, max_len] or [num_pages, page_size] per
        layer), the token vector, and every compiled-program reference.
        Call AFTER ``close()``: the model-unload path (dl/lifecycle.py)
        must return the HBM to the pool budget immediately, not when the
        garbage collector eventually notices the dead engine."""
        if not self._closed:
            raise RuntimeError("release_device_state requires close() first")
        self._cache = None
        self._tok = None
        for attr in ("_admit_prog", "_admit_cached_prog", "_admit_many_prog",
                     "_chunk", "_piece_prog", "_piece_flip_prog",
                     "_seed_prog", "_snap_prog", "_spec_prog"):
            setattr(self, attr, None)
