"""Deploy-spec generation: TPU serving pods with zero GPU containers.

The reference deploys models by injecting modelxdl as an init-container next
to a GPU serving container (docs/setup.md; charts/modelx). The TPU-native
replacement generates a pod spec whose init-container is `modelx dl` and
whose serving container is the JAX/PJRT sidecar — resource requests name TPU
topology (``google.com/tpu``), never ``nvidia.com/gpu`` (BASELINE.json
north_star: 'zero GPU containers in the generated pod spec').
"""

from __future__ import annotations

import yaml

from modelx_tpu.client.model_config import ModelConfig

# topology -> (chips per host, k8s accelerator selector)
TPU_TOPOLOGIES = {
    "v5e-1": (1, "tpu-v5-lite-podslice"),
    "v5e-4": (4, "tpu-v5-lite-podslice"),
    "v5e-8": (8, "tpu-v5-lite-podslice"),
    "v5e-16": (8, "tpu-v5-lite-podslice"),
    "v5p-8": (4, "tpu-v5p-slice"),
    "v5p-32": (4, "tpu-v5p-slice"),
}


def generate_pod_spec(
    name: str,
    uri: str,
    config: ModelConfig,
    image: str = "modelx-tpu:latest",
    volume_size: str = "100Gi",
) -> dict:
    topology = config.serving.topology or "v5e-8"
    chips, selector = TPU_TOPOLOGIES.get(topology, (8, "tpu-v5-lite-podslice"))
    mesh = config.serving.mesh or f"dp=1,tp={chips}"
    spec = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "labels": {"app": name, "modelx.io/model": name}},
        "spec": {
            "nodeSelector": {"cloud.google.com/gke-tpu-accelerator": selector},
            "initContainers": [
                {
                    "name": "modelx-dl",
                    "image": image,
                    "command": ["modelx", "dl", uri, "/mnt/models"],
                    "volumeMounts": [{"name": "model", "mountPath": "/mnt/models"}],
                }
            ],
            "containers": [
                {
                    "name": "serve",
                    "image": image,
                    "command": [
                        "modelx-serve",
                        "--model-dir", "/mnt/models",
                        "--mesh", mesh,
                        "--dtype", config.serving.dtype or "bfloat16",
                    ],
                    "ports": [{"containerPort": 8000, "name": "http"}],
                    "resources": {
                        "limits": {"google.com/tpu": str(chips)},
                        "requests": {"google.com/tpu": str(chips)},
                    },
                    "volumeMounts": [{"name": "model", "mountPath": "/mnt/models"}],
                    "readinessProbe": {
                        "httpGet": {"path": "/healthz", "port": 8000},
                        "initialDelaySeconds": 5,
                    },
                    # liveness is a SEPARATE, stricter probe: /livez fails
                    # only when the serving engine is circuit-broken
                    # (unrecoverable — restart the pod); /healthz 503s for
                    # recoverable states too (loading, draining, supervised
                    # engine restart), which must drain traffic, not kill
                    # the container
                    "livenessProbe": {
                        "httpGet": {"path": "/livez", "port": 8000},
                        "initialDelaySeconds": 30,
                        "periodSeconds": 10,
                        "failureThreshold": 3,
                    },
                }
            ],
            "volumes": [{"name": "model", "emptyDir": {"sizeLimit": volume_size}}],
        },
    }
    return spec


def assert_no_gpu(spec: dict) -> None:
    """The north-star invariant, checkable in tests and CI."""
    text = yaml.safe_dump(spec)
    if "nvidia.com/gpu" in text or "gpu" in str(spec.get("spec", {}).get("nodeSelector", {})):
        raise AssertionError("generated pod spec references GPUs")
