"""JAX serving sidecar: the container the pod spec runs next to the volume.

Replaces the reference deployment's GPU serving container (BASELINE.json
north_star). Loads a checkpoint (local dir or registry URI) onto a mesh,
compiles the forward/decode functions, and serves:

    GET  /healthz          readiness (200 once compiled)
    GET  /metrics          load + inference counters
    POST /v1/forward       {"tokens": [[...]]} -> {"logits_argmax": [[...]]}
    POST /v1/generate      {"tokens": [[...]], "max_new_tokens": N}
                           -> {"tokens": [[prompt+generated...]]}

Token IDs in, token IDs out — tokenization is the caller's concern (the
registry stores tokenizer files alongside weights; wiring a tokenizer in is
deployment glue, not framework).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np

from modelx_tpu.dl.sharding import LLAMA_RULES
from modelx_tpu.models import llama
from modelx_tpu.parallel.mesh import make_mesh

logger = logging.getLogger("modelx.serve")


class ModelServer:
    def __init__(
        self,
        model_dir: str,
        mesh_spec: str = "",
        dtype: str = "bfloat16",
        config: llama.LlamaConfig | None = None,
        max_seq_len: int = 2048,
    ) -> None:
        self.model_dir = model_dir
        self.mesh = make_mesh(mesh_spec) if mesh_spec else make_mesh(f"dp={len(jax.devices())}")
        self.dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        self.max_seq_len = max_seq_len
        self.ready = False
        self.stats: dict = {"requests": 0, "tokens_generated": 0}
        self.cfg = config
        self.params: dict | None = None

    def load(self) -> dict:
        """Load every *.safetensors under model_dir onto the mesh."""
        from modelx_tpu.dl.loader import LocalFileSource, load_safetensors

        t0 = time.monotonic()
        paths = sorted(glob.glob(os.path.join(self.model_dir, "*.safetensors")))
        if not paths:
            raise FileNotFoundError(f"no safetensors under {self.model_dir}")
        params: dict = {}
        total = 0
        for path in paths:
            arrays, stats = load_safetensors(LocalFileSource(path), self.mesh, LLAMA_RULES)
            params.update(arrays)
            total += stats.bytes_to_device
        self.params = params
        if self.cfg is None:
            self.cfg = infer_llama_config(params)
        seconds = time.monotonic() - t0
        self.stats["load_seconds"] = round(seconds, 3)
        self.stats["load_bytes"] = total
        self.stats["load_gbps"] = round(total / max(seconds, 1e-9) / 1e9, 3)
        self._compile()
        self.ready = True
        return dict(self.stats)

    def _compile(self) -> None:
        cfg, mesh = self.cfg, self.mesh
        self._forward = jax.jit(
            lambda p, t: llama.forward(p, t, cfg, mesh=mesh)[0]
        )

    def forward_argmax(self, tokens: np.ndarray) -> np.ndarray:
        logits = self._forward(self.params, jnp.asarray(tokens, jnp.int32))
        return np.asarray(jnp.argmax(logits, axis=-1))

    def generate(self, tokens: np.ndarray, max_new_tokens: int = 16) -> np.ndarray:
        out = llama.greedy_generate(
            self.params, jnp.asarray(tokens, jnp.int32), self.cfg,
            max_new_tokens=max_new_tokens, mesh=self.mesh,
        )
        self.stats["tokens_generated"] += int(out.shape[0] * max_new_tokens)
        return np.asarray(out)


def infer_llama_config(params: dict) -> llama.LlamaConfig:
    """Recover the architecture from checkpoint tensor shapes."""
    embed = params["model.embed_tokens.weight"]
    vocab, hidden = embed.shape
    layers = 0
    while f"model.layers.{layers}.self_attn.q_proj.weight" in params:
        layers += 1
    q = params["model.layers.0.self_attn.q_proj.weight"].shape[0]
    kv = params["model.layers.0.self_attn.k_proj.weight"].shape[0]
    inter = params["model.layers.0.mlp.gate_proj.weight"].shape[0]
    # head_dim heuristics: llama uses 128 for big models; fall back to h/32
    head_dim = 128 if q % 128 == 0 and q // 128 >= 8 else max(q // 32, 32)
    if hidden <= 512:  # toy checkpoints
        head_dim = 32
    return llama.LlamaConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=inter,
        num_layers=layers,
        num_heads=q // head_dim,
        num_kv_heads=kv // head_dim,
        head_dim=head_dim,
        tie_embeddings="lm_head.weight" not in params,
    )


def serve(server: ModelServer, listen: str = ":8000") -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _json(self, status: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                if server.ready:
                    self._json(200, {"status": "ok"})
                else:
                    self._json(503, {"status": "loading"})
            elif self.path == "/metrics":
                self._json(200, server.stats)
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0) or 0)
            try:
                req = json.loads(self.rfile.read(length))
                tokens = np.asarray(req["tokens"], np.int32)
            except (ValueError, KeyError) as e:
                return self._json(400, {"error": f"bad request: {e}"})
            if not server.ready:
                return self._json(503, {"error": "still loading"})
            server.stats["requests"] += 1
            try:
                if self.path == "/v1/forward":
                    out = server.forward_argmax(tokens)
                    self._json(200, {"logits_argmax": out.tolist()})
                elif self.path == "/v1/generate":
                    n = int(req.get("max_new_tokens", 16))
                    out = server.generate(tokens, max_new_tokens=n)
                    self._json(200, {"tokens": out.tolist()})
                else:
                    self._json(404, {"error": "not found"})
            except Exception as e:  # surface inference errors as 500 JSON
                logger.exception("inference error")
                self._json(500, {"error": str(e)})

    host, _, port = listen.rpartition(":")
    httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd
