"""JAX serving sidecar: the container the pod spec runs next to the volume.

Replaces the reference deployment's GPU serving container (BASELINE.json
north_star). Loads one or more checkpoints (multi-tenant: BASELINE config #5
is concurrent pull+serve of 4 models) onto a mesh, compiles the
forward/decode functions, and serves:

    GET  /healthz               readiness (200 once every model is compiled;
                                503 while loading/draining/engine-restarting)
    GET  /livez                 liveness (503 only when the serving engine is
                                circuit-broken -> k8s restarts the pod)
    GET  /metrics               load + inference counters (all models)
    GET  /v1/models             model inventory + per-model stats
    GET  /v1/trace              span summary (utils/trace.py)
    POST /v1/profile            {"seconds": N} -> device-level jax profiler
                                trace written to trace_dir
    POST /v1/forward            default model      {"tokens": [[...]]}
    POST /v1/generate           default model      + {"max_new_tokens": N,
                                "temperature": t, "top_k": k, "top_p": p,
                                "seed": s}  (temperature 0 = greedy)
    POST /v1/{model}/forward    named model
    POST /v1/{model}/generate   named model
    GET  /admin/models          lifecycle states + HBM accounting
    POST /admin/models          runtime load: {"name", "ref"|"model_dir"}
    DELETE /admin/models/{name} drain + unload (dl/lifecycle.py; the
                                mutations need --allow-admin-load, the
                                surface honors --admin-token bearer auth)

Model family (llama / mixtral / gpt2 / bert) is detected from checkpoint
tensor names (dl/families.py) — the checkpoint is self-describing, no
config.json needed. Token IDs in, token IDs out by default; when the model
directory carries a ``tokenizer.json`` (pulled alongside the weights),
``/v1/generate`` also takes ``{"text": "..."}`` and returns the decoded
continuation.

Compile latency: a persistent XLA compilation cache can be enabled
(MODELX_COMPILE_CACHE or ~/.cache/modelx-tpu/xla) so a sidecar restart
skips recompilation — the TTFT budget (BASELINE: p50 < 500 ms) has no room
for a cold pjit.
"""

from __future__ import annotations

import glob
import itertools
import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np

from modelx_tpu.dl import families as fam
from modelx_tpu.dl.serving_errors import (
    ATTEMPT_HEADER,
    DEADLINE_HEADER,
    PRIORITY_HEADER,
    REQUEST_ID_HEADER,
    RESUME_EMITTED_HEADER,
    RESUME_SEED_HEADER,
    DeadlineExceededError,
    MalformedResumeError,
    ModelLoadingError,
    ResumeExhaustedError,
    ServingError,
    client_identity as _client_hash,
    deadline_kwargs,
    mint_request_id,
    parse_attempt,
    parse_deadline_ms,
    parse_priority,
    parse_request_id,
    parse_resume,
    timing_headers,
)
from modelx_tpu.parallel.mesh import make_mesh
from modelx_tpu.utils import accesslog, devmem, promexp, trace, tswheel

logger = logging.getLogger("modelx.serve")

# /v1/generate decode budget an unauthenticated client may request; each
# distinct max_new_tokens value also compiles a new decode program, so the
# cap bounds both HBM for the KV cache and compile-cache churn.
DEFAULT_MAX_NEW_TOKENS_LIMIT = 1024
# /v1/profile holds the handler thread and the profiler for this long at most
MAX_PROFILE_SECONDS = 60
# /admin/profile captures kept on disk; older ones are pruned after each
# capture so the on-demand profiler can never fill the pod's disk
MAX_PROFILE_CAPTURES = 4

_UNSET = object()  # tokenizer not probed yet (absent is cached as None)


class ChatTemplateRejected(Exception):
    """A model chat template called raise_exception(msg) on the request's
    messages — a CLIENT error (the OpenAI layer maps it to 400)."""


_EOS_CANDIDATES = (
    # the end-of-sequence spellings of the supported families' tokenizers:
    # llama2/mistral, gpt2/gpt-j, llama3, chatml/qwen2, llama3 base, gemma
    "</s>", "<|endoftext|>", "<|eot_id|>", "<|im_end|>", "<|end_of_text|>",
    "<eos>", "<|end|>",
)


def _eos_from_config(model_dir: str, tok) -> tuple[int, ...] | None:
    """Explicit end-of-sequence ids from the checkpoint's sidecar configs
    (pulled alongside the weights like tokenizer.json). Precedence follows
    the HF convention: generation_config.json > config.json eos_token_id,
    then tokenizer_config.json's eos_token spelling resolved through the
    vocab. None = no explicit declaration (callers fall back to the
    well-known-spelling probe). An explicit id beats the probe because
    vocabs can carry probe spellings as NON-eos specials (e.g. chatml
    models where <|endoftext|> is pad while <|im_end|> ends turns)."""

    def ids_from(val) -> tuple[int, ...] | None:
        if isinstance(val, bool):
            return None
        if isinstance(val, int):
            return (int(val),)
        if (
            isinstance(val, list) and val
            and all(isinstance(v, int) and not isinstance(v, bool) for v in val)
        ):
            return tuple(dict.fromkeys(int(v) for v in val))
        return None

    for fname in ("generation_config.json", "config.json"):
        path = os.path.join(model_dir, fname)
        if not os.path.isfile(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                got = ids_from(json.load(f).get("eos_token_id"))
        except (OSError, ValueError):
            continue  # malformed sidecar must not kill the tokenizer load
        if got:
            return got
    path = os.path.join(model_dir, "tokenizer_config.json")
    if os.path.isfile(path):
        try:
            with open(path, encoding="utf-8") as f:
                eos = json.load(f).get("eos_token")
        except (OSError, ValueError):
            eos = None
        if isinstance(eos, dict):  # added-token object form
            eos = eos.get("content")
        if isinstance(eos, str):
            tid = tok.token_to_id(eos)
            if tid is not None:
                return (int(tid),)
    return None


class _Tokenizer:
    """list[int]-in/str-out facade over a raw ``tokenizers.Tokenizer``.

    ``eos_override``: explicit eos ids from the model's config sidecars
    (_eos_from_config); when present the spelling probe is skipped."""

    def __init__(self, tok, eos_override: tuple[int, ...] | None = None) -> None:
        self._tok = tok
        self._eos: tuple[int, ...] | None = eos_override

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        # chat-template renders carry their own special tokens (bos etc.),
        # so that path encodes raw — the HF apply_chat_template convention
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids) -> str:
        # keep special tokens: clients watch for e.g. "</s>" in the text,
        # and tokenizers' own default (skip=True) would silently strip them
        return self._tok.decode(list(ids), skip_special_tokens=False)

    def eos_ids(self) -> tuple[int, ...]:
        """End-of-sequence token ids: the config sidecars' explicit
        declaration when the model ships one, otherwise discovered from
        the vocab's well-known spellings (tokenizer.json alone carries no
        EOS marker). Empty = unknown: callers then keep budget-only
        decode; ``ignore_eos`` is the per-request escape hatch."""
        if self._eos is None:
            ids = []
            for cand in _EOS_CANDIDATES:
                tid = self._tok.token_to_id(cand)
                if tid is not None:
                    ids.append(int(tid))
            self._eos = tuple(dict.fromkeys(ids))
        return self._eos


_compile_cache_dir = ""  # set by enable_compile_cache; "" = cold every start


def compile_cache_dir() -> str:
    """The enabled persistent cache dir ("" when not enabled) — warmup paths
    key their serialized-executable (aot_cache) artifacts under it."""
    return _compile_cache_dir


def enable_compile_cache(path: str = "") -> None:
    """Persistent XLA compilation cache (idempotent)."""
    global _compile_cache_dir
    path = path or os.environ.get(
        "MODELX_COMPILE_CACHE", os.path.expanduser("~/.cache/modelx-tpu/xla")
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # no min-compile-time floor: the program store (dl/program_store.py)
        # ships this cache's executables fleet-wide, and a program under
        # the default 1 s threshold would stay cold on EVERY pod — small
        # entries cost bytes once, a fleet of retraces costs TTFT always
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # keep the cache-dir PATH out of the cache key: with XLA side
        # caches on, jax points xla_gpu_per_fusion_autotune_cache_dir at a
        # subdir of `path`, which lands in the hashed compile options — so
        # two pods with different cache dirs (or the bench's fresh per-leg
        # dirs) could never hit each other's shipped executables
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
        _compile_cache_dir = path
    except Exception as e:  # cache is an optimization, never fatal
        logger.warning("compile cache unavailable: %s", e)


class ModelServer:
    """One loaded model: params on the mesh + compiled entry points."""

    def __init__(
        self,
        model_dir: str,
        mesh_spec: str = "",
        dtype: str = "bfloat16",
        config=None,
        max_seq_len: int = 2048,
        mesh=None,
        name: str = "default",
        quantize: str | None = None,
        speculative_k: int = 0,
        lora_dir: str = "",
        prefix_cache_size: int = 0,
        prefix_cache_max_bytes: int = 0,
    ) -> None:
        self.name = name
        self.model_dir = model_dir
        self.quantize = quantize
        self.lora_dir = lora_dir
        # > 0 keeps the prefill KV of the last N single-row stream prompts
        # on device (models/decode.PrefixKVCache): multi-turn chats that
        # re-send their history prefill only the new suffix.
        # prefix_cache_max_bytes additionally caps the entries' actual KV
        # bytes — an entry count alone over-commits HBM for long prefixes
        self._prefix_cache = None
        if int(prefix_cache_size) > 0:
            from modelx_tpu.models.decode import PrefixKVCache

            self._prefix_cache = PrefixKVCache(
                int(prefix_cache_size), max_bytes=int(prefix_cache_max_bytes)
            )
        # > 0 turns on prompt-lookup speculative decoding for single-row
        # greedy requests (models/speculative.py): token-exact, fewer
        # device steps on self-repeating continuations
        self.speculative_k = int(speculative_k)
        self._spec_decoder = None
        self.mesh = mesh if mesh is not None else (
            make_mesh(mesh_spec) if mesh_spec else make_mesh(f"dp={len(jax.devices())}")
        )
        self.dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        self.max_seq_len = max_seq_len
        self.ready = False
        # set by ServerSet.load_all when this model's load crashed: the
        # pool marks it FAILED, /healthz reports the degraded set, and the
        # reason is visible in GET /v1/models — the OTHER tenants keep
        # serving instead of the whole process dying
        self.load_error: str | None = None
        self.stats: dict = {"requests": 0, "tokens_generated": 0}
        self.cfg = config
        self.family: fam.Family | None = None
        self.params: dict | None = None
        self._forward_aot: dict[tuple, object] = {}
        self._param_sds: dict | None = None  # abstract params, set by load()
        self._decoders: dict[int, object] = {}  # chunk_size -> ChunkedDecoder
        self._score_progs: dict[tuple, object] = {}  # (len bucket, top_k)
        self._decoders_lock = threading.Lock()
        # separate lock: tokenizer loading must not block streaming-decoder
        # creation (unrelated caches)
        self._tokenizer_lock = threading.Lock()
        self._tokenizer: object = _UNSET
        self._chat_template: object = _UNSET

    # the shape the dynamic batcher pads a lone first request to (seq to a
    # multiple of 16, batch to a power of two): precompiling it during load
    # means the first real request meets a ready executable
    WARMUP_TOKEN_SHAPES = ((1, 16),)

    def load(self) -> dict:
        """Load every *.safetensors under model_dir onto the mesh. The
        checkpoint headers fully determine the architecture, so the prefill
        program for the warmup shapes AOT-compiles on a side thread WHILE
        the weight bytes stream — a deploy pays max(load, compile), not
        their sum (TTFT budget, BASELINE.md)."""
        from modelx_tpu.dl.loader import LocalFileSource, load_safetensors
        from modelx_tpu.dl.safetensors import read_header_from_file

        with trace.span("serve.load", model=self.name, dir=self.model_dir):
            t0 = time.monotonic()
            paths = sorted(glob.glob(os.path.join(self.model_dir, "*.safetensors")))
            if not paths:
                raise FileNotFoundError(f"no safetensors under {self.model_dir}")
            # program-store bundles pulled alongside the weights install
            # into the AOT cache BEFORE any compile below — the warmup
            # thread then warm-starts from another pod's exports. Purely
            # an optimization: any failure just compiles cold.
            cache_dir = compile_cache_dir()
            if cache_dir:
                from modelx_tpu.dl import program_store

                try:
                    pstats = program_store.install_from_dir(
                        self.model_dir, cache_dir, mesh=self.mesh
                    )
                    if pstats["bundles"] or pstats["skipped"]:
                        self.stats["programs"] = {
                            k: pstats[k]
                            for k in ("bundles", "installed", "present", "skipped")
                        }
                except Exception as e:
                    logger.warning("program bundle install failed: %s", e)
            # detect the family from the headers so the right partition rules
            # apply from the first byte fetched
            infos_all: dict = {}
            for path in paths:
                infos, _ = read_header_from_file(path)
                infos_all.update(infos)
            self.family = fam.detect(list(infos_all))
            # mirror the loader's expert fusion so header-derived shapes
            # match the params it will deliver (stacked [E, ...] experts)
            from modelx_tpu.dl.loader import fuse_expert_tensors

            infos_all = fuse_expert_tensors(infos_all, self.family.rules)
            if self.cfg is None:
                self.cfg = self.family.infer_config(
                    fam.abstract_params(infos_all)
                )
                # reconcile with the pulled config.json sidecar: rope_theta
                # overrides apply; unimplemented rope_scaling (phi-3-*-128k
                # longrope etc.) refuses BEFORE the weights stream to HBM
                sidecar = fam.sidecar_config(self.model_dir)
                if sidecar is not None:
                    self.cfg = fam.apply_sidecar_config(
                        self.cfg, sidecar, self.family.name
                    )
            # quantized included: abstract_params mirrors the loader's int8
            # transform (QTensor pytrees of structs), so int8 deploys overlap
            # load and compile like bf16 ones
            sds = fam.abstract_params(
                infos_all, self.family.rules, self.mesh, quantize=self.quantize
            )
            # kept for the program store: surface keys (publish) and score
            # program AOT routing both need the abstract params later
            self._param_sds = sds
            compile_thread = threading.Thread(
                target=self._precompile_warmup, args=(sds,), daemon=True
            )
            compile_thread.start()
            params: dict = {}
            total = 0
            for path in paths:
                src = LocalFileSource(path)
                try:
                    arrays, stats = load_safetensors(
                        src, self.mesh, self.family.rules, quantize=self.quantize
                    )
                finally:
                    src.close()
                params.update(arrays)
                total += stats.bytes_to_device
            self.params = params
            if self.lora_dir:
                from modelx_tpu.dl import lora

                # merge BEFORE compiling: the jitted programs close over the
                # merged weights, and merge-into-int8 is rejected upstream
                with trace.span("serve.lora", model=self.name, dir=self.lora_dir):
                    self.params = lora.merge_adapter(self.params, self.lora_dir)
                self.stats["lora_dir"] = self.lora_dir
            seconds = time.monotonic() - t0
            from modelx_tpu.parallel.mesh import mesh_str, weight_shard_factor

            self.stats["mesh"] = mesh_str(self.mesh)
            self.stats["mesh_devices"] = int(self.mesh.size)
            # how many ways the weight bytes divide across devices — what
            # load_bytes must be divided by to get the per-device footprint
            self.stats["weight_shard_factor"] = weight_shard_factor(self.mesh)
            self.stats["family"] = self.family.name
            self.stats["load_seconds"] = round(seconds, 3)
            self.stats["load_bytes"] = total
            self.stats["load_gbps"] = round(total / max(seconds, 1e-9) / 1e9, 3)
            self._compile()
            if compile_thread is not None:
                compile_thread.join()
            self.stats["ready_seconds"] = round(time.monotonic() - t0, 3)
            self.ready = True
            self._install_kv_bundles()
        return dict(self.stats)

    def _install_kv_bundles(self) -> None:
        """Install prefix-KV bundles pulled next to the weights
        (``.kv-*.tar``, dl/kv_store.py) into the prefix cache — AFTER the
        family/compile so ``decode_fns`` can validate the leaf layout.
        Purely an optimization: any failure just prefills cold."""
        if self._prefix_cache is None:
            return
        from modelx_tpu.dl import kv_store

        try:
            kstats = kv_store.install_for_server(self, self.model_dir)
        except Exception as e:
            logger.warning("kv bundle install failed: %s", e)
            return
        if kstats and (kstats["bundles"] or kstats["skipped"]):
            self.stats["kv"] = {
                k: kstats[k]
                for k in ("bundles", "installed", "present", "skipped")
            }

    def load_from_tier(self, promo) -> dict:
        """Materialize a demoted model from a tier promotion
        (dl/tiers.Promotion) instead of the checkpoint files: device_put
        each host leaf straight to its recorded NamedSharding placement —
        no fetch, no safetensors parse, no sharding-plan walk. The compile
        overlap works exactly as in ``load`` (and usually hits the AOT
        cache outright, since this content compiled here before)."""
        with trace.span("serve.load_from_tier", model=self.name,
                        tier=promo.tier):
            t0 = time.monotonic()
            self.family = promo.family
            self.cfg = promo.cfg
            self._param_sds = promo.param_sds
            compile_thread = None
            if promo.param_sds is not None:
                compile_thread = threading.Thread(
                    target=self._precompile_warmup, args=(promo.param_sds,),
                    daemon=True,
                )
                compile_thread.start()
            leaves = []
            for arr, sharding in zip(promo.leaves, promo.shardings):
                if sharding is not None:
                    leaves.append(jax.device_put(arr, sharding))
                else:
                    leaves.append(jax.device_put(arr))
            self.params = jax.tree_util.tree_unflatten(promo.treedef, leaves)
            seconds = time.monotonic() - t0
            from modelx_tpu.parallel.mesh import mesh_str, weight_shard_factor

            self.stats["mesh"] = mesh_str(self.mesh)
            self.stats["mesh_devices"] = int(self.mesh.size)
            self.stats["weight_shard_factor"] = weight_shard_factor(self.mesh)
            self.stats["family"] = self.family.name
            self.stats["load_seconds"] = round(seconds, 3)
            self.stats["load_bytes"] = promo.nbytes
            self.stats["load_gbps"] = round(
                promo.nbytes / max(seconds, 1e-9) / 1e9, 3)
            self.stats["tier"] = promo.tier
            self._compile()
            if compile_thread is not None:
                compile_thread.join()
            self.stats["ready_seconds"] = round(time.monotonic() - t0, 3)
            self.ready = True
            self._install_kv_bundles()
        return dict(self.stats)

    def _precompile_warmup(self, sds: dict) -> None:
        """AOT-compile the forward for the warmup token shapes (overlapped
        with the weight load). Failures only lose the warm start."""
        for shape in self.WARMUP_TOKEN_SHAPES:
            try:
                with trace.span("serve.precompile", model=self.name, shape=str(shape)):
                    compiled = fam.precompile_forward(
                        self.family, self.cfg, sds, shape,
                        mesh=self.mesh, mode="argmax_all",
                        cache_dir=compile_cache_dir(),
                    )
                self._forward_aot[shape] = compiled
            except Exception as e:
                logger.warning("precompile %s failed (cold first request): %s", shape, e)

    def _compile(self) -> None:
        cfg, mesh, family = self.cfg, self.mesh, self.family
        with trace.span("serve.compile", model=self.name, family=family.name):
            self._forward = jax.jit(
                lambda p, t: family.forward(p, t, cfg, mesh=mesh)
            )

    def forward_argmax(self, tokens: np.ndarray) -> np.ndarray:
        with trace.span("serve.forward", model=self.name, batch=int(tokens.shape[0])):
            aot = self._forward_aot.get(tuple(tokens.shape))
            if aot is not None:
                return np.asarray(aot(self.params, jnp.asarray(tokens, jnp.int32)))
            out = self._forward(self.params, jnp.asarray(tokens, jnp.int32))
            return np.asarray(jnp.argmax(out, axis=-1))

    def generate(
        self,
        tokens: np.ndarray,
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
    ) -> np.ndarray:
        """Greedy by default; temperature > 0 samples (with optional top-k /
        nucleus cuts and a request seed) via the ragged decode path. With
        --speculative-k, single rows speculate at ANY temperature: greedy
        acceptance is token-exact, sampled acceptance is modified rejection
        (distribution-preserving)."""
        if self.family.generate is None:
            raise ValueError(f"family {self.family.name} is not generative")
        tokens_arr = np.asarray(tokens, np.int32)
        if (
            self.speculative_k > 0
            and tokens_arr.shape[0] == 1
            and self.family.decode_fns is not None
        ):
            with trace.span("serve.generate_spec", model=self.name,
                            new_tokens=max_new_tokens):
                dec = self._speculative_decoder()
                new, stats = dec.generate(
                    self.params, tokens_arr[0].tolist(), max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    seed=seed,
                )
                self.stats["tokens_generated"] += len(new)
                self._record_spec_stats(stats)
                return np.concatenate(
                    [tokens_arr, np.asarray([new], np.int32)], axis=1
                )
        if temperature > 0:
            if self.family.generate_ragged is None:
                raise ValueError(
                    f"family {self.family.name} does not support sampling"
                )
            b, s = np.asarray(tokens).shape
            with trace.span("serve.generate", model=self.name, new_tokens=max_new_tokens):
                gen = self.generate_ragged(
                    tokens, np.full((b,), s, np.int32), max_new_tokens,
                    temperature=np.full((b,), temperature, np.float32),
                    top_k=np.full((b,), top_k, np.int32) if top_k > 0 else None,
                    top_p=np.full((b,), top_p, np.float32) if top_p < 1.0 else None,
                    # distinct per-row streams: a request asking for B samples
                    # of one prompt gets B different completions
                    seeds=((seed + np.arange(b)) % (2**31)).astype(np.int32),
                )
            self.stats["tokens_generated"] += int(b * max_new_tokens)
            return np.concatenate([np.asarray(tokens, np.int32), gen], axis=1)
        with trace.span("serve.generate", model=self.name, new_tokens=max_new_tokens):
            out = self.family.generate(
                self.params, jnp.asarray(tokens_arr, jnp.int32), self.cfg,
                mesh=self.mesh, max_new_tokens=max_new_tokens,
            )
            self.stats["tokens_generated"] += int(out.shape[0] * max_new_tokens)
            return np.asarray(out)

    def score_logprobs_rows(self, rows, top_k: int = 0) -> list:
        """Per-token log-probabilities for completed generations: a prefill
        over [prompt + generated] and a log-softmax gather — the values the
        decode programs saw when they picked each token (the forward is
        deterministic; no decode-path surgery needed, logprobs requests
        just pay scoring forwards). ``rows`` is [(ids, new_ids), ...]; rows
        sharing a length bucket score as ONE batched device call — a
        request's n samples of one prompt all ride one program. Returns,
        per row, (token_logprobs [m], top_ids [m, top_k], top_logprobs
        [m, top_k]); the top_* pair is None when top_k == 0.

        Programs compile per (16-bucketed length, pow2 batch, top_k) — the
        same shape discipline as every other serving path."""
        from modelx_tpu.models.decode import pad_seq_len

        empty = (
            (np.zeros((0,), np.float32),) + (
                (np.zeros((0, top_k), np.int32), np.zeros((0, top_k), np.float32))
                if top_k else (None, None)
            )
        )
        out: list = [empty] * len(rows)
        groups: dict[int, list[int]] = {}
        for i, (ids, new_ids) in enumerate(rows):
            if new_ids:
                groups.setdefault(pad_seq_len(len(ids) + len(new_ids)), []).append(i)
        for lb, idxs in groups.items():
            bb = 1 << (len(idxs) - 1).bit_length()  # pow2 batch bucket
            key = (lb, bb, int(top_k))
            prog = self._score_progs.get(key)
            if prog is None:
                with self._decoders_lock:
                    prog = self._score_progs.get(key)
                    if prog is None and self._param_sds is not None:
                        # route through the AOT cache (families.precompile_score
                        # shares the inline closure's exact body): warm pods —
                        # and pods that pulled a program bundle — skip the
                        # trace+lower; any failure falls through to the
                        # plain jit below
                        cache_dir = compile_cache_dir()
                        if cache_dir:
                            try:
                                prog = fam.precompile_score(
                                    self.family, self.cfg, self._param_sds,
                                    (bb, lb), top_k=int(top_k), mesh=self.mesh,
                                    cache_dir=cache_dir,
                                )
                                self._score_progs[key] = prog
                            except Exception as e:
                                logger.warning(
                                    "score precompile %s failed (%s); plain jit",
                                    key, e,
                                )
                    if prog is None:
                        family, cfg, mesh = self.family, self.cfg, self.mesh

                        def _score(params, toks, k=int(top_k)):
                            logits = family.forward(params, toks, cfg, mesh=mesh)
                            lp = jax.nn.log_softmax(
                                logits.astype(jnp.float32), axis=-1
                            )  # [B, Lb, V]
                            nxt = jnp.concatenate(
                                [toks[:, 1:], jnp.zeros((toks.shape[0], 1), jnp.int32)],
                                axis=1,
                            )
                            chosen = jnp.take_along_axis(
                                lp, nxt[..., None], axis=-1
                            )[..., 0]  # position j scores token j+1
                            if k:
                                top_lp, top_id = jax.lax.top_k(lp, k)
                                return chosen, top_id, top_lp
                            return chosen, None, None

                        prog = self._score_progs[key] = jax.jit(_score)
            padded = np.zeros((bb, lb), np.int32)
            for r, i in enumerate(idxs):
                ids, new_ids = rows[i]
                full = list(ids) + list(new_ids)
                padded[r, : len(full)] = full
            chosen, top_id, top_lp = prog(self.params, jnp.asarray(padded))
            chosen = np.asarray(chosen)
            if top_k:
                top_id, top_lp = np.asarray(top_id), np.asarray(top_lp)
            for r, i in enumerate(idxs):
                ids, new_ids = rows[i]
                lo, hi = len(ids) - 1, len(ids) + len(new_ids) - 1
                if top_k:
                    out[i] = (chosen[r, lo:hi], top_id[r, lo:hi], top_lp[r, lo:hi])
                else:
                    out[i] = (chosen[r, lo:hi], None, None)
        return out

    def score_logprobs(self, ids: list[int], new_ids: list[int],
                       top_k: int = 0):
        """Single-row convenience over score_logprobs_rows."""
        return self.score_logprobs_rows([(ids, new_ids)], top_k=top_k)[0]

    def _speculative_decoder(self):
        if self._spec_decoder is None:
            with self._decoders_lock:  # double-checked, like the stream decoders
                if self._spec_decoder is None:
                    from modelx_tpu.models.speculative import SpeculativeDecoder

                    fwd, init = self.family.decode_fns(self.cfg, mesh=self.mesh)
                    self._spec_decoder = SpeculativeDecoder(fwd, init, k=self.speculative_k)
        return self._spec_decoder

    def tokenizer(self):
        """The model's tokenizer (``tokenizer.json`` pulled alongside the
        weights — the registry stores tokenizer files as ordinary blobs), or
        None. Loaded lazily: the token-id API never pays the import."""
        if self._tokenizer is _UNSET:
            with self._tokenizer_lock:
                if self._tokenizer is _UNSET:
                    path = os.path.join(self.model_dir, "tokenizer.json")
                    if not os.path.isfile(path):
                        self._tokenizer = None  # genuinely absent: cache it
                    else:
                        try:
                            import tokenizers  # rust core; loads in ms where
                            # transformers' wrapper costs a multi-second import

                            raw = tokenizers.Tokenizer.from_file(path)
                            self._tokenizer = _Tokenizer(
                                raw,
                                eos_override=_eos_from_config(self.model_dir, raw),
                            )
                        except Exception as e:
                            # NOT cached: a missing optional dep or transient
                            # read error must surface as a load failure (and
                            # retry later), not as "no tokenizer.json"
                            raise RuntimeError(
                                f"tokenizer.json exists but failed to load: {e}"
                            ) from e
        return self._tokenizer

    def chat_template(self) -> dict | None:
        """The model's own chat template from ``tokenizer_config.json``
        (pulled alongside the weights like any blob), or None. Returns
        ``{"template": str, "compiled": jinja Template, "bos_token": str,
        "eos_token": str}``. Handles the string form and the named-list
        form (a "default" entry ONLY — silently serving an arbitrary named
        template like "tool_use" would format every chat wrong); special
        tokens may be strings or HF AddedToken dicts. The template is
        compiled ONCE here in a sandboxed environment with the HF
        apply_chat_template conveniences (loop controls, strftime_now).
        Cached under double-checked locking (publishing a half-built state
        would race the first concurrent chat requests into inconsistent
        render-vs-encode decisions); any problem — including a missing
        jinja2 — degrades to None (generic role template) with one
        warning, never a 500 per request."""
        if self._chat_template is _UNSET:
            with self._tokenizer_lock:
                if self._chat_template is _UNSET:
                    self._chat_template = self._load_chat_template()
        return self._chat_template

    def _load_chat_template(self) -> dict | None:
        path = os.path.join(self.model_dir, "tokenizer_config.json")
        if not os.path.isfile(path):
            return None
        try:
            with open(path, encoding="utf-8") as f:
                cfg = json.load(f)
            tpl = cfg.get("chat_template")
            if isinstance(tpl, list):  # [{name, template}, ...]
                by_name = {
                    t.get("name"): t.get("template")
                    for t in tpl if isinstance(t, dict)
                }
                tpl = by_name.get("default")
                if tpl is None and by_name:
                    logger.warning(
                        "tokenizer_config.json has named chat templates %s "
                        "but no 'default'; using the generic role template",
                        sorted(k for k in by_name if k),
                    )
                    return None
            if not (isinstance(tpl, str) and tpl.strip()):
                return None
            try:
                from jinja2.sandbox import ImmutableSandboxedEnvironment
            except ImportError:
                logger.warning(
                    "model ships a chat_template but jinja2 is not "
                    "installed (pip install 'modelx-tpu[text]'); using the "
                    "generic role template"
                )
                return None
            env = ImmutableSandboxedEnvironment(
                trim_blocks=True, lstrip_blocks=True,
                extensions=["jinja2.ext.loopcontrols"],
            )
            # the conveniences HF's apply_chat_template provides and real
            # shipped templates use (llama-3.1 calls strftime_now for its
            # date line); raise_exception surfaces as ChatTemplateRejected
            # so the API layer can map it to a clean 400
            import datetime as _dt

            env.globals["strftime_now"] = (
                lambda fmt: _dt.datetime.now().strftime(fmt)
            )

            def _raise(msg):
                raise ChatTemplateRejected(str(msg))

            env.globals["raise_exception"] = _raise

            def token_str(v) -> str:
                if isinstance(v, dict):  # AddedToken form
                    return str(v.get("content", ""))
                return v if isinstance(v, str) else ""

            return {
                "template": tpl,
                "compiled": env.from_string(tpl),
                "bos_token": token_str(cfg.get("bos_token")),
                "eos_token": token_str(cfg.get("eos_token")),
            }
        except Exception as e:
            logger.warning(
                "tokenizer_config.json unusable for chat templating (%s); "
                "falling back to the generic role template", e,
            )
            return None

    def generate_stream(
        self,
        tokens: np.ndarray,
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        chunk_size: int = 8,
        stop_token_ids=None,
    ):
        """Yields [B, k] arrays of new tokens as they decode — the transport
        behind streaming /v1/generate. On the plain path k <= chunk_size;
        the speculative path instead emits one chunk per device step (up to
        speculative_k + 1 tokens). Either way the concatenated chunks equal
        the non-streaming result exactly."""
        if self.family.decode_fns is None:
            raise ValueError(f"family {self.family.name} does not support streaming")
        tokens_arr = np.asarray(tokens, np.int32)
        if self.speculative_k > 0 and tokens_arr.shape[0] == 1:
            # single-row stream: speculation's target — chunks flush per
            # device step (accepted run + bonus token). Greedy concatenates
            # to the plain stream token-for-token; sampled streams keep the
            # plain sampler's distribution (modified rejection).
            # (yield from, not return: this function is itself a generator)
            yield from self._generate_stream_speculative(
                tokens_arr, max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                stop_token_ids=stop_token_ids,
            )
            return
        dec = self._decoders.get(chunk_size)
        if dec is None:
            with self._decoders_lock:
                dec = self._decoders.get(chunk_size)
                if dec is None:  # double-checked: concurrent first streams
                    from modelx_tpu.models.decode import ChunkedDecoder

                    fwd, init = self.family.decode_fns(self.cfg, mesh=self.mesh)
                    dec = self._decoders[chunk_size] = ChunkedDecoder(
                        fwd, init, chunk_size, prefix_cache=self._prefix_cache
                    )
        from modelx_tpu.models.decode import pad_seq_len

        b, s = tokens_arr.shape
        pad_s = pad_seq_len(s)  # bound compiled shapes like the batcher
        padded = np.zeros((b, pad_s), np.int32)
        padded[:, :s] = tokens_arr
        with trace.span("serve.generate_stream", model=self.name,
                        new_tokens=max_new_tokens):
            # unfiltered requests (top_k 0, top_p off) pass None so the
            # decoder's sampler variant compiles without any filter work —
            # with filters off the mask is all-True, so tokens are
            # byte-identical between the two variants
            filtered = top_k > 0 or top_p < 1.0
            for piece in dec.stream(
                self.params, jnp.asarray(padded), np.full((b,), s, np.int32),
                max_new_tokens,
                temperature=np.full((b,), temperature, np.float32),
                top_k=np.full((b,), top_k, np.int32) if filtered else None,
                top_p=np.full((b,), top_p, np.float32) if filtered else None,
                seeds=((seed + np.arange(b)) % (2**31)).astype(np.int32),
                stop_token_ids=stop_token_ids,
            ):
                # account as chunks leave: a client disconnect must not
                # erase the decode work the device already did
                self.stats["tokens_generated"] += int(piece.size)
                yield piece

    def _record_spec_stats(self, stats: dict) -> None:
        self.stats["spec_device_steps"] = (
            self.stats.get("spec_device_steps", 0) + stats["device_steps"]
        )
        self.stats["spec_accepted"] = (
            self.stats.get("spec_accepted", 0) + stats["accepted"]
        )

    def _generate_stream_speculative(self, tokens: np.ndarray, max_new_tokens: int,
                                     temperature: float = 0.0, top_k: int = 0,
                                     top_p: float = 1.0, seed: int = 0,
                                     stop_token_ids=None):
        dec = self._speculative_decoder()
        stats = {"device_steps": 0, "proposed": 0, "accepted": 0}
        stops = set(stop_token_ids or ())
        try:
            with trace.span("serve.generate_stream_spec", model=self.name,
                            new_tokens=max_new_tokens):
                for piece in dec.stream(self.params, tokens[0].tolist(),
                                        max_new_tokens, stats=stats,
                                        temperature=temperature, top_k=top_k,
                                        top_p=top_p, seed=seed):
                    if stops:
                        from modelx_tpu.models.decode import stop_cut

                        cut = stop_cut(piece[0].tolist(), stops)
                        if cut is not None:  # emit through the stop, then end
                            piece = piece[:, :cut]
                            self.stats["tokens_generated"] += int(piece.size)
                            yield piece
                            return
                    self.stats["tokens_generated"] += int(piece.size)
                    yield piece
        finally:
            # an early-stopped consumer (SSE stop match, client disconnect)
            # closes the generator mid-loop; the device work already
            # happened and must still show up in /metrics
            self._record_spec_stats(stats)

    def generate_ragged(
        self, tokens: np.ndarray, row_lens: np.ndarray, max_new_tokens: int,
        temperature=None, top_k=None, top_p=None, seeds=None,
    ) -> np.ndarray:
        """Ragged-batch decode: right-padded rows [B,S] with per-row real
        lengths. Returns generated tokens only, [B, max_new_tokens]. The
        caller accounts tokens_generated — padded rows and bucket rounding
        here would inflate the counter."""
        if self.family.generate_ragged is None:
            raise ValueError(f"family {self.family.name} has no ragged decode")
        with trace.span(
            "serve.generate_ragged", model=self.name,
            rows=int(tokens.shape[0]), new_tokens=max_new_tokens,
        ):
            out = self.family.generate_ragged(
                self.params, jnp.asarray(tokens, jnp.int32),
                jnp.asarray(row_lens, jnp.int32), self.cfg,
                mesh=self.mesh, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p, seeds=seeds,
            )
            return np.asarray(out)


def infer_llama_config(params: dict):
    """Back-compat alias (dl/families.py owns config inference now)."""
    return fam.infer_llama_config(params)


class Batcher:
    """Dynamic batching: concurrent requests arriving within a small window
    coalesce into one device call — forward requests into one padded
    forward, generate requests into one RAGGED decode (per-row prompt
    lengths and offsets, models/decode.ragged_greedy_generate).

    Right-padding is output-preserving ONLY for causal models (later
    positions never influence earlier ones) — bidirectional encoders like
    BERT attend to the pad tokens, so ServerSet only routes causal families
    through a batcher. Rows pad to the group's max sequence and the batch
    to the next power of two, and decode lengths round up to a power of two
    — bounding the set of compiled shapes — then results are sliced back
    per request."""

    def __init__(self, server: ModelServer, max_batch: int = 32, window_ms: float = 3.0) -> None:
        import queue

        self.server = server
        self.max_batch = max_batch
        self.window_s = window_ms / 1e3
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._close_lock = threading.Lock()
        # decodes run for seconds; dispatching them off the worker thread
        # keeps fast forward groups from queueing behind them. One worker
        # preserves decode-group ordering.
        from concurrent.futures import ThreadPoolExecutor

        self._gen_pool = ThreadPoolExecutor(max_workers=1)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        self.batches = 0  # observability: device calls issued

    def _submit(self, kind: str, tokens: np.ndarray, n: int, samp=None):
        import concurrent.futures

        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 2 or tokens.shape[0] < 1 or tokens.shape[1] < 1:
            # validate BEFORE enqueueing: a malformed request inside _run
            # would fail every other request coalesced into its group (and
            # a zero-length prompt has no last position to decode from)
            raise ValueError(
                f"tokens must be non-empty 2-D [batch, seq], got shape {tokens.shape}"
            )
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        # enqueue under the close lock so a racing close() can't consume the
        # sentinel and exit between our check and our put (hung future)
        with self._close_lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._q.put((kind, tokens, n, samp, fut))
        return fut.result()

    def forward_argmax(self, tokens: np.ndarray) -> np.ndarray:
        return self._submit("fwd", tokens, 0)

    def generate(self, tokens: np.ndarray, max_new_tokens: int = 16,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
                 seed: int = 0) -> np.ndarray:
        """Returns [B, S + max_new_tokens] (prompt + generated), matching
        ModelServer.generate. Sampling controls are per-request: a coalesced
        batch can mix greedy and sampled rows (ops/sampling.py)."""
        return self._submit(
            "gen", tokens, max_new_tokens,
            (float(temperature), int(top_k), float(top_p), int(seed)),
        )

    def _worker(self) -> None:
        import queue

        while True:
            item = self._q.get()
            if item is None:
                self._drain_closed()
                return
            group = [item]
            deadline = time.monotonic() + self.window_s
            while len(group) < self.max_batch:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._run(group)
                    self._drain_closed()
                    return
                group.append(nxt)
            self._run(group)

    def _drain_closed(self) -> None:
        """Fail anything that raced past close() rather than hang its waiter."""
        import queue

        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                item[-1].set_exception(RuntimeError("batcher is closed"))

    def _run(self, group: list) -> None:
        fwd = [(t, f) for kind, t, _n, _s, f in group if kind == "fwd"]
        gen = [(t, n, s, f) for kind, t, n, s, f in group if kind == "gen"]
        if gen:
            # off-thread: a long decode must not head-of-line-block the next
            # window's forward requests
            try:
                self._gen_pool.submit(self._run_generate, gen)
            except RuntimeError:  # pool shut down by a racing close(): inline
                self._run_generate(gen)
        if fwd:
            self._run_forward(fwd)

    @staticmethod
    def _pack(token_rows: list) -> tuple:
        """Right-pad a list of [b,s] token arrays into one padded batch:
        seq to a multiple of 16, batch rows to a power of two — bounding
        the set of compiled shapes. Returns (batch, spans=[(start, b, s)])."""
        from modelx_tpu.models.decode import pad_seq_len

        rows = sum(t.shape[0] for t in token_rows)
        max_s = max(t.shape[1] for t in token_rows)
        pad_s = pad_seq_len(max_s)
        pad_b = 1 << (rows - 1).bit_length()
        batch = np.zeros((pad_b, pad_s), np.int32)
        r = 0
        spans = []
        for tokens in token_rows:
            b, s = tokens.shape
            batch[r : r + b, :s] = tokens
            spans.append((r, b, s))
            r += b
        return batch, spans

    def _run_forward(self, group: list) -> None:
        try:
            batch, spans = self._pack([t for t, _f in group])
            out = self.server.forward_argmax(batch)
            self.batches += 1
            for (tokens, fut), (start, b, s) in zip(group, spans):
                fut.set_result(out[start : start + b, :s])
        except BaseException as e:
            for _tokens, fut in group:
                if not fut.done():
                    fut.set_exception(e)

    def _run_generate(self, group: list) -> None:
        """Coalesce generate requests into one ragged decode: rows pad right
        to a common (16-aligned) length, decode steps round up to a power of
        two, each request slices back its own rows and first n tokens.
        Per-request sampling controls become per-row vectors; an all-greedy
        group takes the plain greedy program (no sampling compile)."""
        try:
            batch, spans = self._pack([t for t, _n, _s, _f in group])
            new_bucket = 1 << max(3, (max(n for _t, n, _s, _f in group) - 1).bit_length())
            pad_b = batch.shape[0]
            row_lens = np.ones(pad_b, np.int32)  # pad rows decode harmlessly
            for (start, b, s) in spans:
                row_lens[start : start + b] = s
            sampling: dict = {}
            if any(samp and samp[0] > 0 for _t, _n, samp, _f in group):
                temp = np.zeros(pad_b, np.float32)
                seeds = np.zeros(pad_b, np.int32)
                # filters only when some request asked: the filter-free
                # program skips a full-vocab sort per decode step
                # ...asked by a request that actually SAMPLES — a greedy
                # request's stray filter values must not force the sort
                use_k = any(samp and samp[0] > 0 and samp[1] > 0 for _t, _n, samp, _f in group)
                use_p = any(samp and samp[0] > 0 and samp[2] < 1.0 for _t, _n, samp, _f in group)
                top_k = np.zeros(pad_b, np.int32) if use_k else None
                top_p = np.ones(pad_b, np.float32) if use_p else None
                for (_t, _n, samp, _f), (start, b, _s) in zip(group, spans):
                    if samp:
                        temp[start : start + b] = samp[0]
                        if use_k:
                            top_k[start : start + b] = samp[1]
                        if use_p:
                            top_p[start : start + b] = samp[2]
                        # distinct per-row streams within a multi-row request
                        seeds[start : start + b] = (samp[3] + np.arange(b)) % (2**31)
                sampling = {"temperature": temp, "top_k": top_k,
                            "top_p": top_p, "seeds": seeds}
            out = self.server.generate_ragged(batch, row_lens, new_bucket, **sampling)
            self.batches += 1
            # the padded rows and the bucket rounding are implementation
            # details: account only the tokens requests asked for
            requested = sum(b * n for (_t, n, _ss, _f), (_r, b, _s) in zip(group, spans))
            self.server.stats["tokens_generated"] += requested
            for (tokens, n, _samp, fut), (start, b, _s) in zip(group, spans):
                generated = out[start : start + b, :n]
                fut.set_result(np.concatenate([tokens, generated], axis=1))
        except BaseException as e:
            for _tokens, _n, _samp, fut in group:
                if not fut.done():
                    fut.set_exception(e)

    def close(self) -> None:
        with self._close_lock:
            self._closed = True
            self._q.put(None)
        # let any in-flight decode finish delivering its futures
        self._gen_pool.shutdown(wait=False)


_MODEL_ROUTE = re.compile(r"^/v1/(?P<model>[A-Za-z0-9._-]+)/(?P<verb>forward|generate)$")
_ADMIN_MODEL_ROUTE = re.compile(r"^/admin/models/(?P<model>[A-Za-z0-9._-]+)(?:\?.*)?$")


class ServerSet:
    """Named ModelServers behind one HTTP front (multi-tenant serving)."""

    def __init__(self, servers: dict[str, ModelServer], default: str | None = None,
                 trace_dir: str = "", dynamic_batch: bool = False,
                 max_new_tokens_limit: int = DEFAULT_MAX_NEW_TOKENS_LIMIT,
                 continuous_batch: bool = False, max_slots: int = 8,
                 max_batch: int = 32, batch_window_ms: float = 3.0,
                 stream_chunk_size: int = 8, kv_page_size: int = 0,
                 kv_live_tokens: int = 0,
                 kv_attention: str = "gather",
                 pipeline_depth: int = 2,
                 dispatch_depth: int = 0,
                 burst_window_ms: float = 1.0,
                 prefill_chunk: int = 0,
                 prefill_budget: int = 0,
                 max_queue_depth: int = 0,
                 request_timeout_s: float = 0.0,
                 boundary_watchdog_s: float = 0.0,
                 hbm_budget_bytes: int = 0,
                 evict_idle: bool = False,
                 allow_admin_load: bool = False,
                 admin_tokens: tuple[str, ...] = (),
                 staging_root: str = "",
                 host_state_budget_bytes: int = 0,
                 disk_state_budget_bytes: int = 0,
                 state_spool_dir: str = "",
                 flight_recorder: bool = True,
                 flightrec_capacity: int = 0,
                 flight_dump_dir: str = "",
                 device_telemetry: bool = True) -> None:
        if not servers:
            raise ValueError("no models")
        self.max_new_tokens_limit = max_new_tokens_limit
        self.servers = servers
        # the model set is MUTABLE at runtime (dl/lifecycle.py admin
        # loads/unloads): every structural change goes through
        # add_server/remove_server under this lock
        self._servers_lock = threading.RLock()
        for name, s in servers.items():
            s.name = name  # route key and server identity must agree
        self.default = default or next(iter(servers))
        # template for runtime-loaded ModelServers (the pool's admin load
        # path): same mesh, dtype, context budget, quantization, and cache
        # knobs the boot-time set got — serve_main overrides as needed
        first = next(iter(servers.values()))
        self.server_defaults: dict = {
            "mesh": first.mesh,
            "dtype": "bfloat16" if first.dtype == jnp.bfloat16 else "float32",
            "max_seq_len": first.max_seq_len,
            "quantize": first.quantize,
            "speculative_k": first.speculative_k,
        }
        if first._prefix_cache is not None:
            # a runtime-loaded tenant must not silently lose the boot
            # set's prefix cache: its serving block would then have no
            # hit-rate signal for the router and no KV to publish
            self.server_defaults.update(
                prefix_cache_size=first._prefix_cache.capacity,
                prefix_cache_max_bytes=first._prefix_cache.max_bytes,
            )
        # bearer tokens gating the /admin surface (the registry auth
        # model's static-token tier; empty = anonymous admin, for
        # single-tenant dev pods and tests)
        self.admin_tokens = tuple(admin_tokens)
        self.trace_dir = trace_dir or os.path.join(os.getcwd(), "jax-trace")
        self._profiling = threading.Lock()
        # on-demand profiler captures (POST /admin/profile) land in
        # numbered subdirs under trace_dir; only the newest
        # MAX_PROFILE_CAPTURES survive (the capture dir is CAPPED — an
        # operator probing a live incident must not fill the disk)
        self._capture_seq = 0
        self._capture_lock = threading.Lock()
        # engine flight recorder + black-box dump dir (ISSUE 15), threaded
        # into every ContinuousBatcher this set creates
        self.flight_recorder = bool(flight_recorder)
        self.flightrec_capacity = int(flightrec_capacity)
        self.flight_dump_dir = str(flight_dump_dir or "")
        # measured device telemetry (utils/devmem) in engine snapshots and
        # the /metrics device family
        self.device_telemetry = bool(device_telemetry)
        # windowed pod rates (utils/tswheel): requests/s, 5xx/s, sheds/s
        # over 1m/5m, marked once per completed POST in the handler
        self.rates = tswheel.RateSet(("requests", "http_5xx", "sheds"))
        self._dynamic_batch = dynamic_batch
        self._continuous_batch = continuous_batch
        self.max_slots = max_slots
        # paged KV for the continuous engine: page_size > 0 switches the
        # engine's per-layer state to a page pool sized by kv_live_tokens
        # (see dl/continuous.py) — required for max_slots much beyond 8
        self.kv_page_size = kv_page_size
        self.kv_live_tokens = kv_live_tokens
        # "gather" = bit-exact dense view per step; "in-place" = blockwise
        # paged attention reading pools directly (see ContinuousBatcher)
        self.kv_attention = kv_attention
        # chunks the continuous engine keeps in flight before syncing the
        # oldest (hides the per-chunk fetch round-trip; value-dependent row
        # exits lag by up to this many chunks of wasted compute)
        self.pipeline_depth = pipeline_depth
        # decode chunks scanned per device program in steady decode
        # (amortizes the fixed dispatch cost; 0 = auto, 1 = per-chunk —
        # see ContinuousBatcher.dispatch_depth)
        self.dispatch_depth = dispatch_depth
        # idle-burst gather window (ms): co-arrivals at an idle engine admit
        # as one program + decode in step; 0 disables
        self.burst_window_ms = burst_window_ms
        # chunked prefill (Sarathi-style): prompts longer than one piece
        # land piece by piece between decode chunks instead of as one
        # monolithic admission prefill (0 = off); prefill_budget bounds
        # the per-boundary prefill tokens once decode rows have spent
        self.prefill_chunk = prefill_chunk
        self.prefill_budget = prefill_budget
        # bounded admission + deadlines for the continuous engine: submits
        # past max_queue_depth shed with 429 + Retry-After; requests older
        # than request_timeout_s expire with 504 at chunk boundaries
        self.max_queue_depth = max_queue_depth
        self.request_timeout_s = request_timeout_s
        # no-progress boundary watchdog for the continuous engine: a wedged
        # device dispatch (real on TPU) is treated as a crash after this
        # many seconds so the restart/breaker machinery applies (0 = off)
        self.boundary_watchdog_s = boundary_watchdog_s
        self.max_batch = max_batch
        self.batch_window_ms = batch_window_ms
        self.stream_chunk_size = stream_chunk_size
        self._batcher_lock = threading.Lock()
        self.batchers: dict[str, Batcher] = {}
        self.cbatchers: dict = {}  # name -> ContinuousBatcher
        self._engine_locks: dict[str, threading.Lock] = {}  # per-model creation
        # set on SIGTERM: /healthz flips to 503 so load balancers stop
        # routing here while in-flight requests finish (graceful drain)
        self.draining = False
        # live POST count (streams included, until their last byte): the
        # drain loop in serve_main waits for this to reach zero before
        # closing engines, instead of sleeping a fixed interval
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        # the lifecycle pool (dl/lifecycle.py): state machine + HBM budget
        # + in-flight accounting for every tenant, boot-time set included
        from modelx_tpu.dl.lifecycle import ModelPool

        self.pool = ModelPool(
            self, hbm_budget_bytes=hbm_budget_bytes, evict_idle=evict_idle,
            allow_admin_load=allow_admin_load, staging_root=staging_root,
            # the shared serving mesh: --hbm-budget-bytes is per-device
            # HBM, and on a weight-sharding mesh the pool divides each
            # model's footprint by the mesh's weight-shard factor
            mesh=first.mesh,
            # tiered live state (dl/tiers.py): demoted models stage in
            # host RAM/disk instead of being discarded, and a re-load of
            # the same content is a tier promotion
            host_state_budget_bytes=host_state_budget_bytes,
            disk_state_budget_bytes=disk_state_budget_bytes,
            state_spool_dir=state_spool_dir,
        )

    def request_began(self) -> None:
        """Count a POST as in-flight until its last byte is written — a
        streaming response stays in-flight for its whole body, which is
        what the SIGTERM drain loop must wait out."""
        with self._inflight_lock:
            self._inflight += 1

    def request_ended(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def next_capture_dir(self) -> str:
        """A fresh numbered capture dir under ``trace_dir/captures`` for
        one on-demand profiler run; prunes all but the newest
        ``MAX_PROFILE_CAPTURES - 1`` existing captures first (the new one
        brings the total back to the cap)."""
        import shutil

        root = os.path.join(self.trace_dir, "captures")
        # only the sequence bump needs the lock; the filesystem work runs
        # outside it (callers are already serialized by _profiling — this
        # lock just keeps the counter coherent for any future caller)
        with self._capture_lock:
            self._capture_seq += 1
            seq = self._capture_seq
        os.makedirs(root, exist_ok=True)
        keep = MAX_PROFILE_CAPTURES - 1
        old = sorted(
            (d for d in os.listdir(root)
             if d.startswith("cap-")
             and os.path.isdir(os.path.join(root, d))),
            key=lambda d: os.path.getmtime(os.path.join(root, d)),
        )
        for name in old[:max(0, len(old) - keep)]:
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        path = os.path.join(root, "cap-%d-%d" % (int(time.time()), seq))
        os.makedirs(path, exist_ok=True)
        return path

    def add_server(self, name: str, server: ModelServer) -> None:
        """Insert a runtime-loaded model into the routing set (the pool's
        READY transition)."""
        with self._servers_lock:
            server.name = name
            self.servers[name] = server

    def remove_server(self, name: str, close: bool = True):
        """Remove a model from routing; returns ``(server, batcher,
        engine)``. With ``close`` the window batcher and continuous engine
        close and the engine's device state (KV cache / page pool) is
        released here; the pool's unload path passes ``close=False`` and
        closes them OUTSIDE its lock, so freeing one tenant never stalls
        admission for the others."""
        with self._servers_lock:
            server = self.servers.pop(name, None)
            batcher = self.batchers.pop(name, None)
            cb = self.cbatchers.pop(name, None)
            self._engine_locks.pop(name, None)
            if self.default == name and self.servers:
                ready = [n for n, s in self.servers.items() if s.ready]
                self.default = (ready or list(self.servers))[0]
        if close:
            if batcher is not None:
                batcher.close()
            if cb is not None:
                cb.close()
                cb.release_device_state()
        return server, batcher, cb

    def batcher_for(self, server: ModelServer) -> "Batcher | None":
        """Lazily create a batcher once the model is loaded — only causal
        families batch (right-padding changes bidirectional-encoder
        outputs, see Batcher docstring)."""
        if not self._dynamic_batch or server.family is None or server.family.generate is None:
            return None
        b = self.batchers.get(server.name)
        if b is None:
            with self._batcher_lock:
                b = self.batchers.get(server.name)
                if b is None:
                    b = self.batchers[server.name] = Batcher(
                        server, max_batch=self.max_batch, window_ms=self.batch_window_ms
                    )
        return b

    def continuous_for(self, server: ModelServer):
        """Lazily create the continuous (in-flight) batching engine —
        cached-decode causal families only. When enabled it supersedes both
        the window batcher and speculation for generate/stream traffic:
        iteration-level scheduling owns the device. Construction (a full
        [max_slots, max_len] KV-cache allocation) runs under a PER-MODEL
        lock so other tenants' traffic never stalls behind it."""
        if (
            not self._continuous_batch
            or server.family is None
            or server.family.decode_fns is None
        ):
            return None
        cb = self.cbatchers.get(server.name)
        if cb is not None:
            return cb
        with self._batcher_lock:
            lk = self._engine_locks.setdefault(server.name, threading.Lock())
        with lk:
            cb = self.cbatchers.get(server.name)
            if cb is None:
                from modelx_tpu.dl.continuous import ContinuousBatcher

                max_len = server.max_seq_len
                n_pos = getattr(server.cfg, "n_positions", 0) or 0
                if n_pos:  # gpt2: positions past wpe silently clamp
                    max_len = min(max_len, n_pos)
                page_size = self.kv_page_size
                if page_size > 0 and max_len % page_size:
                    # gpt2-style clamped max_len may not be a page multiple:
                    # clamp max_len DOWN (losing < one page of context)
                    # rather than degrading to an arbitrary tiny page size
                    clamped = (max_len // page_size) * page_size
                    if clamped <= 0:
                        logger.warning(
                            "kv_page_size %d exceeds max_len %d for %s; "
                            "paged KV disabled", page_size, max_len, server.name,
                        )
                        page_size = 0
                    else:
                        logger.warning(
                            "max_len %d -> %d for %s (kv_page_size %d multiple)",
                            max_len, clamped, server.name, page_size,
                        )
                        max_len = clamped
                def build():
                    return ContinuousBatcher(
                        server, max_slots=self.max_slots,
                        chunk_size=self.stream_chunk_size, max_len=max_len,
                        prefix_cache=server._prefix_cache,
                        page_size=page_size,
                        max_live_tokens=self.kv_live_tokens,
                        paged_attention=self.kv_attention,
                        # --speculative-k composes with continuous batching:
                        # the engine speculates whenever exactly one greedy
                        # row is active (VERDICT r4: the flags must not be
                        # mutually exclusive)
                        speculative_k=server.speculative_k,
                        pipeline_depth=self.pipeline_depth,
                        dispatch_depth=self.dispatch_depth,
                        burst_window_ms=self.burst_window_ms,
                        prefill_chunk=self.prefill_chunk,
                        prefill_budget=self.prefill_budget,
                        max_queue_depth=self.max_queue_depth,
                        request_timeout_s=self.request_timeout_s,
                        boundary_watchdog_s=self.boundary_watchdog_s,
                        flight_recorder=self.flight_recorder,
                        flightrec_capacity=self.flightrec_capacity,
                        flight_dump_dir=self.flight_dump_dir,
                        device_telemetry=self.device_telemetry,
                    )

                try:
                    cb = build()
                except Exception as exc:
                    # RESOURCE_EXHAUSTED allocating the KV/page pool: demote
                    # idle tenants' state to the host tier and retry ONCE;
                    # anything else (or a dry pool) is a typed 503 — the
                    # request sheds instead of wedging the engine slot
                    from modelx_tpu.dl import tiers as tiers_mod
                    from modelx_tpu.dl.serving_errors import EngineBrokenError

                    if not tiers_mod.is_resource_exhausted(exc):
                        raise
                    freed = self.pool.shed_idle_for_bytes(
                        0, exclude=server.name)
                    self.pool.flightrec.record(
                        "engine.alloc_oom_retry", model=server.name,
                        freed_bytes=freed)
                    if freed <= 0:
                        raise EngineBrokenError(
                            f"KV allocation for {server.name} hit "
                            "RESOURCE_EXHAUSTED and no idle model could be "
                            "demoted") from exc
                    logger.warning(
                        "KV allocation for %s hit RESOURCE_EXHAUSTED; "
                        "demoted %d reserved bytes of idle state, retrying "
                        "once", server.name, freed,
                    )
                    try:
                        cb = build()
                    except Exception as exc2:
                        raise EngineBrokenError(
                            f"KV allocation for {server.name} failed after "
                            "demoting idle state") from exc2
                self.cbatchers[server.name] = cb
        return cb

    def serving_stats(self) -> dict:
        """Per-model load + locality stats for the fleet router's placement
        table (rides GET /admin/models next to the lifecycle states):
        ``queue_depth``/``active``/``waiting`` from the continuous engine
        (0s when the engine is off — the plain path has no backlog),
        ``engine_state``, and the prefix cache's entry/byte/hit counters —
        what prefix-sticky routing ranks pods by."""
        out: dict = {}
        # snapshot the mutable set under its lock (remove_server pops
        # entries at runtime); the per-engine reads below then run
        # lock-free like /metrics does
        with self._servers_lock:
            pairs = [(n, s, self.cbatchers.get(n))
                     for n, s in self.servers.items()]
        for name, s, cb in pairs:
            d: dict = {"queue_depth": 0, "active": 0, "waiting": 0}
            if cb is not None:
                snap = cb.snapshot()
                d["queue_depth"] = int(snap.get("queue_depth", 0))
                d["active"] = int(snap.get("active", 0))
                d["waiting"] = int(snap.get("waiting", 0))
                d["engine_state"] = snap.get("engine_state", "running")
            if s._prefix_cache is not None:
                d["prefix_cache"] = s._prefix_cache.stats()
            out[name] = d
        return out

    def engine_health(self) -> str | None:
        """Worst continuous-engine state across tenants, or None when every
        engine is healthy: "engine-broken" (circuit open — the pod needs a
        restart) beats "engine-restarting" (the supervisor is mid-backoff;
        load balancers should drain until it comes back)."""
        worst = None
        for cb in list(self.cbatchers.values()):
            state = getattr(cb, "engine_state", "running")
            if state == "broken":
                return "engine-broken"
            if state == "restarting":
                worst = "engine-restarting"
        return worst

    def engine_for(self, server: ModelServer, n_rows: int, temperature: float):
        """THE generate-routing policy, in one place: continuous batching
        (when enabled; with --speculative-k the ENGINE speculates whenever
        a single greedy row has the device to itself) > standalone
        speculation (single-row, --speculative-k) > window batcher > plain
        server."""
        cb = self.continuous_for(server)
        if cb is not None:
            return cb
        if (
            server.speculative_k > 0
            and n_rows == 1
            and server.family.decode_fns is not None
        ):
            # speculation's target shape (greedy = token-exact, sampled =
            # modified rejection); it must not be silently inert under
            # --dynamic-batch
            return server
        batcher = self.batcher_for(server)
        if batcher is not None and server.family.generate_ragged is not None:
            return batcher
        return server

    def stream_source(self, server: ModelServer, tokens, n: int, samp: dict,
                      stop_token_ids=None, timeout_s: float | None = None,
                      priority: str = "interactive", resume_step: int = 0,
                      request_id: str = "", timing: dict | None = None):
        """Streaming analogue of engine_for: a token-chunk iterator.
        Single-row streams join the continuous engine when enabled; all
        paths honor the operator's --stream-chunk-size and end early on a
        stop-token hit. ``timeout_s``/``priority`` (a propagated
        X-ModelX-Deadline-Ms remainder + priority class) reach only the
        continuous engine — the plain path has no deadline machinery, so
        the handler's up-front expiry check is its whole contract.
        ``resume_step`` > 0 continues a severed stream token-exactly (the
        row is ``prompt + emitted`` and sampling restarts at step k) —
        continuous-engine only; the plain path has no per-step sample
        streams to rejoin, so the handler refuses resume before we get
        here (MalformedResumeError, 400).
        ``request_id``/``timing`` (ISSUE 13) thread the end-to-end id
        into the engine ticket and return its phase breakdown via the
        caller's out-param — continuous-engine only; the plain path has
        no per-request phases to report."""
        cb = self.continuous_for(server)
        if cb is not None and tokens.shape[0] == 1:
            return cb.stream(tokens, max_new_tokens=n,
                             stop_token_ids=stop_token_ids,
                             timeout_s=timeout_s, priority=priority,
                             resume_step=resume_step,
                             request_id=request_id, timing=timing, **samp)
        if resume_step:
            raise MalformedResumeError(
                "resume requires the continuous engine (single-row stream)"
            )
        return server.generate_stream(
            tokens, max_new_tokens=n, chunk_size=self.stream_chunk_size,
            stop_token_ids=stop_token_ids, **samp
        )

    @property
    def ready(self) -> bool:
        """Readiness over the HEALTHY set: models whose load crashed are
        FAILED (degraded, reported on /healthz and /v1/models) but must
        not hold the whole pod un-ready forever — the other tenants are
        serving. Empty-or-all-failed is not ready."""
        if self.draining:
            return False
        with self._servers_lock:
            healthy = [s for s in self.servers.values() if s.load_error is None]
        return bool(healthy) and all(s.ready for s in healthy)

    def load_all(self, concurrent: bool = False) -> dict:
        """Load every model; ``concurrent`` overlaps the fetch phases (device
        transfers already funnel through the loader's transfer pool).

        One model failing marks ONLY that model FAILED (load_error set,
        pool state FAILED, reason on /v1/models) — the others keep
        serving. Only when EVERY model fails does the process-level error
        propagate (a single-tenant pod with a broken checkpoint should
        still crash-loop visibly)."""
        def _load(s: ModelServer, catch=Exception) -> None:
            if self.pool is not None:
                self.pool.mark_loading(s.name)
            try:
                s.load()
            except catch as e:
                s.load_error = str(e)
                errs[s.name] = e
                if self.pool is not None:
                    self.pool.mark_failed(s.name, str(e))
                logger.error("loading %s failed (tenant marked FAILED, "
                             "others keep serving): %s", s.name, e)
            else:
                if self.pool is not None:
                    self.pool.mark_ready(s.name)

        errs: dict[str, BaseException] = {}
        servers = list(self.servers.values())
        if concurrent and len(servers) > 1:
            # worker threads catch BaseException so a crash surfaces as a
            # FAILED tenant rather than a silently dead thread
            threads = [
                threading.Thread(target=_load, args=(s, BaseException),
                                 daemon=True)
                for s in servers
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            # sequential path runs on the MAIN thread: Exception only, so
            # an operator Ctrl-C (KeyboardInterrupt) still aborts the boot
            # instead of marking the in-flight model FAILED
            for s in servers:
                _load(s)
        if errs and len(errs) == len(servers):
            name, err = next(iter(errs.items()))
            raise RuntimeError(f"loading {name} failed: {err}") from err
        return {
            name: dict(s.stats, **({"error": s.load_error} if s.load_error else {}))
            for name, s in self.servers.items()
        }

    def resolve(self, path: str) -> tuple[ModelServer | None, str | None]:
        """(server, verb) for a POST path; (None, None) if unroutable."""
        with self._servers_lock:
            if path in ("/v1/forward", "/v1/generate"):
                server = self.servers.get(self.default)
                return server, (path.rsplit("/", 1)[1] if server else None)
            m = _MODEL_ROUTE.match(path)
            if m and m.group("model") in self.servers:
                return self.servers[m.group("model")], m.group("verb")
        return None, None

    def route_name(self, path: str) -> str | None:
        """The model name a POST path addresses (resolved or not) — the
        404 path asks the pool about THIS name before giving up, so a
        PULLING/LOADING model answers 503 + Retry-After instead of 404."""
        if path in ("/v1/forward", "/v1/generate"):
            return self.default
        m = _MODEL_ROUTE.match(path)
        return m.group("model") if m else None


def _query_param(path: str, name: str) -> str:
    """One query-string value from a raw request path ("" when absent)."""
    from urllib.parse import parse_qs, urlparse

    vals = parse_qs(urlparse(path).query).get(name)
    return vals[0] if vals else ""


def propagated_timeout(headers) -> float | None:
    """The caller's remaining budget from ``X-ModelX-Deadline-Ms``
    (stamped by the fleet router per upstream attempt; the header name
    AND its parser are shared with the router via serving_errors so the
    two halves of the wire contract cannot drift): None = no propagated
    deadline, else remaining seconds (0.0 = the caller's budget is
    ALREADY gone — answer 504 without doing any work). The engine clamps
    its own --request-timeout to this remainder, so a router failover
    never re-grants a fresh full timeout."""
    return parse_deadline_ms(headers.get(DEADLINE_HEADER))


def request_priority(headers) -> str:
    """Priority class from ``X-ModelX-Priority`` (shared parser: batch
    only on an explicit opt-in). Batch rows queue behind interactive
    ones at the engine's admission boundary."""
    return parse_priority(headers.get(PRIORITY_HEADER))


def serve(servers: ModelServer | ServerSet, listen: str = ":8000",
          access_log: str = "", access_log_max_bytes: int = 0) -> ThreadingHTTPServer:
    sset = servers if isinstance(servers, ServerSet) else ServerSet({servers.name: servers})
    access = accesslog.open_log(access_log, max_bytes=access_log_max_bytes)

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def send_response(self, code, message=None):
            # remember the committed status for the access-log line (one
            # capture point covers _json AND the streaming 200)
            self._resp_status = code
            super().send_response(code, message)

        def _obs_headers(self) -> None:
            """Echo the request id + attempt on EVERY response (JSON and
            streamed): the client joins its response to the fleet's logs
            and traces by this one header. No-op on paths that never
            bound an id (GETs)."""
            rid = getattr(self, "_rid", "")
            if rid:
                self.send_header(REQUEST_ID_HEADER, rid)
                self.send_header(ATTEMPT_HEADER, str(self._attempt))

        def _json(self, status: int, obj, headers: dict | None = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self._obs_headers()
            if getattr(self, "_rid", ""):
                # the non-streaming timing contract: whatever phases this
                # request reached ride as X-ModelX-Timing-* headers — a
                # 504 still reports the queue time it burned
                timing = dict(self._timing)
                timing["total_ms"] = round(
                    (time.monotonic() - self._t0) * 1e3, 3)
                for k, v in timing_headers(timing).items():
                    self.send_header(k, v)
            for k, v in (headers or {}).items():  # e.g. Retry-After on 429
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _text(self, status: int, text: str, content_type: str) -> None:
            body = text.encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _stream_chunks(self, content_type: str, payloads, error_payload) -> None:
            """Commit a 200 + chunked transfer encoding and write each bytes
            payload. A mid-stream error (status already on the wire) writes
            ``error_payload(e)``; the terminator always goes out. Shared by
            the NDJSON token stream and the OpenAI SSE stream."""
            self.send_response(200)
            self.send_header("Content-Type", content_type)
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self._obs_headers()
            self.end_headers()

            def write_chunk(payload: bytes) -> None:
                self.wfile.write(f"{len(payload):x}\r\n".encode())
                self.wfile.write(payload + b"\r\n")

            try:
                for payload in payloads:
                    write_chunk(payload)
            except Exception as e:
                logger.exception("stream error")
                try:
                    write_chunk(error_payload(e))
                except OSError:
                    pass  # client went away
            finally:
                try:
                    self.wfile.write(b"0\r\n\r\n")  # chunked terminator
                except OSError:
                    pass

        def _stream_generate(self, server, tokens, n, samp, stop_ids=None,
                             timeout_s=None, priority="interactive",
                             resume_step=0, include_timing=False) -> None:
            """NDJSON token stream, then {"done": true}; concatenates to
            the non-streaming result. Single-row streams emit ONE token
            per line ({"tokens": [[t]]}): position-independent framing, so
            a router splicing a continuation (resume after a pod death)
            produces a body byte-identical to the uninterrupted stream
            regardless of where the original died relative to chunk
            boundaries. Multi-row streams (plain path only) keep one line
            per decoded chunk. Single-row streams ride the continuous
            engine when enabled, so N concurrent clients share one running
            decode instead of contending with N independent loops."""
            kw = deadline_kwargs(timeout_s, priority)
            if resume_step:
                kw["resume_step"] = resume_step
            timing: dict = self._timing
            gen = sset.stream_source(server, tokens, n, samp,
                                     stop_token_ids=stop_ids,
                                     request_id=getattr(self, "_rid", ""),
                                     timing=timing, **kw)
            try:
                # pull the first chunk BEFORE committing a 200: an
                # unsupported family / bad request must still be a 4xx
                first = next(gen, None)
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            except ServingError as e:
                # typed serving failures (queue full / deadline / engine
                # broken) carry their canonical status + headers — a shed
                # stream request still gets its 429 + Retry-After
                return self._json(e.http_status, {"error": str(e)},
                                  headers=e.headers())

            def payloads():
                emitted = 0
                if first is not None:
                    for piece in itertools.chain([first], gen):
                        rows = piece.tolist()
                        if len(rows) == 1:
                            for t in rows[0]:
                                emitted += 1
                                yield (json.dumps({"tokens": [[t]]}).encode()
                                       + b"\n")
                        else:
                            emitted += sum(len(r) for r in rows)
                            yield (json.dumps({"tokens": rows}).encode()
                                   + b"\n")
                if include_timing:
                    # OPT-IN final timing line, BEFORE the done line. The
                    # default stream is byte-unchanged — the router's
                    # continuation splice and the byte-equality contract
                    # it tests depend on that. gen.close() runs the
                    # engine-side finally, so the breakdown is complete.
                    gen.close()
                    yield (json.dumps(
                        {"timing": self._finish_timing(timing, emitted)}
                    ).encode() + b"\n")
                yield b'{"done": true}\n'

            self._stream_chunks(
                "application/x-ndjson", payloads(),
                lambda e: json.dumps({"error": str(e)}).encode() + b"\n",
            )

        def _finish_timing(self, timing: dict, emitted: int) -> dict:
            """Complete a phase breakdown with the handler-side view:
            wall total, emitted count, and the decode rate (tokens after
            the first over the post-TTFT wall time)."""
            t = dict(timing)
            total_ms = round((time.monotonic() - self._t0) * 1e3, 3)
            t["total_ms"] = total_ms
            t["tokens"] = emitted
            ttft = t.get("ttft_ms")
            if ttft is not None and emitted > 1 and total_ms > ttft:
                t["decode_tps"] = round(
                    (emitted - 1) / ((total_ms - ttft) / 1e3), 2)
            self._timing.update(t)  # the access-log line sees it too
            return t

        def _openai(self, req: dict, chat: bool) -> None:
            """/v1/completions + /v1/chat/completions (openai_api.py). SSE
            for stream=true; errors use the OpenAI {"error": {...}} shape."""
            from modelx_tpu.dl import openai_api as oai

            # lifecycle gate, in the OpenAI error shape: PULLING/LOADING
            # 503 + Retry-After, DRAINING 409, FAILED 503 + reason — the
            # SAME typed errors the native surface maps
            name = str(req.get("model") or sset.default)
            self._log_model = name
            if sset.pool is not None:
                try:
                    sset.pool.check_admission(name)
                    sset.pool.enter(name)  # raises 409 if a drain raced in
                except ServingError as e:
                    api = oai.api_error_for(e)
                    return self._json(api.status, api.payload, headers=e.headers())
            # deadline propagation + priority class, same contract as the
            # native path: expired budgets 504 in the OpenAI error shape
            # before any engine work, live ones clamp the engine deadline
            timeout_s = propagated_timeout(self.headers)
            priority = request_priority(self.headers)
            if timeout_s is not None and timeout_s <= 0:
                e = DeadlineExceededError("admitting", timeout_s)
                api = oai.api_error_for(e)
                if sset.pool is not None:
                    sset.pool.exit(name)
                return self._json(api.status, api.payload, headers=e.headers())
            try:
                # mid-stream failover resume (ISSUE 12): the SAME wire
                # block as the native surface — router headers win over a
                # native ``resume`` field. Validation and token-exact
                # continuation run here too; the fleet router only
                # SPLICES native NDJSON streams (docs/router.md), but the
                # pod-side contract must not differ between surfaces.
                resume = None
                hdr_e = self.headers.get(RESUME_EMITTED_HEADER)
                hdr_s = self.headers.get(RESUME_SEED_HEADER)
                if hdr_e is not None or hdr_s is not None:
                    resume = parse_resume(hdr_e, hdr_s)
                else:
                    rz = req.get("resume")
                    if rz is not None:
                        if not isinstance(rz, dict):
                            raise MalformedResumeError(
                                "resume must be an object with emitted + seed")
                        resume = parse_resume(rz.get("emitted"), rz.get("seed"))
                if resume is not None and not bool(req.get("stream", False)):
                    raise MalformedResumeError(
                        "resume requires a streaming request")
                if bool(req.get("stream", False)):
                    events = oai.stream_completion(sset, req, chat,
                                                   timeout_s=timeout_s,
                                                   priority=priority,
                                                   resume=resume,
                                                   request_id=self._rid,
                                                   timing=self._timing)
                    try:
                        # validation + compile errors must surface as a real
                        # status, so pull the first event before the 200
                        # (stream_completion primes generation before its
                        # first yield, chat role chunk included)
                        first = next(events, None)
                    except ValueError as e:
                        raise oai.APIError(400, str(e)) from e

                    def payloads():
                        if first is not None:
                            yield oai.sse_encode(first)
                            for ev in events:
                                yield oai.sse_encode(ev)
                        yield oai.SSE_DONE

                    return self._stream_chunks(
                        "text/event-stream", payloads(),
                        # mid-stream failures: typed serving errors keep
                        # their one canonical payload even after the 200
                        # is on the wire (a deadline expiry mid-SSE reads
                        # the same as a pre-stream 504 body)
                        lambda e: oai.sse_encode(
                            oai.api_error_for(e).payload
                            if isinstance(e, ServingError)
                            else {"error": {"message": str(e), "type": "server_error"}}
                        ),
                    )
                return self._json(200, oai.run_completion(
                    sset, req, chat, timeout_s=timeout_s, priority=priority,
                    request_id=self._rid, timing=self._timing))
            except oai.APIError as e:
                # typed lifecycle 503s raised inside the API layer carry
                # Retry-After like the native surface's (satellite:
                # resolve_model's still-loading must back clients off)
                return self._json(e.status, e.payload,
                                  headers=getattr(e, "headers", None))
            except ValueError as e:
                return self._json(400, oai.APIError(400, str(e)).payload)
            except ServingError as e:
                # one OpenAI-shaped payload per typed failure class: 429
                # sheds carry Retry-After, deadlines 504, engine death 503
                api = oai.api_error_for(e)
                return self._json(api.status, api.payload, headers=e.headers())
            except Exception as e:
                logger.exception("openai api error")
                return self._json(500, oai.APIError(500, str(e), "server_error").payload)
            finally:
                if sset.pool is not None:
                    sset.pool.exit(name)

        def _admin_auth(self) -> bool:
            """Bearer-token filter for the /admin surface (the registry
            auth model's static-token tier — --admin-token). Empty token
            set = anonymous admin. Returns False after writing the 401."""
            if not sset.admin_tokens:
                return True
            import hmac

            authz = self.headers.get("Authorization", "")
            presented = authz[len("Bearer "):] if authz.startswith("Bearer ") else ""
            # constant-time per candidate: the admin surface controls model
            # load/unload, so token comparison must not leak prefix timing
            if any(hmac.compare_digest(presented, t) for t in sset.admin_tokens):
                return True
            self._json(401, {"error": "invalid or missing bearer token"})
            return False

        def do_GET(self):
            # GETs share keep-alive connections with POSTs: clear the
            # per-request observability state a previous POST bound
            self._rid = ""
            self._resp_status = 0
            if self.path == "/healthz":
                from modelx_tpu.dl import manifest_cache

                engine = sset.engine_health()
                failed = sset.pool.failed() if sset.pool is not None else {}
                # registry reachability rides ALONGSIDE readiness, never
                # into it: a pod serving READY models through a registry
                # outage stays 200/routable — control_plane is the
                # operator/rebalancer signal that freshness is degraded
                cp = manifest_cache.health().status()
                if engine is not None:
                    # a crash-looping or circuit-broken engine must flip
                    # readiness so load balancers drain instead of routing
                    # every request into a dead engine
                    self._json(503, {"status": engine, "control_plane": cp})
                elif sset.ready:
                    # degraded: some tenants FAILED to load, the rest are
                    # serving — stay routable but say who is down and why
                    if failed:
                        self._json(200, {"status": "degraded", "failed": failed,
                                         "control_plane": cp})
                    else:
                        self._json(200, {"status": "ok", "control_plane": cp})
                else:
                    status = "draining" if sset.draining else (
                        "failed" if failed else "loading"
                    )
                    body = {"status": status, "control_plane": cp}
                    if failed:
                        body["failed"] = failed
                    # loading resolves on its own: tell the LB when to look
                    # again (the same contract the 429 shed path set)
                    headers = {} if sset.draining else {"Retry-After": "2"}
                    self._json(503, body, headers=headers)
            elif self.path == "/livez":
                # liveness, distinct from readiness: fails ONLY on the
                # unrecoverable engine-broken state (circuit open), so the
                # podspec livenessProbe restarts the pod — the blob cache +
                # compile cache make that restart cheap. Loading, draining,
                # and supervised restarting are all ALIVE (killing a pod
                # mid-load/drain/backoff would turn recoverable states into
                # restart loops).
                if sset.engine_health() == "engine-broken":
                    self._json(503, {"status": "engine-broken"})
                else:
                    self._json(200, {"status": "ok"})
            elif self.path.split("?", 1)[0] == "/metrics":
                payload = {}
                lifecycle = sset.pool.states() if sset.pool is not None else {}
                for n, s in list(sset.servers.items()):
                    d = dict(s.stats)
                    cb = sset.cbatchers.get(n)
                    if cb is not None:
                        # counters + live gauges (chunks/admitted/
                        # active_peak, prefill_pieces, stall_ms_max,
                        # spec accept stats, pages) — the operator/bench
                        # surface for the engine, no internals poking
                        d["continuous"] = cb.snapshot()
                    if s._prefix_cache is not None:
                        d["prefix_cache"] = s._prefix_cache.stats()
                    if n in lifecycle:
                        # per-model lifecycle gauges: state, loads_total,
                        # evictions_total, hbm_reserved_bytes, drain_seconds
                        d["lifecycle"] = lifecycle[n]
                    payload[n] = d
                for n, st in lifecycle.items():
                    if n not in payload:  # PULLING/UNLOADED: no server yet
                        payload[n] = {"lifecycle": st}
                if sset.pool is not None and "pool" not in payload:
                    payload["pool"] = sset.pool.pool_snapshot()
                # pod-level windowed rates (ISSUE 15): requests/s,
                # 5xx/s, sheds/s over 1m and 5m — floats, so they
                # render as gauges in the Prometheus view for free
                payload["rates"] = sset.rates.snapshot()
                # registry reachability counters (PR 19); the string
                # state key is JSON-only, the totals render as gauges
                from modelx_tpu.dl import manifest_cache as _mc

                payload["control_plane"] = _mc.health().status()
                if sset.device_telemetry:
                    # measured device memory next to the lifecycle
                    # ESTIMATES (hbm_reserved_bytes): the source key is
                    # a string, skipped by the text renderer, kept in
                    # JSON so a reader knows how it was measured
                    payload["device"] = devmem.sample()
                # content negotiation (ISSUE 13): the SAME tree renders
                # as Prometheus text on Accept: text/plain or
                # ?format=prometheus; the default JSON is byte-unchanged
                fmt = _query_param(self.path, "format")
                if promexp.wants_prometheus(self.headers.get("Accept"), fmt):
                    # the second rule labels the per-device HBM breakdown
                    # (payload["device"]["devices"][i]) with device="<i>"
                    # instead of minting one metric name per device index
                    self._text(200, promexp.render(
                        payload, label_levels={
                            ("*",): "model",
                            ("*", "devices", "*"): "device",
                        }),
                        promexp.CONTENT_TYPE)
                else:
                    self._json(200, payload)
            elif self.path == "/admin/models":
                if not self._admin_auth():
                    return
                from modelx_tpu.dl import manifest_cache

                self._json(200, {
                    "models": sset.pool.states(),
                    "pool": sset.pool.pool_snapshot(),
                    # per-model serving load + locality stats: the fleet
                    # router ranks stickiness (prefix-cache state) and
                    # load (queue depth) from THIS one endpoint instead of
                    # scraping /metrics too (PR 8)
                    "serving": sset.serving_stats(),
                    # registry reachability (PR 19): ok|degraded|offline —
                    # the rebalancer reads this to go observe-only when
                    # the whole fleet has lost the control plane
                    "control_plane": manifest_cache.health().status(),
                })
            elif self.path == "/v1/models":
                from modelx_tpu.dl import openai_api as oai

                # one body, two contracts: the native {default, models} keys
                # plus OpenAI's {object: "list", data: [...]}
                self._json(200, oai.models_payload(sset))
            elif self.path.split("?", 1)[0] == "/v1/trace":
                # ?request_id= filters the summary to one request's
                # timeline; ?prefix= narrows by span path (both optional)
                self._json(200, trace.tracer().summary(
                    prefix=_query_param(self.path, "prefix"),
                    request_id=_query_param(self.path, "request_id"),
                ))
            elif self.path.split("?", 1)[0] == "/debug/flightrec":
                # the live flight-recorder ring (ISSUE 15): the same
                # timeline the black-box dump freezes, served while the
                # engine is still flying. Admin-gated — events carry
                # request ids — with /v1/trace's ?request_id= slicing.
                if not self._admin_auth():
                    return
                rid = _query_param(self.path, "request_id") or None
                body = {}
                for n, cb in list(sset.cbatchers.items()):
                    if cb.flightrec is not None:
                        body[n] = cb.flightrec.summary(rid)
                # pool-level ring: tier promotions/demotions, OOM retries
                body["pool"] = sset.pool.flightrec.summary(rid)
                self._json(200, body)
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            # pod-level in-flight accounting for coordinated drain: a
            # SIGTERM'd pod stops admitting (ready flips false) and waits
            # for this count — streams included, until their LAST byte —
            # to reach zero before closing engines (serve_main's
            # --drain-grace loop), instead of sleeping a fixed interval
            sset.request_began()
            # end-to-end request identity (ISSUE 13): honor the router's
            # (or client's) id, mint one for direct traffic; the id binds
            # every span this handler thread closes, echoes on the
            # response, and threads into the engine ticket
            self._rid = (parse_request_id(self.headers.get(REQUEST_ID_HEADER))
                         or mint_request_id())
            self._attempt = parse_attempt(self.headers.get(ATTEMPT_HEADER))
            self._timing = {}
            self._resp_status = 0
            self._log_model = ""
            self._t0 = time.monotonic()
            path = self.path.split("?", 1)[0]
            try:
                with trace.request_context(self._rid), \
                        trace.span("serve.request", http_path=path,
                                   attempt=self._attempt):
                    self._do_POST()
            finally:
                sset.request_ended()
                # windowed fleet rates (ISSUE 15): one mark per request
                # plus outcome classes, bucketed into 1-s wheels the
                # /metrics snapshot reads as *_per_s_{1m,5m}
                sset.rates.mark("requests")
                if self._resp_status >= 500:
                    sset.rates.mark("http_5xx")
                elif self._resp_status == 429:
                    sset.rates.mark("sheds")
                if access is not None:
                    access.write(
                        request_id=self._rid,
                        attempt=self._attempt,
                        client=_client_hash(self.headers,
                                            self.client_address),
                        path=path,
                        model=self._log_model,
                        status=self._resp_status,
                        ms=round((time.monotonic() - self._t0) * 1e3, 3),
                        timing=self._timing,
                    )

        def _do_POST(self):
            length = int(self.headers.get("Content-Length", 0) or 0)
            try:
                req = json.loads(self.rfile.read(length)) if length else {}
            except ValueError as e:
                return self._json(400, {"error": f"bad request: {e}"})

            if not isinstance(req, dict):
                # a non-object body ({"tokens": ...} is the contract) must be
                # a 400, not an uncaught TypeError that drops the connection
                return self._json(400, {"error": "request body must be a JSON object"})

            if self.path == "/v1/profile":
                try:
                    seconds = float(req.get("seconds", 3))
                except (TypeError, ValueError):
                    seconds = -1.0
                if not (0 <= seconds <= MAX_PROFILE_SECONDS):
                    return self._json(
                        400,
                        {"error": f"seconds must be a number in [0, {MAX_PROFILE_SECONDS}]"},
                    )
                if not sset._profiling.acquire(blocking=False):
                    return self._json(409, {"error": "profile already running"})
                try:
                    with trace.jax_profile(sset.trace_dir):
                        time.sleep(seconds)
                finally:
                    sset._profiling.release()
                return self._json(200, {"trace_dir": sset.trace_dir})

            if self.path == "/admin/profile":
                # on-demand XLA profiler capture (ISSUE 15): same
                # one-at-a-time lock as /v1/profile, but admin-gated and
                # writing into a fresh CAPPED capture dir (the oldest
                # captures age out) so repeated captures on a live pod
                # never grow the disk without bound
                if not self._admin_auth():
                    return
                try:
                    seconds = float(req.get("duration_s", 3))
                except (TypeError, ValueError):
                    seconds = -1.0
                if not (0 < seconds <= MAX_PROFILE_SECONDS):
                    return self._json(
                        400,
                        {"error": "duration_s must be a number in "
                                  f"(0, {MAX_PROFILE_SECONDS}]"},
                    )
                if not sset._profiling.acquire(blocking=False):
                    return self._json(409, {"error": "profile already running"})
                try:
                    capture_dir = sset.next_capture_dir()
                    with trace.jax_profile(capture_dir):
                        time.sleep(seconds)
                finally:
                    sset._profiling.release()
                return self._json(200, {"capture_dir": capture_dir,
                                        "duration_s": seconds})

            if self.path == "/admin/models":
                # runtime load: pull a registry ref (or point at a local
                # dir) and materialize it while traffic is live
                if not self._admin_auth():
                    return
                from modelx_tpu.dl.lifecycle import PoolError

                wait = bool(req.get("wait", False))
                try:
                    snap = sset.pool.request_load(
                        str(req.get("name") or ""),
                        ref=str(req.get("ref") or ""),
                        model_dir=str(req.get("model_dir") or ""),
                        wait=wait,
                    )
                except PoolError as e:
                    # a 507 that demotion could clear carries Retry-After
                    # (ISSUE 18's 507 contract); hard refusals carry none
                    return self._json(e.status, {"error": str(e)},
                                      headers=e.headers or None)
                return self._json(200 if wait else 202, snap)

            if self.path in ("/v1/completions", "/v1/chat/completions"):
                return self._openai(req, chat=self.path.endswith("chat/completions"))

            server, verb = sset.resolve(self.path)
            if server is not None:
                self._log_model = server.name
            if server is None:
                # a name the routing set doesn't know may still be a
                # lifecycle entry: PULLING/LOADING answers 503 +
                # Retry-After (it will be READY shortly), DRAINING 409,
                # FAILED 503 + reason; only truly unknown names 404
                name = sset.route_name(self.path)
                err = (
                    sset.pool.routing_error(name)
                    if (sset.pool is not None and name) else None
                )
                if err is not None:
                    return self._json(err.http_status, {"error": str(err)},
                                      headers=err.headers())
                return self._json(404, {"error": "not found"})
            try:
                # lifecycle gate for resolved models too: DRAINING models
                # still sit in the routing set while in-flight requests
                # finish, but must not admit new ones (409)
                if sset.pool is not None:
                    sset.pool.check_admission(server.name)
            except ServingError as e:
                return self._json(e.http_status, {"error": str(e)},
                                  headers=e.headers())
            # deadline propagation (ISSUE 9): the router stamps each
            # upstream attempt's REMAINING budget — a failover hop must
            # not restart the clock. Already-expired budgets 504 before
            # any tokenization or engine work; live ones clamp the
            # engine's own --request-timeout below.
            timeout_s = propagated_timeout(self.headers)
            priority = request_priority(self.headers)
            if timeout_s is not None and timeout_s <= 0:
                e = DeadlineExceededError("admitting", timeout_s)
                return self._json(e.http_status, {"error": str(e)},
                                  headers=e.headers())
            if "text" in req and "tokens" in req:
                # generating from the tokens while silently dropping the text
                # would answer the wrong prompt; make the caller pick one
                return self._json(400, {"error": "send either text or tokens, not both"})
            if "text" in req and verb != "generate":
                # text is a generate-only contract (docs/api.md); a typo'd
                # endpoint must not return an undocumented hybrid response
                return self._json(400, {"error": "text is only supported on generate"})
            try:
                tok = None
                if "text" in req:
                    # text in, text out — needs the model's tokenizer.json
                    if not isinstance(req["text"], str) or not req["text"]:
                        raise ValueError("text must be a non-empty string")
                    if bool(req.get("stream", False)):
                        return self._json(400, {
                            "error": "text with stream is not supported; send token ids"
                        })
                    try:
                        tok = server.tokenizer()
                    except RuntimeError as e:  # file exists, load failed
                        return self._json(503, {"error": str(e)})
                    if tok is None:
                        return self._json(400, {
                            "error": "model has no tokenizer.json; send token ids"
                        })
                    ids = tok.encode(req["text"])
                    if not ids:
                        raise ValueError("text tokenized to zero tokens")
                    tokens = np.asarray([ids], np.int32)
                else:
                    tokens = np.asarray(req["tokens"], np.int32)
                if tokens.ndim != 2 or tokens.shape[0] < 1 or tokens.shape[1] < 1:
                    raise ValueError(
                        f"tokens must be non-empty 2-D [batch, seq], got shape {tokens.shape}"
                    )
            except (ValueError, KeyError, TypeError, OverflowError) as e:
                # numpy raises OverflowError for ids outside int32 and
                # TypeError for null/ragged rows — those are 400s, not a
                # dropped connection
                return self._json(400, {"error": f"bad request: {e}"})
            if not server.ready:
                # 503 + Retry-After, like the 429 shed path: load
                # balancers and the retrying RegistryClient back off and
                # come back once the load lands READY
                e = ModelLoadingError(server.name)
                return self._json(e.http_status, {"error": str(e)},
                                  headers=e.headers())
            vocab = getattr(server.cfg, "vocab_size", 0) or 0
            if vocab and (int(tokens.min()) < 0 or int(tokens.max()) >= vocab):
                # inside jit the gather CLAMPS out-of-range ids (silent
                # garbage); this also catches a tokenizer.json whose vocab
                # outgrew the checkpoint's embedding table
                return self._json(400, {"error": f"token ids must be in [0, {vocab})"})
            n_pos = getattr(server.cfg, "n_positions", 0) or 0
            if n_pos and tokens.shape[1] > n_pos:
                # absolute-position families (gpt2 wpe): the position gather
                # would clamp inside jit past n_positions and return
                # plausible garbage — same failure mode as the vocab check
                return self._json(400, {
                    "error": f"prompt length {tokens.shape[1]} exceeds the "
                    f"model's {n_pos}-position context"
                })
            server.stats["requests"] += 1
            if sset.pool is not None:
                # in-flight accounting: the pool's drain waits for this
                # request to finish before freeing the model (streams
                # complete inside this handler, so exit() fires after the
                # last chunk is on the wire); a drain that started since
                # the admission check above refuses here instead (409)
                try:
                    sset.pool.enter(server.name)
                except ServingError as e:
                    return self._json(e.http_status, {"error": str(e)},
                                      headers=e.headers())
            try:
                if verb == "forward":
                    batcher = sset.batcher_for(server)
                    out = (batcher or server).forward_argmax(tokens)
                    self._json(200, {"logits_argmax": out.tolist()})
                else:
                    try:
                        n = int(req.get("max_new_tokens", 16))
                    except (TypeError, ValueError):
                        return self._json(400, {"error": "max_new_tokens must be an integer"})
                    if not (1 <= n <= sset.max_new_tokens_limit):
                        # an unauthenticated client must not be able to force
                        # a huge compile / HBM alloc with one request
                        return self._json(
                            400,
                            {
                                "error": "max_new_tokens must be in "
                                f"[1, {sset.max_new_tokens_limit}]"
                            },
                        )
                    if n_pos and tokens.shape[1] + n > n_pos:
                        # decode past n_positions would silently clamp the
                        # wpe gather (ADVICE r3, gpt2.py:101)
                        return self._json(400, {
                            "error": f"prompt ({tokens.shape[1]}) + "
                            f"max_new_tokens ({n}) exceeds the model's "
                            f"{n_pos}-position context"
                        })
                    try:
                        samp = {
                            "temperature": float(req.get("temperature", 0.0)),
                            "top_k": int(req.get("top_k", 0)),
                            "top_p": float(req.get("top_p", 1.0)),
                            "seed": int(req.get("seed", 0)),
                        }
                    except (TypeError, ValueError):
                        return self._json(
                            400, {"error": "temperature/top_k/top_p/seed must be numbers"}
                        )
                    if (
                        not (0.0 <= samp["temperature"] <= 100.0)
                        or not (0 <= samp["top_k"] < 2**31)
                        or not (0.0 < samp["top_p"] <= 1.0)
                        or not (0 <= samp["seed"] < 2**31)
                        # int32 vectors carry these on device; out-of-range
                        # values must 400 here, not overflow a whole batch
                    ):
                        return self._json(400, {
                            "error": "temperature in [0,100], top_k/seed in "
                            "[0, 2^31), top_p in (0,1] required"
                        })
                    stop_ids = req.get("stop_token_ids")
                    if stop_ids is not None:
                        if (
                            not isinstance(stop_ids, list)
                            or len(stop_ids) > 16
                            or not all(isinstance(t, int) and not isinstance(t, bool)
                                       and 0 <= t < (vocab or 2**31) for t in stop_ids)
                        ):
                            return self._json(400, {
                                "error": "stop_token_ids must be a list of up "
                                "to 16 in-vocab token ids"
                            })
                    # mid-stream failover resume (ISSUE 12): both surfaces
                    # carry the same block — X-ModelX-Resume-* headers (the
                    # router's continuation path) win over the native
                    # ``resume`` field (a resumed client request that is
                    # itself being continued keeps the router's LONGER
                    # emitted list); each surface is both-or-neither
                    resume = None
                    resume_step = 0
                    try:
                        hdr_e = self.headers.get(RESUME_EMITTED_HEADER)
                        hdr_s = self.headers.get(RESUME_SEED_HEADER)
                        if hdr_e is not None or hdr_s is not None:
                            resume = parse_resume(hdr_e, hdr_s)
                        else:
                            rz = req.get("resume")
                            if rz is not None:
                                if not isinstance(rz, dict):
                                    raise MalformedResumeError(
                                        "resume must be an object with "
                                        "emitted + seed")
                                resume = parse_resume(rz.get("emitted"),
                                                      rz.get("seed"))
                        if resume is not None:
                            emitted, rseed = resume
                            if (not bool(req.get("stream", False))
                                    or tokens.shape[0] != 1):
                                raise MalformedResumeError(
                                    "resume requires a single-row "
                                    "streaming request")
                            if vocab and max(emitted) >= vocab:
                                raise MalformedResumeError(
                                    f"emitted token ids must be in "
                                    f"[0, {vocab})")
                            if len(emitted) >= n:
                                # the original stream was COMPLETE — the
                                # router finishes the client stream (done
                                # line) instead of re-decoding anything
                                raise ResumeExhaustedError(
                                    f"{len(emitted)} tokens already "
                                    f"emitted of a {n}-token budget")
                            if stop_ids and any(t in stop_ids
                                                for t in emitted):
                                raise ResumeExhaustedError(
                                    "a stop token was already emitted")
                    except ServingError as e:
                        return self._json(e.http_status, {"error": str(e)},
                                          headers=e.headers())
                    if resume is not None:
                        # re-prefill prompt + emitted (chunked prefill and
                        # the prefix cache apply unchanged) and continue
                        # the ORIGINAL (seed, step) sample stream at step
                        # k; resume.seed pins the effective seed — the
                        # OpenAI surface derives a random one when the
                        # request omits it, and a continuation must not
                        samp["seed"] = rseed
                        resume_step = len(emitted)
                        tokens = np.concatenate(
                            [tokens, np.asarray([emitted], np.int32)],
                            axis=1)
                        n -= resume_step
                    if bool(req.get("stream", False)):
                        if stop_ids and tokens.shape[0] > 1:
                            # per-row early stop breaks the [B, k]-aligned
                            # stream contract; refuse rather than silently
                            # return untrimmed rows
                            return self._json(400, {
                                "error": "stop_token_ids with stream is "
                                "single-row only"
                            })
                        return self._stream_generate(
                            server, tokens, n, samp, stop_ids,
                            timeout_s=timeout_s, priority=priority,
                            resume_step=resume_step,
                            include_timing=bool(
                                req.get("include_timing", False)))
                    engine = sset.engine_for(
                        server, tokens.shape[0], samp["temperature"]
                    )
                    if engine is sset.cbatchers.get(server.name):
                        # the continuous engine honors stops server-side:
                        # every row's slot frees at its stop token (short
                        # rows come back padded with the stop; the trim
                        # below cuts at the FIRST stop either way) — and
                        # the propagated deadline remainder clamps the
                        # per-request expiry
                        out = engine.generate(tokens, max_new_tokens=n,
                                              stop_token_ids=stop_ids,
                                              timeout_s=timeout_s,
                                              priority=priority,
                                              timing=self._timing, **samp)
                    else:
                        out = engine.generate(tokens, max_new_tokens=n, **samp)
                    rows = out.tolist()
                    if stop_ids:
                        # trim each row's GENERATED portion at the first stop
                        # token (inclusive) — response rows may be ragged
                        from modelx_tpu.models.decode import stop_cut

                        stops = set(stop_ids)
                        plen = tokens.shape[1]
                        trimmed = []
                        for row in rows:
                            gen_part = row[plen:]
                            cut = stop_cut(gen_part, stops)
                            if cut is not None:
                                gen_part = gen_part[:cut]
                            trimmed.append(row[:plen] + gen_part)
                        rows = trimmed
                    resp = {"tokens": rows}
                    if tok is not None:  # text request: decode the new tokens
                        resp["text"] = tok.decode(rows[0][tokens.shape[1]:])
                    self._json(200, resp)
            except ValueError as e:  # e.g. generate on a non-generative family
                self._json(400, {"error": str(e)})
            except ServingError as e:
                # typed serving failures carry their canonical status:
                # 429 (queue full, + Retry-After), 504 (deadline),
                # 503 (engine broken/restarting), 400 (quarantined)
                self._json(e.http_status, {"error": str(e)}, headers=e.headers())
            except Exception as e:  # surface inference errors as 500 JSON
                logger.exception("inference error")
                self._json(500, {"error": str(e)})
            finally:
                if sset.pool is not None:
                    sset.pool.exit(server.name)

        def do_DELETE(self):
            """DELETE /admin/models/{name}: drain in-flight requests, stop
            admission (new requests 409 while draining, 404 once gone),
            then free params, KV/page pools, compiled programs, and
            pool-owned staging. ``?wait=0`` returns 202 immediately and
            drains in the background."""
            m = _ADMIN_MODEL_ROUTE.match(self.path)
            if m is None:
                return self._json(404, {"error": "not found"})
            if not self._admin_auth():
                return
            from urllib.parse import parse_qs, urlparse

            from modelx_tpu.dl.lifecycle import PoolError

            if not sset.pool.allow_admin_load:
                return self._json(403, {
                    "error": "admin model unloading is disabled "
                             "(start with --allow-admin-load)"
                })
            q = parse_qs(urlparse(self.path).query)
            wait = q.get("wait", ["1"])[0] not in ("0", "false")
            try:
                snap = sset.pool.request_unload(m.group("model"), wait=wait)
            except PoolError as e:
                return self._json(e.status, {"error": str(e)},
                                  headers=e.headers or None)
            return self._json(200 if wait else 202, snap)

    host, _, port = listen.rpartition(":")
    httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), Handler)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd
