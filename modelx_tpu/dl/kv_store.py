"""Content-addressed prefix-KV store: ship hot prefix caches with the model.

The fleet already fingerprints prompt prefixes at the router (sticky
routing) and caches prefix KV per pod (models/decode.py PrefixKVCache) —
but that cache dies with the pod, and a popular shared system prompt gets
re-prefilled once per replica. This module generalizes dl/program_store.py
to a SECOND derived-artifact kind: a hot PrefixKVCache entry serializes
into one deterministic tar (``meta.json`` first, then raw little-endian
leaf buffers) attached to the model version as a real manifest descriptor
under ``application/vnd.modelx.kvcache.v1`` — so sha256 verification,
scrub/quarantine, upload markers and GC referenced-digest tracking apply
to serving state with zero new registry code.

Keying: a bundle is named ``.kv-<env_key>-<prefix_hash>.tar`` where
``env_key`` is program_store's environment digest (jax version, backend,
package-source digest, GSPMD mesh shape — KV layouts never cross-install
between topologies or code versions) and ``prefix_hash`` is
sha256(model content key x env_key x the exact tokenized prompt head).
Same prefix, same weights, same world => same name => republish is a
registry no-op; anything else coexists.

Flow: pods count per-key hits and publish entries crossing a threshold
through the PR 19 outbox (kind ``"kvcache"``; durable across registry
brownouts); pulls drop ``.kv-*.tar`` next to the weights and the server
installs them at load; a prefix-cache MISS can fetch through to the
registry on demand (KVFetcher), bounded by the existing
``--prefix-cache-max-bytes``. Installed leaves ``device_put`` to their
recorded shardings the way tier promotion does (dl/tiers.py). Because KV
is a deterministic function of the token prefix, a decode resumed from
installed KV is byte-identical to a locally-prefilled one — greedy and
sampled alike; tests/test_kv_store.py holds that contract.

Trust boundary (mirrors program_store): member names must match
``leaf-NNNNN.bin``, every member is re-hashed against the bundle's own
meta.json, leaf shapes/dtypes must match what the model family's
``init_kv_cache`` says a cache of that length looks like, and installs
never overwrite local entries. The store is an optimization, never
load-bearing: any miss, skew, truncation or corruption is logged,
counted, and skipped — the caller simply prefills cold.
"""

from __future__ import annotations

import glob
import hashlib
import io
import json
import logging
import os
import re
import tarfile
import threading

from modelx_tpu.dl import program_store as _ps
from modelx_tpu.types import (
    AnnotationKVCode,
    AnnotationKVMesh,
    AnnotationKVModel,
    AnnotationKVPrefix,
    AnnotationKVTokens,
    Descriptor,
    Digest,
    Manifest,
    MediaTypeModelKVCache,
)

logger = logging.getLogger("modelx.kv")

BUNDLE_FORMAT = 1
META_MEMBER = "meta.json"
OUTBOX_KIND = "kvcache"
# the only member shape a kv bundle may carry: a raw leaf buffer. Paths,
# traversal, stray files are rejected at install.
_LEAF_RE = re.compile(r"^leaf-\d{5}\.bin$")

# program_store owns the environment fingerprint (PR 16): same quadruple,
# same digest — a KV layout's compatibility domain IS the compiled
# surface's
env_key = _ps.env_key


def _env_key_of(jx: str, backend: str, code: str, mesh_s: str) -> str:
    """env_key recomputed from a bundle's OWN stamped quadruple (publish
    may run in another process/epoch than the capture — never re-derive
    the name from the local environment)."""
    h = hashlib.sha256(f"{jx}\x00{backend}\x00{code}\x00{mesh_s}".encode())
    return h.hexdigest()[:12]


def prefix_hash(model_key: str, envk: str, ids) -> str:
    """Digest naming one cached prefix within one (weights, environment)
    world: the exact token ids are the content, the model key scopes
    equal prompts across different weights, the env key scopes equal
    prompts across meshes/code versions."""
    payload = json.dumps([int(t) for t in ids], separators=(",", ":"))
    h = hashlib.sha256(f"{model_key}\x00{envk}\x00{payload}".encode())
    return h.hexdigest()[:16]


def bundle_name(envk: str, phash: str) -> str:
    """Dotfile on purpose (same reason as program_store.bundle_name): a
    model dir holding pulled kv bundles re-pushes cleanly."""
    return f".kv-{envk}-{phash}.tar"


def model_key_for_ref(ref: str) -> str:
    """Content key of the weights a registry ref names — manifest-digest
    salted (dl/tiers.ref_pairs), so every pod serving the same version
    derives the SAME key (a dir mtime salt would not survive a re-pull).
    Empty string when the manifest is unreachable: publishing retries
    later, installing skips the check (descriptors are already scoped to
    the model version)."""
    from modelx_tpu.dl import tiers as tiers_mod

    try:
        return tiers_mod.content_key(tiers_mod.ref_pairs(ref))
    except Exception as e:
        logger.warning("kv model key for %s unavailable: %s", ref, e)
        return ""


# --- bundle build -------------------------------------------------------------


def _spec_of(leaf):
    """JSON-able PartitionSpec of a leaf's NamedSharding (None for
    single-device / fully replicated layouts): each axis entry is null, a
    mesh-axis name, or a list of names — exactly what PartitionSpec(*...)
    rebuilds on install."""
    import jax

    sharding = getattr(leaf, "sharding", None)
    if not isinstance(sharding, jax.sharding.NamedSharding):
        return None
    out = []
    for entry in tuple(sharding.spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append([str(a) for a in entry])
    return out


def build_bundle(ids, entry, model_key: str = "", mesh=None) -> bytes | None:
    """Pack one PrefixKVCache entry into a deterministic tar (sorted
    members, zeroed mtimes/owners): same tokens + same KV bytes => same
    content address. Leaves serialize in pytree order as raw buffers with
    dtype/shape/sharding recorded in meta.json — bf16 and friends ride as
    bytes, the install side resolves the dtype via ml_dtypes. Returns
    None when the entry has nothing to ship or a leaf refuses to
    materialize (device OOM on the transfer — never let publishing break
    serving)."""
    import jax
    import numpy as np

    ids = [int(t) for t in ids]
    if not ids:
        return None
    leaves = jax.tree_util.tree_leaves(entry)
    if not leaves:
        return None
    jx, backend, code, mesh_s = _ps._env(mesh)
    envk = _env_key_of(jx, backend, code, mesh_s)
    members = []
    recorded = []
    for i, leaf in enumerate(leaves):
        name = f"leaf-{i:05d}.bin"
        try:
            host = np.asarray(jax.device_get(leaf))
            data = host.tobytes()
        except Exception as e:
            logger.warning("kv bundle: leaf %d refused to materialize: %s", i, e)
            return None
        recorded.append({
            "name": name,
            "dtype": str(host.dtype),
            "shape": [int(d) for d in host.shape],
            "spec": _spec_of(leaf),
            "sha256": hashlib.sha256(data).hexdigest(),
            "size": len(data),
        })
        members.append((name, data))
    try:
        stored_len = int(recorded[0]["shape"][1])
    except IndexError:
        logger.warning("kv bundle: leaf 0 has no sequence axis; not bundling")
        return None
    meta = {
        "formatVersion": BUNDLE_FORMAT,
        "jax": jx,
        "backend": backend,
        "codeVersion": code,
        "mesh": mesh_s,
        "modelKey": model_key,
        "prefixHash": prefix_hash(model_key, envk, ids),
        "tokens": ids,
        "storedLen": stored_len,
        "leaves": recorded,
    }
    meta_bytes = json.dumps(meta, sort_keys=True, separators=(",", ":")).encode()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w", format=tarfile.USTAR_FORMAT) as tar:
        for name, data in [(META_MEMBER, meta_bytes)] + members:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            info.mode = 0o644
            tar.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def _bundle_meta(data: bytes) -> dict:
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:") as tar:
        meta = json.loads(tar.extractfile(tar.getmember(META_MEMBER)).read())
    if not isinstance(meta, dict) or not isinstance(meta.get("leaves"), list):
        raise ValueError("kv bundle meta.json is not a kv bundle manifest")
    return meta


# --- bundle install -----------------------------------------------------------


def install_bundle(data: bytes, init_kv_cache, cache, mesh=None,
                   model_key: str = "") -> dict:
    """Install one bundle into a live PrefixKVCache.

    Never raises: every failure mode — undecodable tar, missing/invalid
    meta, environment/mesh/model skew, tampered or truncated leaf, a
    leaf layout the model family's ``init_kv_cache`` disowns, an entry
    that alone busts the cache's byte cap — is logged, counted, and
    skipped; the caller simply prefills cold. Existing cache entries are
    never overwritten (a pod's own prefill is at least as fresh), and
    installed entries land with ``origin="installed"`` so they are
    never re-published and their hits are separately countable."""
    import jax
    import numpy as np

    from modelx_tpu.dl.tiers import _np_dtype

    stats = {"installed": 0, "present": 0, "skipped": 0, "reasons": []}

    def _skip(reason: str, n: int = 1) -> dict:
        stats["skipped"] += n
        stats["reasons"].append(reason)
        logger.warning("kv install: %s", reason)
        return stats

    try:
        tar = tarfile.open(fileobj=io.BytesIO(data), mode="r:")
    except (tarfile.TarError, ValueError, EOFError) as e:
        return _skip(f"unreadable bundle: {e}")
    with tar:
        try:
            meta = json.loads(tar.extractfile(tar.getmember(META_MEMBER)).read())
        except (KeyError, tarfile.TarError, ValueError, AttributeError, OSError) as e:
            return _skip(f"bundle meta unreadable: {e}")
        if not isinstance(meta, dict) or meta.get("formatVersion") != BUNDLE_FORMAT:
            return _skip(f"unsupported bundle format {meta.get('formatVersion')!r}"
                         if isinstance(meta, dict) else "bundle meta is not an object")
        jx, backend, code, mesh_s = _ps._env(mesh)
        got = (meta.get("jax"), meta.get("backend"), meta.get("codeVersion"))
        if got != (jx, backend, code):
            # KV layout (dtype promotion, cache geometry) follows the
            # code that produced it: another world's bytes never land
            return _skip(
                "version skew: bundle built for jax=%s backend=%s code=%s, "
                "local jax=%s backend=%s code=%s" % (*got, jx, backend, code))
        if meta.get("mesh") != mesh_s:
            # unlike programs there is no pre-mesh generation to grandfather:
            # the mesh stamp is load-bearing from bundle format 1
            return _skip(f"mesh skew: bundle built for mesh={meta.get('mesh')!r}, "
                         f"local mesh={mesh_s}")
        got_model = meta.get("modelKey") or ""
        if model_key and got_model and got_model != model_key:
            return _skip(f"model skew: bundle keyed {got_model}, local {model_key}")
        ids = meta.get("tokens")
        if (not isinstance(ids, list) or not ids
                or not all(isinstance(t, int) for t in ids)):
            return _skip("bundle tokens missing or malformed")
        recorded = meta.get("leaves")
        if not isinstance(recorded, list) or not recorded:
            return _skip("bundle has no leaves")
        if cache.entry_origin(ids) is not None:
            stats["present"] += 1
            return stats
        stored_len = meta.get("storedLen")
        if not isinstance(stored_len, int) or stored_len < 1:
            return _skip(f"bundle storedLen {stored_len!r} invalid")
        # the model family is the shape oracle: a cache of this length has
        # exactly these leaves, in this order, with these shapes/dtypes.
        # eval_shape costs no device memory; batch/length close over the
        # call because init fns use them as static python shapes
        try:
            want = jax.eval_shape(lambda: init_kv_cache(1, stored_len))
        except Exception as e:
            return _skip(f"init_kv_cache refused length {stored_len}: {e}")
        want_leaves, treedef = jax.tree_util.tree_flatten(want)
        if len(want_leaves) != len(recorded):
            return _skip(f"bundle has {len(recorded)} leaves, model wants "
                         f"{len(want_leaves)}")
        total = sum(int(a.get("size", 0)) for a in recorded
                    if isinstance(a, dict))
        if cache.max_bytes and total > cache.max_bytes:
            return _skip(f"entry ({total} bytes) exceeds prefix-cache byte cap "
                         f"({cache.max_bytes})")
        host_leaves = []
        for art, want_leaf in zip(recorded, want_leaves):
            name = art.get("name", "") if isinstance(art, dict) else ""
            if not _LEAF_RE.match(name):
                return _skip(f"leaf name {name!r} rejected")
            try:
                blob = tar.extractfile(tar.getmember(name)).read()
            except (KeyError, tarfile.TarError, AttributeError, OSError) as e:
                return _skip(f"leaf {name} unreadable: {e}")
            if (len(blob) != art.get("size")
                    or hashlib.sha256(blob).hexdigest() != art.get("sha256")):
                return _skip(f"leaf {name} fails hash/size check; not installing")
            try:
                dtype = _np_dtype(str(art.get("dtype")))
                shape = tuple(int(d) for d in art.get("shape") or ())
                arr = np.frombuffer(blob, dtype=dtype).reshape(shape)
            except (TypeError, ValueError, AttributeError) as e:
                return _skip(f"leaf {name} undecodable: {e}")
            if shape != tuple(want_leaf.shape) or dtype != want_leaf.dtype:
                return _skip(f"leaf {name} shape/dtype {shape}/{dtype} does not "
                             f"match model cache layout "
                             f"{tuple(want_leaf.shape)}/{want_leaf.dtype}")
            host_leaves.append((arr, art.get("spec")))
        try:
            placed = [_place(arr, spec, mesh) for arr, spec in host_leaves]
            entry = jax.tree_util.tree_unflatten(treedef, placed)
        except Exception as e:
            return _skip(f"device placement failed: {e}")
        cache.put(ids, entry, origin="installed")
        stats["installed"] += 1
        logger.info("kv install: %d-token prefix installed (%d leaves, %d bytes)",
                    len(ids), len(recorded), total)
    return stats


def _place(arr, spec, mesh):
    """device_put a host leaf to its recorded sharding — the tier
    promotion discipline (dl/tiers.py): the layout the capture ran under
    is the layout decode expects."""
    import jax

    if spec is not None and mesh is not None and not isinstance(mesh, str):
        parts = [tuple(e) if isinstance(e, list) else e for e in spec]
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(*parts))
        return jax.device_put(arr, sharding)
    return jax.device_put(arr)


def install_from_dir(model_dir: str, init_kv_cache, cache, mesh=None,
                     model_key: str = "") -> dict:
    """Install every pulled kv bundle found in a model dir (the
    lifecycle/boot path: pull_model drops ``.kv-*.tar`` next to the
    weights). Aggregated stats; never raises."""
    total = {"bundles": 0, "installed": 0, "present": 0, "skipped": 0,
             "reasons": []}
    for path in sorted(glob.glob(os.path.join(model_dir, ".kv-*.tar"))):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            total["reasons"].append(f"{os.path.basename(path)}: {e}")
            logger.warning("kv install: cannot read %s: %s", path, e)
            continue
        total["bundles"] += 1
        stats = install_bundle(data, init_kv_cache, cache, mesh=mesh,
                               model_key=model_key)
        for k in ("installed", "present", "skipped"):
            total[k] += stats[k]
        total["reasons"].extend(stats["reasons"])
    return total


# --- registry plumbing --------------------------------------------------------


def kv_descriptors(manifest: Manifest) -> list[Descriptor]:
    return [b for b in manifest.blobs if b.media_type == MediaTypeModelKVCache]


def publish(remote, repository: str, version: str, data: bytes) -> Descriptor:
    """Attach a kv bundle to an existing model version as a real
    descriptor — blob first (content-addressed HEAD dedup), then the
    manifest re-PUT with the descriptor merged in by name: a republished
    identical prefix replaces itself, different prefixes/environments
    coexist. Same commit-delta retry discipline as program_store."""
    from modelx_tpu import errors
    from modelx_tpu.client.push import commit_delta_digests

    meta = _bundle_meta(data)
    envk = _env_key_of(str(meta.get("jax")), str(meta.get("backend")),
                       str(meta.get("codeVersion")), str(meta.get("mesh")))
    name = bundle_name(envk, str(meta.get("prefixHash")))
    desc = Descriptor(
        name=name,
        media_type=MediaTypeModelKVCache,
        digest=Digest.from_bytes(data),
        size=len(data),
        annotations={
            AnnotationKVCode: str(meta.get("codeVersion")),
            AnnotationKVMesh: str(meta.get("mesh")),
            AnnotationKVModel: str(meta.get("modelKey") or ""),
            AnnotationKVTokens: str(len(meta.get("tokens") or ())),
            AnnotationKVPrefix: str(meta.get("prefixHash")),
        },
    )
    if not remote.head_blob(repository, desc.digest):
        remote.upload_blob_content(repository, desc, data)
    manifest = remote.get_manifest(repository, version)
    manifest.blobs = [b for b in manifest.blobs if b.name != name] + [desc]
    try:
        remote.put_manifest(repository, version, manifest)
    except errors.ErrorInfo as e:
        if str(desc.digest) not in commit_delta_digests(e):
            raise
        remote.upload_blob_content(repository, desc, data)
        remote.put_manifest(repository, version, manifest)
    return desc


def publish_bundle(ref: str, data: bytes) -> Descriptor:
    """The NETWORK half of a kv publish — what the outbox drainer replays
    for kind ``"kvcache"`` after a registry outage. The bundle carries
    its own stamped environment and prefix hash, so publishing later (or
    from another process) is identical to publishing now."""
    from modelx_tpu.client.reference import parse_reference

    parsed = parse_reference(ref)
    if not parsed.version:
        raise ValueError(f"kv publish needs an exact version, got {ref!r}")
    client = parsed.client(quiet=True)
    desc = publish(client.remote, parsed.repository, parsed.version, data)
    logger.info("published prefix KV for %s (%s, %d bytes)",
                ref, desc.name, desc.size)
    return desc


def pull_and_install(client, repository: str, manifest: Manifest,
                     init_kv_cache, cache, blob_cache=None, mesh=None,
                     model_key: str = "") -> dict:
    """Fetch the manifest's kv bundles (blob cache first) and install
    them into a live PrefixKVCache. Annotation-level skew (code / mesh)
    skips without moving blob bytes; corrupt bytes are discarded.
    Never raises."""
    total = {"bundles": 0, "installed": 0, "present": 0, "skipped": 0,
             "reasons": []}
    env = _ps._env(mesh)
    for desc in kv_descriptors(manifest):
        code = desc.annotations.get(AnnotationKVCode)
        if code is not None and code != env[2]:
            total["skipped"] += 1
            total["reasons"].append(f"{desc.name}: version skew (annotation)")
            continue
        bundle_mesh = desc.annotations.get(AnnotationKVMesh)
        if bundle_mesh is not None and bundle_mesh != env[3]:
            total["skipped"] += 1
            total["reasons"].append(f"{desc.name}: mesh skew (annotation)")
            continue
        try:
            data = _ps._read_blob(client, repository, desc, cache=blob_cache)
        except Exception as e:
            total["reasons"].append(f"{desc.name}: {e}")
            logger.warning("kv pull: %s unavailable: %s", desc.name, e)
            continue
        if data is None:
            total["reasons"].append(f"{desc.name}: digest mismatch")
            continue
        total["bundles"] += 1
        stats = install_bundle(data, init_kv_cache, cache, mesh=mesh,
                               model_key=model_key)
        for k in ("installed", "present", "skipped"):
            total[k] += stats[k]
        total["reasons"].extend(stats["reasons"])
    return total


# --- publisher (threshold -> outbox) ------------------------------------------


class KVPublisher:
    """Periodic sweep of live prefix caches for entries hot enough to
    ship. ``targets()`` yields ``(ref, server)`` pairs for ref-loaded
    READY models; each swept entry builds a bundle and hands the bytes to
    ``sink(ref, data)`` — the lifecycle wires that to the PR 19 outbox
    (kind ``"kvcache"``), so durability, backoff and brownout recovery
    are the drainer's problem, not this thread's. ``flush()`` runs one
    sweep synchronously (the drain path's last call before an unload
    frees the cache)."""

    def __init__(self, targets, sink, threshold: int = 2,
                 interval_s: float = 5.0, sleeper=None) -> None:
        self.targets = targets  # () -> iterable of (ref, server)
        self.sink = sink        # (ref, data) -> None, may raise
        self.threshold = max(1, int(threshold))
        self.interval_s = float(interval_s)
        self._sleeper = sleeper or threading.Event.wait
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._keys: dict[str, str] = {}  # ref -> memoized model key
        self._lock = threading.Lock()
        self.stats = {"published_total": 0, "build_failures_total": 0,
                      "sink_failures_total": 0}

    def _model_key(self, ref: str) -> str:
        key = self._keys.get(ref)
        if not key:
            key = model_key_for_ref(ref)
            if key:
                self._keys[ref] = key
        return key

    def flush(self) -> int:
        """One synchronous sweep; returns how many bundles left here."""
        shipped = 0
        for ref, server in list(self.targets()):
            cache = getattr(server, "_prefix_cache", None)
            mesh = getattr(server, "mesh", None)
            if cache is None or not ref:
                continue
            for ids, entry in cache.take_publishable(self.threshold):
                data = build_bundle(ids, entry, model_key=self._model_key(ref),
                                    mesh=mesh)
                if data is None:
                    with self._lock:
                        self.stats["build_failures_total"] += 1
                    continue
                try:
                    self.sink(ref, data)
                except Exception as e:
                    with self._lock:
                        self.stats["sink_failures_total"] += 1
                    logger.warning("kv publish sink for %s failed: %s", ref, e)
                    continue
                shipped += 1
                with self._lock:
                    self.stats["published_total"] += 1
        return shipped

    def kick(self) -> None:
        self._wake.set()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kv-publisher")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.flush()
            except Exception:
                # the sweep must never die quietly mid-flight: log and
                # keep the cadence — next interval retakes nothing (keys
                # were marked published) but new heat still ships
                logger.exception("kv publisher sweep failed")
            self._wake.clear()
            self._sleeper(self._wake, self.interval_s)

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
        out["running"] = self._thread is not None
        out["threshold"] = self.threshold
        return out


# --- fetch-through (miss -> registry) -----------------------------------------


class KVFetcher:
    """On-demand install of published prefix KV at prefix-cache miss.

    ``PrefixKVCache.lookup`` calls ``on_miss(ids)`` (outside its lock):
    the miss enqueues into a small dedup ring and a worker matches it
    against the model version's kv descriptors — annotation-only until a
    hash matches, so a miss costs one cached manifest read and a few
    sha256s, not blob bytes. A matched bundle pulls digest-verified
    through the blob cache and installs under the normal trust boundary;
    the NEXT lookup of that prompt hits. Tried digests are negatively
    cached so a mismatched or corrupt bundle is not refetched per miss.
    Bounded by the prefix cache's own byte cap — fetch-through can never
    admit more than ``--prefix-cache-max-bytes``."""

    MAX_QUEUE = 16
    MANIFEST_TTL_S = 5.0

    def __init__(self, ref: str, init_kv_cache, cache, mesh=None,
                 model_key: str = "", blob_cache=None, sleeper=None) -> None:
        self.ref = ref
        self.init_kv_cache = init_kv_cache
        self.cache = cache
        self.mesh = mesh
        self.model_key = model_key
        self.blob_cache = blob_cache
        self._sleeper = sleeper or threading.Event.wait
        self._lock = threading.Lock()
        self._pending: list[tuple] = []
        self._tried: set[str] = set()   # descriptor digests already pulled
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._manifest = None
        self._manifest_at = 0.0
        self.stats = {"misses_seen_total": 0, "fetched_total": 0,
                      "installed_total": 0, "errors_total": 0}

    def on_miss(self, ids) -> None:
        """O(1) bounded dedup enqueue — PrefixKVCache calls this on its
        miss path, so it must never block or raise."""
        key = tuple(int(t) for t in ids)
        with self._lock:
            self.stats["misses_seen_total"] += 1
            if key in self._pending or len(self._pending) >= self.MAX_QUEUE:
                return
            self._pending.append(key)
        self._wake.set()

    def _get_manifest(self, client, repository: str, version: str):
        import time

        now = time.monotonic()
        if self._manifest is not None and now - self._manifest_at < self.MANIFEST_TTL_S:
            return self._manifest
        self._manifest = client.get_manifest(repository, version)
        self._manifest_at = now
        return self._manifest

    def drain_once(self) -> bool:
        """Process one queued miss; True when one was consumed. Public so
        tests drive the fetch deterministically without the thread."""
        with self._lock:
            if not self._pending:
                return False
            ids = self._pending.pop(0)
        try:
            self._fetch_for(ids)
        except Exception as e:
            with self._lock:
                self.stats["errors_total"] += 1
            logger.warning("kv fetch-through for %s failed: %s", self.ref, e)
        return True

    def _fetch_for(self, ids: tuple) -> None:
        from modelx_tpu.client.reference import parse_reference

        parsed = parse_reference(self.ref)
        if not parsed.version:
            return
        client = parsed.client(quiet=True)
        manifest = self._get_manifest(client, parsed.repository, parsed.version)
        env = _ps._env(self.mesh)
        envk = _env_key_of(*env)
        for desc in kv_descriptors(manifest):
            code = desc.annotations.get(AnnotationKVCode)
            if code is not None and code != env[2]:
                continue
            bundle_mesh = desc.annotations.get(AnnotationKVMesh)
            if bundle_mesh is not None and bundle_mesh != env[3]:
                continue
            try:
                length = int(desc.annotations.get(AnnotationKVTokens, "0"))
            except ValueError:
                continue
            # strict prefix: the suffix prefill needs >= 1 real token
            if length < 1 or length >= len(ids):
                continue
            want = desc.annotations.get(AnnotationKVPrefix, "")
            got = prefix_hash(desc.annotations.get(AnnotationKVModel, ""),
                              envk, ids[:length])
            if not want or want != got:
                continue
            digest = str(desc.digest)
            with self._lock:
                if digest in self._tried:
                    continue
                self._tried.add(digest)
            data = _ps._read_blob(client, parsed.repository, desc,
                                  cache=self.blob_cache)
            if data is None:
                continue
            with self._lock:
                self.stats["fetched_total"] += 1
            stats = install_bundle(data, self.init_kv_cache, self.cache,
                                   mesh=self.mesh, model_key=self.model_key)
            with self._lock:
                self.stats["installed_total"] += stats["installed"]

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kv-fetcher")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.drain_once():
                continue
            self._wake.clear()
            self._sleeper(self._wake, None)

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self.stats)
            out["pending"] = len(self._pending)
        out["running"] = self._thread is not None
        return out


# --- server glue --------------------------------------------------------------


def install_for_server(server, model_dir: str, model_key: str = "") -> dict | None:
    """Install every pulled kv bundle in ``model_dir`` into a freshly
    loaded server's prefix cache — the tail of ModelServer.load(), after
    the family is known (``init_kv_cache`` is the family's). Never
    raises; None when the server has no prefix cache or no decode fns."""
    cache = getattr(server, "_prefix_cache", None)
    if cache is None or server.family is None:
        return None
    try:
        _fwd, init = server.family.decode_fns(server.cfg, mesh=server.mesh)
    except Exception as e:
        logger.warning("kv install: decode fns unavailable: %s", e)
        return None
    return install_from_dir(model_dir, init, cache, mesh=server.mesh,
                            model_key=model_key)


def fetcher_for_server(ref: str, server, blob_cache=None,
                       model_key: str = "") -> KVFetcher | None:
    """Build (and attach) a fetch-through worker for a ref-loaded
    server: subsequent prefix-cache misses consult the registry. Returns
    the started fetcher (the lifecycle stops it at unload), or None."""
    cache = getattr(server, "_prefix_cache", None)
    if cache is None or server.family is None or not ref:
        return None
    try:
        _fwd, init = server.family.decode_fns(server.cfg, mesh=server.mesh)
    except Exception as e:
        logger.warning("kv fetcher: decode fns unavailable: %s", e)
        return None
    fetcher = KVFetcher(ref, init, cache, mesh=server.mesh,
                        model_key=model_key, blob_cache=blob_cache)
    cache.fetcher = fetcher
    fetcher.start()
    return fetcher
