"""Typed serving-path errors with one HTTP mapping.

The engine, the native /v1 handlers, and the OpenAI veneer all need to
agree on what an overloaded queue, an expired deadline, or a dead engine
looks like on the wire. Before this module, raw ``BaseException`` objects
flowed through ``row.out.put(err)`` and surfaced differently between the
streaming and non-streaming paths; now every failure class is one typed
exception carrying its canonical status:

- ``QueueFullError``  -> 429 + ``Retry-After`` (bounded admission shed)
- ``DeadlineExceededError`` -> 504 (request expired before/while decoding)
- ``PoisonedRequestError``  -> 400 (quarantined: this request crashed the
  engine loop repeatedly; re-admitting it would crash-loop the server)
- ``EngineBrokenError``     -> 503 (the engine died mid-flight; the
  supervisor may be restarting it — retryable, unlike a 500)
- ``ModelLoadingError``     -> 503 + ``Retry-After`` (the model is
  PULLING/LOADING in the lifecycle pool; a later retry will hit it READY)
- ``ModelDrainingError``    -> 409 (the model is being unloaded; new
  admissions are refused while in-flight requests finish)
- ``ModelFailedError``      -> 503 (the model's load crashed; the slot is
  retryable via the admin API, and the reason rides in the message)

The fleet router (modelx_tpu/router/) speaks the SAME family — a client
cannot tell one pod from a fleet by error shape — plus two router-only
classes:

- ``NoReadyPodError``       -> 503 + ``Retry-After`` (no READY pod serves
  the model right now: every candidate is loading, draining, quarantined,
  or shedding; the fleet may recover on its own, so back off and retry)
- ``UpstreamSeveredError``  -> 502 (a pod died MID-STREAM after bytes were
  already relayed and CONTINUATION was exhausted; the router surfaces
  this typed payload in-stream — never a silently truncated 200 — and
  quarantines the pod)

Live request continuation (ISSUE 12) adds a resume block to the wire
contract — a native ``resume`` field and the ``X-ModelX-Resume-*``
headers, parsed by ONE function here so the router and pod halves cannot
drift — plus two typed refusals:

- ``MalformedResumeError``  -> 400 (the resume block cannot be honored as
  stated; the router falls back to the typed severed error rather than
  silently restarting a stream the client already holds half of)
- ``ResumeExhaustedError``  -> 422 (the resume frontier is at or past the
  request's end — every budgeted token, or a stop token, was already
  emitted; the router COMPLETES the client stream instead of erroring)

Kept dependency-free (no jax, no requests) so the transport layer can
import it at module top without cost.
"""

from __future__ import annotations

# The overload-protection wire contract (ISSUE 9), shared by the router
# (which stamps these on every upstream attempt) and the pods (which
# honor them): the request's REMAINING deadline budget in milliseconds,
# its priority class, and an explicit fairness identity. Defined here —
# the one dependency-free module both sides already import — so the
# router and pod halves of the contract cannot drift apart.
DEADLINE_HEADER = "X-ModelX-Deadline-Ms"
PRIORITY_HEADER = "X-ModelX-Priority"
CLIENT_HEADER = "X-ModelX-Client"

# Live request continuation (ISSUE 12): a re-issued request carries the
# tokens the CLIENT already received and the original sample-stream seed,
# so the receiving pod re-prefills prompt + emitted, pins the seed, and
# continues the (seed, step) stream at step k = len(emitted) — emitting
# byte-identical tokens from k+1 on. Self-contained: the pod derives the
# resume point entirely from this block plus the original request body.
RESUME_EMITTED_HEADER = "X-ModelX-Resume-Emitted"
RESUME_SEED_HEADER = "X-ModelX-Resume-Seed"

# End-to-end request identity (ISSUE 13): the router mints ONE id per
# client request (honoring a client-supplied one) and stamps it on every
# upstream attempt; pods echo it on the response and thread it through
# spans, access-log lines, and the engine ticket. A failover or stream
# continuation re-uses the SAME id with the attempt counter bumped, so
# one grep joins the whole request across processes. Timing headers share
# the prefix: ``X-ModelX-Timing-Queue-Ms`` etc. on non-streaming replies.
REQUEST_ID_HEADER = "X-ModelX-Request-Id"
ATTEMPT_HEADER = "X-ModelX-Attempt"
TIMING_HEADER_PREFIX = "X-ModelX-Timing-"

PRIORITY_INTERACTIVE = "interactive"
PRIORITY_BATCH = "batch"

# the id alphabet is CLOSED (it rides in headers and JSON log lines, so
# a hostile client-supplied id must not inject header/log structure)
_REQUEST_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.:")
_REQUEST_ID_MAX = 128


def mint_request_id() -> str:
    """A fresh request id: 16 hex chars of OS entropy under a fixed
    prefix. Minted by the FIRST hop that sees the request without one
    (normally the router; a direct-to-pod request gets one from the pod)."""
    import secrets

    return "req-" + secrets.token_hex(8)


def parse_request_id(value) -> str | None:
    """Client-supplied ``X-ModelX-Request-Id`` -> the id to honor, or
    None when absent/unusable (the receiver mints instead). Validation is
    strict — a closed alphabet and a length cap — because the id is
    reflected verbatim into response headers and access logs."""
    if not value:
        return None
    rid = str(value).strip()
    if not rid or len(rid) > _REQUEST_ID_MAX:
        return None
    if not all(c in _REQUEST_ID_CHARS for c in rid):
        return None
    return rid


def parse_attempt(value) -> int:
    """``X-ModelX-Attempt`` header value -> attempt ordinal (>= 1);
    absent/malformed reads as attempt 1 — the first try."""
    try:
        return max(1, int(str(value).strip()))
    except (TypeError, ValueError):
        return 1


def client_identity(headers, client_address) -> str:
    """The hashed client identity of a request: API token, else the
    explicit ``X-ModelX-Client`` header, else source IP — first
    available. Tokens are hashed before they become a metrics or
    access-log key: neither surface may leak a bearer credential. ONE
    function for the router's fairness queues and both access logs, so
    the same caller aggregates under the same key fleet-wide."""
    import hashlib

    auth = str(headers.get("Authorization", "") or "")
    if auth.startswith("Bearer ") and auth[len("Bearer "):].strip():
        digest = hashlib.sha256(
            auth[len("Bearer "):].strip().encode()).hexdigest()
        return "tok:" + digest[:12]
    explicit = str(headers.get(CLIENT_HEADER, "") or "").strip()
    if explicit:
        return "hdr:" + explicit[:64]
    host = client_address[0] if client_address else ""
    return "ip:" + (host or "unknown")


def timing_headers(timing: dict) -> dict[str, str]:
    """A timing breakdown dict -> ``X-ModelX-Timing-*`` response headers.
    ``{"queue_ms": 1.25}`` becomes ``X-ModelX-Timing-Queue-Ms: 1.25``;
    non-numeric values are skipped so a partially-filled breakdown never
    breaks the response."""
    out: dict[str, str] = {}
    for key, val in (timing or {}).items():
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        name = TIMING_HEADER_PREFIX + "-".join(
            p.capitalize() for p in str(key).split("_") if p)
        out[name] = f"{val:g}" if isinstance(val, float) else str(val)
    return out


def parse_priority(value) -> str:
    """Header value -> priority class; anything but an explicit "batch"
    is interactive (the default class must be the safe one)."""
    return PRIORITY_BATCH if str(value or "").strip().lower() == PRIORITY_BATCH \
        else PRIORITY_INTERACTIVE


def parse_deadline_ms(value) -> float | None:
    """``X-ModelX-Deadline-Ms`` header value -> remaining seconds
    (>= 0.0; 0.0 = the caller's budget is already gone), or None when the
    header is absent/malformed (no propagated deadline — the receiver's
    own budget stands). ONE parser for both halves of the wire contract:
    the router's clamp and the pod's honor must read the same number."""
    if not value:
        return None
    try:
        return max(0, int(float(value))) / 1000.0
    except (TypeError, ValueError, OverflowError):
        # OverflowError: "inf"/"1e400" parse as float but refuse int() —
        # malformed like the rest, never an escaped handler exception
        return None


def parse_resume(emitted_value, seed_value):
    """Resume block -> ``(emitted token ids, seed)``, or None when absent.
    ONE parser for both wire surfaces: ``emitted_value`` is either the
    header's comma-separated string or the native field's id list;
    ``seed_value`` the header string or native int. Anything the pod
    cannot honor AS STATED raises ``MalformedResumeError`` (400) — a
    resume must never be silently treated as a fresh request, because the
    caller splices the continuation into a stream the client already
    holds the first k tokens of."""
    if emitted_value is None and seed_value is None:
        return None
    if emitted_value is None or seed_value is None:
        raise MalformedResumeError(
            "resume requires both the emitted tokens and the original seed"
        )
    try:
        seed = int(str(seed_value).strip())
    except (TypeError, ValueError):
        raise MalformedResumeError(
            f"resume seed {seed_value!r} is not an integer"
        ) from None
    if not 0 <= seed < 2**31:
        raise MalformedResumeError(f"resume seed {seed} out of [0, 2^31)")
    if isinstance(emitted_value, str):
        parts = [p for p in emitted_value.split(",") if p.strip()]
    elif isinstance(emitted_value, (list, tuple)):
        parts = list(emitted_value)
    else:
        raise MalformedResumeError("resume emitted must be a token id list")
    if not parts:
        raise MalformedResumeError("resume emitted is empty: nothing to resume")
    emitted = []
    for p in parts:
        try:
            t = int(str(p).strip())
        except (TypeError, ValueError):
            raise MalformedResumeError(
                f"resume emitted token {p!r} is not an integer"
            ) from None
        if t < 0:
            raise MalformedResumeError(f"resume emitted token {t} is negative")
        emitted.append(t)
    return emitted, seed


def resume_headers(emitted, seed) -> dict[str, str]:
    """The resume block as headers — what the router stamps on a
    continuation attempt (the original body is re-sent verbatim, so the
    resume state rides out-of-band exactly like the deadline)."""
    return {
        RESUME_EMITTED_HEADER: ",".join(str(int(t)) for t in emitted),
        RESUME_SEED_HEADER: str(int(seed)),
    }


def deadline_kwargs(timeout_s: float | None, priority: str) -> dict:
    """Engine-call kwargs for a propagated deadline/priority, included
    ONLY when actually stamped — direct-pod traffic (and legacy-signature
    test doubles of ``stream_source``) keep the pre-contract call shape."""
    kw: dict = {}
    if timeout_s is not None:
        kw["timeout_s"] = timeout_s
    if priority != PRIORITY_INTERACTIVE:
        kw["priority"] = priority
    return kw


class ServingError(RuntimeError):
    """Base for typed serving failures; ``http_status`` is the canonical
    mapping every transport (native JSON + OpenAI SSE) uses."""

    http_status = 500
    api_type = "server_error"  # OpenAI error.type

    def headers(self) -> dict[str, str]:
        return {}


class QueueFullError(ServingError):
    """Admission backlog is at --max-queue-depth: shed NOW with 429 so the
    client backs off, instead of queueing into unbounded latency."""

    http_status = 429
    api_type = "rate_limit_error"

    def __init__(self, depth: int, limit: int, retry_after: float = 1.0,
                 message: str | None = None) -> None:
        # ``message`` lets a non-backlog shed (the router's per-client
        # rate ceiling) name its real cause instead of a queue that may
        # not even exist; the 429 + Retry-After contract is unchanged
        super().__init__(
            message
            or f"admission queue full ({depth} waiting, limit {limit}); retry later"
        )
        self.retry_after = max(1, int(retry_after))

    def headers(self) -> dict[str, str]:
        return {"Retry-After": str(self.retry_after)}


class DeadlineExceededError(ServingError):
    """The request sat past --request-timeout (queued, filling, or
    decoding); it was expired at a chunk boundary instead of occupying a
    slot the backlog needs."""

    http_status = 504

    def __init__(self, state: str, timeout_s: float) -> None:
        super().__init__(
            f"request deadline exceeded while {state} "
            f"(--request-timeout {timeout_s:g}s)"
        )
        self.state = state


class PoisonedRequestError(ServingError):
    """This exact request crashed the engine loop repeatedly; it is
    quarantined and rejected up front — re-admitting it would turn one bad
    request into a restart livelock."""

    http_status = 400
    api_type = "invalid_request_error"

    def __init__(self, crashes: int) -> None:
        super().__init__(
            f"request quarantined: it crashed the engine {crashes} times"
        )


class EngineBrokenError(ServingError):
    """The engine loop died while this request was in flight (or the
    circuit breaker opened). 503: the supervisor restarts the engine, so
    a retry against this pod (or another) is the right client move."""

    http_status = 503

    def __init__(self, message: str = "serving engine failed") -> None:
        super().__init__(message)


class ModelLoadingError(ServingError):
    """The requested model is mid-materialization (PULLING its blobs or
    LOADING them onto the mesh — dl/lifecycle.py). 503 + ``Retry-After``
    so load balancers and the retrying RegistryClient back off instead of
    hammering a model that will be READY shortly."""

    http_status = 503

    def __init__(self, name: str, state: str = "loading",
                 retry_after: float = 2.0) -> None:
        super().__init__(f"model {name!r} is still {state}; retry later")
        self.model = name
        self.state = state
        self.retry_after = max(1, int(retry_after))

    def headers(self) -> dict[str, str]:
        return {"Retry-After": str(self.retry_after)}


class ModelUnloadedError(ServingError):
    """The model was unloaded (or evicted): the name no longer serves.
    404, matching the routing layer's treatment of unknown names — raised
    when a request slips past the admission check just as the free
    completes, so it can never run against a freed server."""

    http_status = 404
    api_type = "not_found_error"

    def __init__(self, name: str) -> None:
        super().__init__(f"model {name!r} is not loaded")
        self.model = name


class ModelDrainingError(ServingError):
    """The requested model is DRAINING (an unload/evict is letting its
    in-flight requests finish). 409: new admissions are refused — once the
    drain completes the name 404s, so a retry loop should re-resolve."""

    http_status = 409
    api_type = "invalid_request_error"

    def __init__(self, name: str) -> None:
        super().__init__(f"model {name!r} is draining (being unloaded)")
        self.model = name


class NoReadyPodError(ServingError):
    """The fleet router found no READY pod for the model: every candidate
    is loading/draining/quarantined, or every candidate shed the request
    (429/503 propagated through the failover chain). 503 + ``Retry-After``:
    pods poll back to health and the rebalancer may be spreading the model,
    so the client should back off and retry — the same contract a single
    pod's ModelLoadingError sets."""

    http_status = 503

    def __init__(self, model: str, detail: str = "",
                 retry_after: float = 2.0) -> None:
        super().__init__(
            f"no ready pod serves model {model!r}"
            + (f" ({detail})" if detail else "") + "; retry later"
        )
        self.model = model
        self.retry_after = max(1, int(retry_after))

    def headers(self) -> dict[str, str]:
        return {"Retry-After": str(self.retry_after)}


class UpstreamSeveredError(ServingError):
    """A pod died while the router was mid-relay of its streaming body —
    bytes are already on the wire, so the status cannot change, but the
    client must NOT mistake the truncation for a complete response. The
    router writes this typed payload as the final stream event (502 in the
    payload; the pod is quarantined and the router's metrics count the
    severed stream)."""

    http_status = 502

    def __init__(self, pod: str, detail: str = "") -> None:
        super().__init__(
            f"upstream pod {pod} died mid-stream"
            + (f": {detail}" if detail else "")
            + "; response is incomplete — retry the request"
        )
        self.pod = pod


class MalformedResumeError(ServingError):
    """The request carried a resume block the pod cannot honor as stated
    (missing seed, non-integer or negative tokens, empty emitted list,
    or a resume on a surface/path that cannot replay it). 400: the
    caller must fall back to its typed severed error, never silently
    restart the stream — the client already holds the first k tokens."""

    http_status = 400
    api_type = "invalid_request_error"

    def __init__(self, detail: str) -> None:
        super().__init__(f"malformed resume: {detail}")


class ResumeExhaustedError(ServingError):
    """The resume frontier is at or past the request's end: every
    budgeted token — or a stop token — was already emitted, so there is
    nothing left to continue. 422, distinct from the 400 family: the
    block was well-formed and the original stream was COMPLETE, so the
    router finishes the client stream instead of surfacing an error."""

    http_status = 422
    api_type = "invalid_request_error"

    def __init__(self, detail: str) -> None:
        super().__init__(f"resume exhausted: {detail}")


class ModelFailedError(ServingError):
    """The model's load crashed (state FAILED in the lifecycle pool). 503:
    the slot stays retryable — an admin re-POST of the same name retries
    the load — and the failure reason rides in the message so clients and
    GET /v1/models agree on what broke."""

    http_status = 503

    def __init__(self, name: str, reason: str = "") -> None:
        super().__init__(
            f"model {name!r} failed to load" + (f": {reason}" if reason else "")
        )
        self.model = name
        self.reason = reason
