"""Typed serving-path errors with one HTTP mapping.

The engine, the native /v1 handlers, and the OpenAI veneer all need to
agree on what an overloaded queue, an expired deadline, or a dead engine
looks like on the wire. Before this module, raw ``BaseException`` objects
flowed through ``row.out.put(err)`` and surfaced differently between the
streaming and non-streaming paths; now every failure class is one typed
exception carrying its canonical status:

- ``QueueFullError``  -> 429 + ``Retry-After`` (bounded admission shed)
- ``DeadlineExceededError`` -> 504 (request expired before/while decoding)
- ``PoisonedRequestError``  -> 400 (quarantined: this request crashed the
  engine loop repeatedly; re-admitting it would crash-loop the server)
- ``EngineBrokenError``     -> 503 (the engine died mid-flight; the
  supervisor may be restarting it — retryable, unlike a 500)

Kept dependency-free (no jax, no requests) so the transport layer can
import it at module top without cost.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base for typed serving failures; ``http_status`` is the canonical
    mapping every transport (native JSON + OpenAI SSE) uses."""

    http_status = 500
    api_type = "server_error"  # OpenAI error.type

    def headers(self) -> dict[str, str]:
        return {}


class QueueFullError(ServingError):
    """Admission backlog is at --max-queue-depth: shed NOW with 429 so the
    client backs off, instead of queueing into unbounded latency."""

    http_status = 429
    api_type = "rate_limit_error"

    def __init__(self, depth: int, limit: int, retry_after: float = 1.0) -> None:
        super().__init__(
            f"admission queue full ({depth} waiting, limit {limit}); retry later"
        )
        self.retry_after = max(1, int(retry_after))

    def headers(self) -> dict[str, str]:
        return {"Retry-After": str(self.retry_after)}


class DeadlineExceededError(ServingError):
    """The request sat past --request-timeout (queued, filling, or
    decoding); it was expired at a chunk boundary instead of occupying a
    slot the backlog needs."""

    http_status = 504

    def __init__(self, state: str, timeout_s: float) -> None:
        super().__init__(
            f"request deadline exceeded while {state} "
            f"(--request-timeout {timeout_s:g}s)"
        )
        self.state = state


class PoisonedRequestError(ServingError):
    """This exact request crashed the engine loop repeatedly; it is
    quarantined and rejected up front — re-admitting it would turn one bad
    request into a restart livelock."""

    http_status = 400
    api_type = "invalid_request_error"

    def __init__(self, crashes: int) -> None:
        super().__init__(
            f"request quarantined: it crashed the engine {crashes} times"
        )


class EngineBrokenError(ServingError):
    """The engine loop died while this request was in flight (or the
    circuit breaker opened). 503: the supervisor restarts the engine, so
    a retry against this pod (or another) is the right client move."""

    http_status = 503

    def __init__(self, message: str = "serving engine failed") -> None:
        super().__init__(message)
