"""OpenAI-compatible serving surface: /v1/completions + /v1/chat/completions.

The reference registry has no serving API at all; this sidecar's native
token-id API (docs/api.md) is the precise contract, and this module is the
compatibility veneer over it so off-the-shelf OpenAI SDK clients can point
at a modelx-tpu sidecar unchanged (``base_url=http://sidecar:8000/v1``).

Scope (documented, deliberate):
- ``prompt``: str or list of str (each row generated independently);
  ``messages``: the standard role/content list. When the model ships a
  ``chat_template`` in its tokenizer_config.json (stored in the registry
  like any blob), messages render through IT — sandboxed jinja with the
  HF conventions (add_generation_prompt=True, bos/eos tokens, encode with
  add_special_tokens=False), so llama-3-instruct/qwen-chat/gemma-it get
  their real turn formatting. Without one, the simple documented fallback
  ``<|role|>\\n{content}\\n`` ... ``<|assistant|>\\n``.
- ``max_tokens``, ``temperature``, ``top_p``, ``seed``, ``stop`` (up to 4
  strings), ``stream`` (SSE). ``top_k`` accepted as an extension.
- ``n``: each prompt decodes n samples (per-row seed streams — the same
  derivation multi-row native requests use), non-streaming.
- ``logprobs``: completions take the classic integer form (0-5 alternatives
  per position), chat takes ``logprobs: true`` + ``top_logprobs`` (0-20).
  Values come from scoring forwards over prompt+completion after
  generation (ModelServer.score_logprobs_rows — a request's choices batch
  into shared device calls); non-streaming only — stream=true with
  logprobs (or n > 1) gets a clear 400.
- ``echo``, tool calls: rejected with a clear 400.

Requires the model to ship a ``tokenizer.json`` (the registry stores it as
an ordinary blob next to the weights).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import time
import uuid
from typing import Iterator

import numpy as np

from modelx_tpu.dl.serving_errors import (
    MalformedResumeError,
    ResumeExhaustedError,
    deadline_kwargs,
)

logger = logging.getLogger("modelx.serve")

OBJ_COMPLETION = "text_completion"
OBJ_CHAT = "chat.completion"
OBJ_CHAT_CHUNK = "chat.completion.chunk"

_UNSUPPORTED = ("echo", "tools", "tool_choice", "functions")


class APIError(Exception):
    """OpenAI-shaped error: {"error": {"message", "type", "code"}}.

    ``headers`` ride to the transport (dl/serve.py) — the still-loading
    503 carries Retry-After exactly like the native surface's."""

    def __init__(self, status: int, message: str, err_type: str = "invalid_request_error",
                 headers: dict | None = None) -> None:
        super().__init__(message)
        self.status = status
        self.headers = dict(headers or {})
        self.payload = {
            "error": {"message": message, "type": err_type, "param": None, "code": None}
        }


def api_error_for(e) -> APIError:
    """ONE OpenAI payload per typed serving failure (dl/serving_errors.py):
    the exception's canonical status + api_type + headers (Retry-After on
    sheds and still-loading), identical between the streaming and
    non-streaming paths."""
    return APIError(e.http_status, str(e), e.api_type, headers=e.headers())


def resolve_model(sset, req: dict):
    """The ``model`` field picks the sidecar tenant; absent = default.
    Lifecycle-transitioning names (dl/lifecycle.py) map like the native
    surface: PULLING/LOADING 503 + Retry-After, DRAINING 409, FAILED 503
    with the reason (the serve.py handler also pre-gates, but direct
    library callers of run_completion get identical behavior here)."""
    from modelx_tpu.dl.serving_errors import ServingError

    name = req.get("model") or sset.default
    server = sset.servers.get(name)
    pool = getattr(sset, "pool", None)
    if pool is not None:
        try:
            pool.check_admission(name)
        except ServingError as e:
            raise api_error_for(e) from e
    if server is None:
        raise APIError(404, f"model {name!r} not found", "not_found_error")
    if not server.ready:
        raise APIError(503, f"model {name!r} is still loading", "server_error",
                       headers={"Retry-After": "2"})
    return server


def tokenizer_for(server):
    try:
        tok = server.tokenizer()
    except RuntimeError as e:
        raise APIError(503, str(e), "server_error") from e
    if tok is None:
        raise APIError(
            400, "model has no tokenizer.json; use the token-id API (/v1/generate)"
        )
    return tok


def render_messages(messages, spec: dict | None = None) -> str:
    """Messages -> prompt text. With ``spec`` (the model's own
    ``chat_template`` from tokenizer_config.json, see
    ModelServer.chat_template) the template renders in a SANDBOXED jinja
    environment with the HF conventions (``messages``,
    ``add_generation_prompt=True``, ``bos_token``/``eos_token``,
    ``raise_exception``) — llama-3-instruct/qwen-chat/gemma-it get their
    real formatting. Without one, the simple generic role template."""
    if not isinstance(messages, list) or not messages:
        raise APIError(400, "messages must be a non-empty list")
    for i, m in enumerate(messages):
        if not isinstance(m, dict) or not isinstance(m.get("content"), str):
            raise APIError(400, f"messages[{i}] must be {{role, content}} with string content")
        role = m.get("role", "user")
        if not isinstance(role, str) or (
            spec is None and role not in ("system", "user", "assistant")
        ):
            # the generic template only knows the three core roles; a model
            # template validates roles itself (raise_exception)
            raise APIError(400, f"messages[{i}].role must be system|user|assistant")
    if spec is not None and not spec.get("broken"):
        from modelx_tpu.dl.serve import ChatTemplateRejected

        render_kwargs = dict(
            add_generation_prompt=True,
            bos_token=spec.get("bos_token", ""),
            eos_token=spec.get("eos_token", ""),
        )
        try:
            # compiled ONCE per model (ModelServer.chat_template); only the
            # render runs per request
            return spec["compiled"].render(messages=messages, **render_kwargs)
        except ChatTemplateRejected as e:
            # the template itself said no (raise_exception): the caller's
            # messages violate the model's conversation contract — 400
            raise APIError(400, f"chat template rejected the messages: {e}")
        except Exception as e:
            # triage before blaming the client: a render that ALSO fails on
            # a trivial probe payload is a broken template (a server-side
            # defect in the pushed tokenizer_config.json), and a 400 would
            # send the caller fixing messages that aren't the problem —
            # fall back to the generic role template with a warning.
            # Failures the probe does NOT reproduce are message-dependent:
            # those stay 400. The verdict memoizes in the per-model spec
            # dict so a broken template costs two failed renders + one log
            # line ONCE, not per request.
            probe = [{"role": "user", "content": "probe"}]
            try:
                spec["compiled"].render(messages=probe, **render_kwargs)
            except ChatTemplateRejected:
                # the template deliberately rejected the bare probe (e.g.
                # requires a system turn): that's template logic working,
                # not breakage — the original failure stays the caller's
                raise APIError(400, f"chat template failed to render: {e}")
            except Exception:
                spec["broken"] = True
                logger.warning(
                    "chat template fails independent of the request (%s); "
                    "falling back to the generic role template", e,
                )
            else:
                raise APIError(400, f"chat template failed to render: {e}")
    parts = [
        f"<|{m.get('role', 'user')}|>\n{m['content']}\n" for m in messages
    ]
    parts.append("<|assistant|>\n")
    return "".join(parts)


MAX_PROMPTS = 32  # one request must stay one bounded unit of device work


def parse_prompts(req: dict, chat: bool, server=None) -> list[str]:
    if chat:
        spec = server.chat_template() if server is not None else None
        return [render_messages(req.get("messages"), spec)]
    prompt = req.get("prompt")
    if isinstance(prompt, str) and prompt:
        return [prompt]
    if (
        isinstance(prompt, list)
        and prompt
        and all(isinstance(p, str) and p for p in prompt)
    ):
        if len(prompt) > MAX_PROMPTS:
            raise APIError(400, f"at most {MAX_PROMPTS} prompts per request")
        return prompt
    raise APIError(400, "prompt must be a non-empty string or list of non-empty strings")


def parse_sampling(req: dict, limit: int) -> tuple[int, dict]:
    for key in _UNSUPPORTED:
        if key not in req:
            continue
        # ignoring these would silently change semantics the caller asked
        # for; values that ask for nothing (None/False, empty containers
        # like LiteLLM's serialized-default tools: []) pass
        val = req.get(key)
        if not (val is None or val is False or val == [] or val == {}):
            raise APIError(400, f"{key!r} is not supported")
    try:
        # max_completion_tokens is the current OpenAI chat param (newer SDKs
        # send it instead of the deprecated max_tokens); honoring only one
        # would silently cap a 1000-token ask at the default 16, violating
        # the module's 400-or-honor principle. Current name wins when both
        # are present (matching OpenAI's own precedence).
        if "max_completion_tokens" in req and req["max_completion_tokens"] is not None:
            n_tokens = int(req["max_completion_tokens"])
        else:
            n_tokens = int(req.get("max_tokens", 16))
        if "seed" in req:
            seed = int(req["seed"])
        else:
            # OpenAI semantics: no seed means nondeterministic — two
            # identical requests must not return byte-identical samples
            # (the default temperature here is 1.0, not the native API's
            # greedy 0), so derive a fresh per-request seed
            seed = int.from_bytes(os.urandom(4), "big") >> 1
        samp = {
            "temperature": float(req.get("temperature", 1.0)),
            "top_k": int(req.get("top_k", 0)),
            "top_p": float(req.get("top_p", 1.0)),
            "seed": seed,
        }
    except (TypeError, ValueError):
        raise APIError(
            400,
            "max_tokens/max_completion_tokens/temperature/top_k/top_p/seed "
            "must be numbers",
        ) from None
    if not (1 <= n_tokens <= limit):
        raise APIError(400, f"max_tokens/max_completion_tokens must be in [1, {limit}]")
    if not (0.0 <= samp["temperature"] <= 2.0):
        raise APIError(400, "temperature must be in [0, 2]")
    if not (0.0 < samp["top_p"] <= 1.0):
        raise APIError(400, "top_p must be in (0, 1]")
    if not (0 <= samp["top_k"] < 2**31) or not (0 <= samp["seed"] < 2**31):
        raise APIError(400, "top_k/seed must be in [0, 2^31)")
    return n_tokens, samp


def parse_n(req: dict, prompts: int, limit: int = MAX_PROMPTS) -> int:
    """``n`` samples per prompt; prompts x n stays one bounded unit of
    device work (the MAX_PROMPTS cap the prompt list already obeys).
    An explicit null asks for nothing (LiteLLM-style serialized defaults)
    and means the default 1."""
    n = req.get("n")
    if n is None:
        return 1
    if isinstance(n, bool) or not isinstance(n, int) or n < 1:
        raise APIError(400, "n must be a positive integer")
    if prompts * n > limit:
        raise APIError(400, f"prompt count x n must not exceed {limit}")
    return n


def parse_logprobs(req: dict, chat: bool) -> int | None:
    """Requested alternatives-per-position, or None when logprobs are off.

    Completions: the classic integer form (``logprobs: k``, 0 <= k <= 5 —
    0 still returns the chosen tokens' logprobs). Chat: ``logprobs: true``
    with optional ``top_logprobs`` (0-20); OpenAI requires top_logprobs to
    ride only with logprobs=true, and so does this. Explicit null/false
    ask for nothing and mean off (clients that serialize defaults)."""
    val = req.get("logprobs")
    if chat:
        if val is None or val is False:
            if req.get("top_logprobs") is not None:
                raise APIError(400, "top_logprobs requires logprobs: true")
            return None
        if val is not True:
            raise APIError(400, "logprobs must be a boolean for chat")
        k = req.get("top_logprobs")
        if k is None:
            return 0
        if isinstance(k, bool) or not isinstance(k, int) or not (0 <= k <= 20):
            raise APIError(400, "top_logprobs must be an integer in [0, 20]")
        return k
    if val is None or val is False:
        return None
    if isinstance(val, bool) or not isinstance(val, int) or not (0 <= val <= 5):
        raise APIError(400, "logprobs must be an integer in [0, 5]")
    return val


def logprobs_trim(tok, new_ids: list[int], text_len: int):
    """(kept_ids, token_strs, offsets): the content tokens whose text
    survived stop-sequence truncation (``text_len`` < 0 keeps all;
    cumulative per-token offsets, best-effort for tokenizers whose full
    decode differs from per-token concatenation)."""
    token_strs = [tok.decode([int(t)]) for t in new_ids]
    offsets, off, keep = [], 0, 0
    for s in token_strs:
        if 0 <= text_len <= off:
            break
        offsets.append(off)
        off += len(s)
        keep += 1
    return new_ids[:keep], token_strs[:keep], offsets


def logprobs_shape(tok, token_strs: list[str], offsets: list[int],
                   scores, k: int, chat: bool) -> dict:
    """OpenAI-shaped logprobs for one choice from precomputed ``scores``
    ((token_lps, top_ids, top_lps) — ModelServer.score_logprobs_rows;
    empty token lists produce valid empty shapes)."""
    token_lps, top_ids, top_lps = scores
    if chat:
        content = []
        for i, s in enumerate(token_strs):
            content.append({
                "token": s,
                "logprob": float(token_lps[i]),
                "bytes": list(s.encode()),
                "top_logprobs": [
                    {"token": tok.decode([int(tid)]), "logprob": float(tlp),
                     "bytes": list(tok.decode([int(tid)]).encode())}
                    for tid, tlp in zip(top_ids[i], top_lps[i])
                ] if k else [],
            })
        return {"content": content}
    return {
        "tokens": token_strs,
        "token_logprobs": [float(x) for x in token_lps],
        # the classic completions format keys alternatives by token TEXT —
        # inherently lossy when distinct ids decode to the same string
        # (byte-fallback tokens); entries can number fewer than k, exactly
        # as OpenAI's own dict-shaped responses do
        "top_logprobs": (
            [
                {tok.decode([int(tid)]): float(tlp)
                 for tid, tlp in zip(row_i, row_l)}
                for row_i, row_l in zip(top_ids, top_lps)
            ] if k else None
        ),
        "text_offset": offsets,
    }


def parse_stop(req: dict) -> list[str]:
    stop = req.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if (
        not isinstance(stop, list)
        or len(stop) > 4
        or not all(isinstance(s, str) and s for s in stop)
    ):
        raise APIError(400, "stop must be a string or a list of up to 4 non-empty strings")
    return stop


def apply_stop(text: str, stops: list[str]) -> tuple[str, str]:
    """(truncated text, finish_reason): cut at the earliest stop match."""
    cut = len(text)
    for s in stops:
        i = text.find(s)
        if i >= 0:
            cut = min(cut, i)
    if cut < len(text):
        return text[:cut], "stop"
    return text, "length"


def encode_prompt(tok, server, text: str, n_tokens: int = 0,
                  add_special_tokens: bool = True) -> list[int]:
    ids = tok.encode(text, add_special_tokens=add_special_tokens)
    if not ids:
        raise APIError(400, "prompt tokenized to zero tokens")
    vocab = getattr(server.cfg, "vocab_size", 0) or 0
    if vocab and (min(ids) < 0 or max(ids) >= vocab):
        raise APIError(400, f"tokenizer produced ids outside the model vocab [0, {vocab})")
    n_pos = getattr(server.cfg, "n_positions", 0) or 0
    if n_pos and len(ids) + n_tokens > n_pos:
        # absolute-position families (gpt2): decoding past n_positions
        # silently clamps the wpe gather inside jit — 400 like /v1/generate
        raise APIError(
            400,
            f"prompt ({len(ids)} tokens) + max_tokens ({n_tokens}) exceeds "
            f"the model's {n_pos}-position context",
        )
    return ids


def _envelope(obj_type: str, model: str) -> dict:
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": obj_type,
        "created": int(time.time()),
        "model": model,
    }


def eos_for(tok, req: dict) -> tuple[int, ...]:
    """The tokenizer's end-of-sequence ids, unless the request opts out
    with the ``ignore_eos`` extension (vLLM-compatible). OpenAI semantics:
    generation ends at EOS with finish_reason "stop" and the EOS token
    never appears in the content."""
    ignore = req.get("ignore_eos", False)
    if not isinstance(ignore, bool):
        raise APIError(400, "ignore_eos must be a boolean")
    if ignore or not hasattr(tok, "eos_ids"):
        return ()
    return tok.eos_ids()


def run_completion(sset, req: dict, chat: bool,
                   timeout_s: float | None = None,
                   priority: str = "interactive",
                   request_id: str = "",
                   timing: dict | None = None) -> dict:
    """Non-streaming completions/chat: returns the OpenAI response body.
    ``timeout_s``/``priority`` are the transport's propagated deadline
    remainder and priority class — honored by the continuous engine
    (clamping its per-request expiry), ignored by engines without
    deadline machinery. ``timing`` (ISSUE 13) is the transport's
    out-param: the continuous engine fills it with the per-request phase
    breakdown, which the handler returns as X-ModelX-Timing-* headers."""
    server = resolve_model(sset, req)
    tok = tokenizer_for(server)
    prompts = parse_prompts(req, chat, server)
    # a model chat template carries its own special tokens (bos, turn
    # markers): encode raw, the HF apply_chat_template convention
    raw_encode = chat and server.chat_template() is not None
    n_tokens, samp = parse_sampling(req, sset.max_new_tokens_limit)
    n_samples = parse_n(req, len(prompts))
    top_lp = parse_logprobs(req, chat)
    stops = parse_stop(req)
    eos = eos_for(tok, req)

    if req.get("stream_options") is not None:
        # OpenAI contract: only valid with stream=true — silently accepting
        # it here would hide the misuse until the client flips stream on.
        # (An explicit null matches the streaming path's "absent" handling.)
        raise APIError(400, "stream_options is only allowed when stream is true")
    # routing policy lives in ONE place: continuous > speculation > batcher
    engine = sset.engine_for(server, len(prompts) * n_samples, samp["temperature"])
    server.stats["requests"] += 1
    id_rows = [
        encode_prompt(tok, server, text, n_tokens,
                      add_special_tokens=not raw_encode)
        for text in prompts
    ]
    # the continuous engine can retire a row's slot AT its EOS; other
    # engines decode the full budget and the EOS trim happens below
    continuous = engine is sset.cbatchers.get(server.name)
    stops_kw = {"stop_token_ids": list(eos)} if eos and continuous else {}
    # the deadline remainder + priority class reach only the continuous
    # engine (per-request expiry clamp, interactive-first backlog); other
    # engines have no deadline machinery to honor them with
    deadline_kw = deadline_kwargs(timeout_s, priority) if continuous else {}
    timing_kw = {"timing": timing} if continuous and timing is not None else {}

    def _one(ids: list[int]) -> list[list[int]]:
        # n samples of one prompt = n rows of the same ids in ONE engine
        # call: every engine derives per-row (seed + i) streams for
        # multi-row requests, which is exactly OpenAI's n semantics
        batch = np.asarray([ids] * n_samples, np.int32)
        out = engine.generate(batch, max_new_tokens=n_tokens,
                              **stops_kw, **deadline_kw, **timing_kw, **samp)
        return [row[len(ids):].tolist() for row in out]

    if len(id_rows) > 1 and engine is not server:
        # concurrent submissions ride the batcher's coalescing window and
        # decode as ONE ragged device call instead of N sequential ones
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(id_rows)) as pool:
            rows_out = list(pool.map(_one, id_rows))
    else:
        rows_out = [_one(ids) for ids in id_rows]

    from modelx_tpu.models.decode import stop_cut

    eos_set = set(eos)
    # usage counts each PROMPT once, however many samples it produced
    prompt_tokens = sum(len(ids) for ids in id_rows)
    completion_tokens = 0
    flat = [
        (ids, new_ids)
        for ids, samples in zip(id_rows, rows_out)
        for new_ids in samples
    ]
    built = []  # (kept token strs, offsets, text, finish)
    score_rows = []
    for ids, new_ids in flat:
        cut = stop_cut(new_ids, eos_set)
        hit_eos = cut is not None
        if hit_eos:
            # usage counts the EOS (it was generated); content excludes it
            new_ids = new_ids[:cut]
        completion_tokens += len(new_ids)
        content_ids = new_ids[:-1] if hit_eos else new_ids
        text_out, finish = apply_stop(tok.decode(content_ids), stops)
        stop_truncated = finish == "stop"  # apply_stop cut the text itself
        if hit_eos and finish == "length":
            finish = "stop"
        strs, offsets = [], []
        if top_lp is not None:
            kept, strs, offsets = logprobs_trim(
                tok, content_ids, len(text_out) if stop_truncated else -1
            )
            score_rows.append((ids, kept))
        built.append((strs, offsets, text_out, finish))
    scores = (
        server.score_logprobs_rows(score_rows, top_k=top_lp)
        if top_lp is not None else None
    )
    choices = []
    for i, (strs, offsets, text_out, finish) in enumerate(built):
        lp = None
        if scores is not None:
            lp = logprobs_shape(tok, strs, offsets, scores[i], top_lp, chat)
        if chat:
            choices.append({
                "index": i,
                "message": {"role": "assistant", "content": text_out},
                "logprobs": lp,
                "finish_reason": finish,
            })
        else:
            choices.append({
                "index": i, "text": text_out, "logprobs": lp,
                "finish_reason": finish,
            })

    body = _envelope(OBJ_CHAT if chat else OBJ_COMPLETION, server.name)
    body["choices"] = choices
    body["usage"] = {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }
    return body


def stream_completion(sset, req: dict, chat: bool,
                      timeout_s: float | None = None,
                      priority: str = "interactive",
                      resume=None, request_id: str = "",
                      timing: dict | None = None) -> Iterator[dict]:
    """SSE event bodies for stream=true (single prompt only). The first
    ``next()`` performs all validation — callers pull one event before
    committing a 200 so bad requests still fail with their real status.
    ``timeout_s``/``priority`` propagate to the continuous engine like
    the non-streaming path's.

    ``resume`` is a parsed ``(emitted_token_ids, seed)`` pair (the shared
    ``serving_errors.parse_resume`` output): the row re-prefills
    prompt + emitted and continues the ORIGINAL (seed, step) sample
    stream at step k, so the continuation's tokens are byte-identical to
    the ones the severed stream would have produced. The SSE content
    resumes from the text the emitted tokens decode to — already on the
    dead stream's wire, never re-sent. Typed: malformed 400, resume past
    the budget or EOS 422 (the original stream was complete)."""
    server = resolve_model(sset, req)
    tok = tokenizer_for(server)
    prompts = parse_prompts(req, chat, server)
    raw_encode = chat and server.chat_template() is not None
    if len(prompts) != 1:
        raise APIError(400, "stream supports a single prompt")
    if parse_n(req, 1) != 1:
        raise APIError(400, "n > 1 is not supported with stream")
    if parse_logprobs(req, chat) is not None:
        # logprobs come from a post-generation scoring forward
        # (ModelServer.score_logprobs); per-chunk values would need the
        # decode programs to emit them — honor the non-streaming form
        raise APIError(400, "logprobs are not supported with stream; "
                            "use stream: false")
    n_tokens, samp = parse_sampling(req, sset.max_new_tokens_limit)
    stops = parse_stop(req)
    ids = encode_prompt(tok, server, prompts[0], n_tokens,
                        add_special_tokens=not raw_encode)
    if server.family.decode_fns is None:
        # fail before any SSE bytes hit the wire, not mid-stream
        raise APIError(400, f"model family {server.family.name!r} does not support streaming")
    opts = req.get("stream_options")
    if opts is not None and not isinstance(opts, dict):
        raise APIError(400, "stream_options must be an object")
    include_usage = bool((opts or {}).get("include_usage", False))

    eos = eos_for(tok, req)  # validates ignore_eos BEFORE counting
    resume_step = 0
    resume_ids: list[int] = []
    if resume is not None:
        emitted, rseed = resume
        vocab = getattr(server.cfg, "vocab_size", 0) or 0
        if vocab and max(emitted) >= vocab:
            raise MalformedResumeError(f"emitted token ids must be in [0, {vocab})")
        if len(emitted) >= n_tokens:
            # the original stream was COMPLETE: nothing left to decode
            raise ResumeExhaustedError(
                f"{len(emitted)} tokens already emitted of a "
                f"{n_tokens}-token budget")
        if any(t in set(eos) for t in emitted):
            raise ResumeExhaustedError("an EOS token was already emitted")
        # resume.seed pins the effective seed: this surface derives a
        # RANDOM seed when the request omits one, and a continuation must
        # rejoin the original stream, not start a fresh one
        samp["seed"] = int(rseed)
        resume_ids = [int(t) for t in emitted]
        resume_step = len(resume_ids)
        ids = list(ids) + resume_ids
        n_tokens -= resume_step
    server.stats["requests"] += 1
    # a stop sequence can straddle decode chunks ("hello wo" + "rld"):
    # hold back the longest prefix a stop could still complete, so no text
    # past a stop match ever reaches the wire
    reserve = max((len(s) for s in stops), default=1) - 1

    def events() -> Iterator[dict]:
        from modelx_tpu.models.decode import stop_cut

        eos_set = set(eos)
        # continuous engine when enabled, operator chunk size either way;
        # an EOS hit ends decode early (the stream layer drops the EOS
        # token from the content and reports finish_reason "stop")
        kw = deadline_kwargs(timeout_s, priority)
        if resume_step:
            kw["resume_step"] = resume_step
        gen = sset.stream_source(server, np.asarray([ids], np.int32), n_tokens,
                                 samp, stop_token_ids=list(eos) or None,
                                 request_id=request_id, timing=timing, **kw)
        # prime generation BEFORE yielding anything: the transport commits
        # its 200 after the first event, and a compile/decode failure must
        # surface as a real status even for chat (whose first event is the
        # role chunk, not decoded text)
        first_piece = next(gen, None)
        envelope = _envelope(OBJ_CHAT_CHUNK if chat else OBJ_COMPLETION, server.name)

        def content_event(delta: str) -> dict:
            choice = (
                {"index": 0, "delta": {"content": delta}, "finish_reason": None}
                if chat
                else {"index": 0, "text": delta, "finish_reason": None}
            )
            return {**envelope, "choices": [choice]}

        if chat:  # role announcement chunk (OpenAI contract)
            yield {
                **envelope,
                "choices": [{"index": 0, "delta": {"role": "assistant"}, "finish_reason": None}],
            }
        # a resumed stream's emitted tokens decoded to text ALREADY on the
        # severed stream's wire: seed the sent/decoded state with them so
        # only genuinely new text is emitted (glyph-stable decode still
        # runs over the full generated prefix, emitted included)
        sent = tok.decode(resume_ids) if resume_ids else ""
        text = sent
        new_ids: list[int] = list(resume_ids)
        eos_count = 0
        finish = "length"
        pieces = gen if first_piece is None else itertools.chain((first_piece,), gen)
        for piece in pieces:
            piece_ids = piece[0].tolist()
            tcut = stop_cut(piece_ids, eos_set)
            hit_eos = tcut is not None
            if hit_eos:
                # usage counts the EOS; the content never includes it
                eos_count = 1
                piece_ids = piece_ids[: tcut - 1]
            new_ids.extend(piece_ids)
            # decode the FULL generated prefix each chunk and emit the tail:
            # per-chunk decode would split multi-token glyphs at chunk edges
            text = tok.decode(new_ids)
            cut, finish_now = apply_stop(text, stops)
            if finish_now == "stop":
                if cut[len(sent):]:
                    yield content_event(cut[len(sent):])
                sent, finish = cut, "stop"
                break
            if not cut.startswith(sent):
                # an emitted prefix changed on re-decode (an incomplete glyph
                # slipped out); bytes on the wire can't be retracted — hold
                # everything until the decode re-extends what was sent
                if hit_eos:
                    break  # the flush below emits the re-decoded remainder
                continue
            # trailing U+FFFD means the last glyph's bytes are still split
            # across tokens: provisional, the next chunk may resolve it
            stable = len(cut)
            while stable > len(sent) and cut[stable - 1] == "�":
                stable -= 1
            safe = max(len(sent), min(len(cut) - reserve, stable))
            if cut[len(sent):safe]:
                yield content_event(cut[len(sent):safe])
                sent = cut[:safe]
            if hit_eos:
                break  # the engine already stopped; flush the tail below
        if finish != "stop":
            if text.startswith(sent):
                if text[len(sent):]:
                    yield content_event(text[len(sent):])  # flush the held tail
            elif text:
                # the final re-decode DIVERGED from bytes already on the wire
                # (an incomplete glyph slipped out before an EOS/stream end).
                # The wire can't be retracted, so emit everything past the
                # longest common prefix: content arrives complete (matching
                # usage.completion_tokens) at the cost of one rewritten
                # glyph region, instead of being silently dropped
                lcp = 0
                for a, b in zip(sent, text):
                    if a != b:
                        break
                    lcp += 1
                if text[lcp:]:
                    yield content_event(text[lcp:])
        if eos_count and finish == "length":
            finish = "stop"
        yield {
            **envelope,
            "choices": [
                {"index": 0, "delta": {}, "finish_reason": finish}
                if chat
                else {"index": 0, "text": "", "finish_reason": finish}
            ],
        }
        if include_usage:  # stream_options.include_usage (OpenAI contract:
            # a final chunk with empty choices carrying the usage)
            usage_event = {
                **envelope,
                "choices": [],
                "usage": {
                    "prompt_tokens": len(ids),
                    "completion_tokens": len(new_ids) + eos_count,
                    "total_tokens": len(ids) + len(new_ids) + eos_count,
                },
            }
            if timing is not None:
                # the per-request phase breakdown rides the SAME opt-in
                # final chunk (ISSUE 13): close the source first so the
                # engine's finally has filled the out-param even when a
                # stop token ended the loop early. Engines without phase
                # machinery leave it empty — the chunk stays unchanged.
                gen.close()
                if timing:
                    usage_event["timing"] = dict(timing)
            yield usage_event

    return events()


def models_payload(sset) -> dict:
    """GET /v1/models body serving BOTH contracts: the sidecar's native
    {default, models} keys and OpenAI's {object: "list", data: [...]}.

    The DYNAMIC model set (dl/lifecycle.py) is fully reflected: every
    lifecycle entry appears with its state — a PULLING/LOADING model shows
    up before it can serve, a FAILED one carries its failure reason, an
    UNLOADED one records that it was here — and OpenAI ``data`` rows gain
    a ``lifecycle_state`` extension field."""
    pool = getattr(sset, "pool", None)
    lifecycle = pool.states() if pool is not None else {}
    models: dict = {}
    for n, s in list(sset.servers.items()):
        d = {"ready": s.ready, **s.stats}
        if s.load_error:
            d["error"] = s.load_error
        if n in lifecycle:
            d["lifecycle"] = lifecycle[n]
        models[n] = d
    for n, st in lifecycle.items():
        if n not in models:  # PULLING/FAILED-at-pull/UNLOADED: no server
            d = {"ready": False, "lifecycle": st}
            if st.get("error"):
                d["error"] = st["error"]
            models[n] = d
    return {
        "default": sset.default,
        "models": models,
        "object": "list",
        # OpenAI clients treat data rows as invokable: UNLOADED models
        # stay visible in the native ``models`` history but not here
        "data": [
            {"id": n, "object": "model", "created": 0, "owned_by": "modelx-tpu",
             **({"lifecycle_state": lifecycle[n]["state"]} if n in lifecycle else {})}
            for n in models
            if lifecycle.get(n, {}).get("state") != "UNLOADED"
        ],
    }


def sse_encode(event: dict) -> bytes:
    return b"data: " + json.dumps(event).encode() + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
