"""safetensors format: header parsing, range planning, and writing.

The format is trivially range-friendly — 8-byte LE header length, JSON header
mapping tensor name -> {dtype, shape, data_offsets:[start,end]} (offsets
relative to the end of the header), then raw little-endian tensor bytes.
That property is what makes "stream shards straight into HBM" possible: a
tensor's bytes (or any slice of rows) live at a computable byte range.

Implemented directly (no safetensors-library dependency on the load path) so
reads can be planned and fetched rangewise.
"""

from __future__ import annotations

import dataclasses
import json
import struct
from typing import Any, BinaryIO

import numpy as np

try:  # bundled with jax; needed for bfloat16/fp8 numpy views
    import ml_dtypes
except ImportError:  # pragma: no cover
    ml_dtypes = None

_DTYPES: dict[str, Any] = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}
if ml_dtypes is not None:
    _DTYPES["BF16"] = ml_dtypes.bfloat16
    _DTYPES["F8_E4M3"] = ml_dtypes.float8_e4m3fn
    _DTYPES["F8_E5M2"] = ml_dtypes.float8_e5m2

_DTYPE_NAMES = {np.dtype(v): k for k, v in _DTYPES.items()}


@dataclasses.dataclass
class TensorInfo:
    name: str
    dtype: str  # safetensors dtype tag, e.g. "BF16"
    shape: tuple[int, ...]
    start: int  # byte offsets relative to data section start
    end: int
    # virtual stacked tensor (loader.fuse_expert_tensors): the byte ranges
    # live in these member tensors, one per leading-axis slot
    members: "list[TensorInfo] | None" = None

    @property
    def nbytes(self) -> int:
        return self.end - self.start

    def np_dtype(self):
        try:
            return np.dtype(_DTYPES[self.dtype])
        except KeyError:
            raise ValueError(f"unsupported safetensors dtype {self.dtype!r} for {self.name}") from None


def parse_header(header_bytes: bytes) -> dict[str, TensorInfo]:
    d = json.loads(header_bytes)
    out: dict[str, TensorInfo] = {}
    for name, info in d.items():
        if name == "__metadata__":
            continue
        out[name] = TensorInfo(
            name=name,
            dtype=info["dtype"],
            shape=tuple(info["shape"]),
            start=info["data_offsets"][0],
            end=info["data_offsets"][1],
        )
    return out


def read_header(reader: BinaryIO) -> tuple[dict[str, TensorInfo], int]:
    """Returns (tensors, data_offset) — data_offset is the absolute file
    offset where tensor data begins."""
    prefix = reader.read(8)
    if len(prefix) != 8:
        raise ValueError("truncated safetensors file")
    (header_len,) = struct.unpack("<Q", prefix)
    if header_len > 512 * 1024 * 1024:
        raise ValueError(f"implausible safetensors header length {header_len}")
    header = reader.read(header_len)
    return parse_header(header), 8 + header_len


def read_header_from_file(path: str) -> tuple[dict[str, TensorInfo], int]:
    with open(path, "rb") as f:
        return read_header(f)


def read_tensors(path: str, want=None) -> dict[str, np.ndarray]:
    """Read whole tensors from one file; ``want(name)`` filters without
    touching skipped tensors' bytes. Arrays own their memory (copied out of
    the read buffer). The single full-read helper shared by checkpoint
    restore and adapter loading — the loader's ranged/sharded path is
    separate by design (dl/loader.py)."""
    out: dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        infos, off = read_header(f)
        for name, info in infos.items():
            if want is not None and not want(name):
                continue
            f.seek(off + info.start)
            raw = f.read(info.nbytes)
            out[name] = np.frombuffer(raw, info.np_dtype()).reshape(info.shape).copy()
    return out


def write_safetensors(path: str, tensors: dict[str, np.ndarray], metadata: dict[str, str] | None = None) -> None:
    """Write a safetensors file (used by push-side conversion, tests, bench)."""
    header: dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = metadata
    offset = 0
    arrays = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        tag = _DTYPE_NAMES.get(arr.dtype)
        if tag is None:
            raise ValueError(f"unsupported numpy dtype {arr.dtype} for {name}")
        n = arr.nbytes
        header[name] = {
            "dtype": tag,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + n],
        }
        arrays.append(arr)
        offset += n
    hjson = json.dumps(header, separators=(",", ":")).encode()
    # pad header to 8-byte alignment (spec recommendation)
    pad = (8 - len(hjson) % 8) % 8
    hjson += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for arr in arrays:
            f.write(arr.tobytes())


def tensor_index_annotation(tensors: dict[str, TensorInfo], data_offset: int) -> str:
    """Serialize the header as the ``modelx.tensor.index`` blob annotation."""
    index = {
        name: {"dtype": t.dtype, "shape": list(t.shape), "data_offsets": [t.start, t.end]}
        for name, t in tensors.items()
    }
    return json.dumps({"data_offset": data_offset, "tensors": index}, sort_keys=True)


def parse_index_annotation(payload: str) -> tuple[dict[str, TensorInfo], int]:
    d = json.loads(payload)
    tensors = {}
    for name, info in d["tensors"].items():
        tensors[name] = TensorInfo(
            name=name,
            dtype=info["dtype"],
            shape=tuple(info["shape"]),
            start=info["data_offsets"][0],
            end=info["data_offsets"][1],
        )
    return tensors, int(d["data_offset"])


def row_range(t: TensorInfo, row_start: int, row_stop: int) -> tuple[int, int]:
    """Byte range (relative to data section) covering rows [row_start,row_stop)
    of the tensor's leading axis — the unit of shard-aligned fetching."""
    if not t.shape:
        return t.start, t.end
    rows = t.shape[0]
    row_bytes = (t.end - t.start) // max(rows, 1)
    return t.start + row_start * row_bytes, t.start + row_stop * row_bytes
