"""Pinned-manifest cache + control-plane health (PR 19).

The registry is the fleet's last hard dependency on the serving path: a
pull, a swap-in, a tier keying, a program publish all start with "fetch
the manifest". Content addressing makes that dependency SOFT — a manifest
the pod fetched yesterday still names the exact blob digests it named
then, and every blob either sits digest-verified in the local blob cache
(dl/blob_cache.py) or re-verifies on fetch. So this module persists every
successful manifest fetch (``{ref -> manifest JSON, config yaml,
fetched_at}``) on local disk, and ``RegistryClient.get_manifest`` serves
the pinned copy when every endpoint is down: stale-WHILE-revalidate,
where stale is explicitly safe because blobs are content-addressed and
staleness degrades control-plane freshness, never data-plane
correctness.

The module also owns the pod-level control-plane health tracker the
serving surface reports (``/healthz``/``/admin/models`` ->
``control_plane: ok|degraded|offline``). Readiness does NOT gate on it:
a pod whose models are READY keeps serving through any registry outage;
the block exists so operators (and the fleet router's rebalancer) can
tell "registry is down" apart from "pod is down".
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import tempfile
import threading
import time

logger = logging.getLogger("modelx.dl")

_ENV_DIR = "MODELX_MANIFEST_CACHE_DIR"


class OfflineUnavailableError(Exception):
    """The control plane is down and the local ladder (cached manifest +
    blob cache + tier store) cannot materialize the model. The lifecycle
    pool maps this to the retryable-507 contract: the pressure clears
    when the registry comes back."""


def _entry_key(registry: str, repository: str, version: str) -> str:
    ident = f"{registry.rstrip('/')}/{repository}@{version or 'latest'}"
    return hashlib.sha256(ident.encode()).hexdigest()


class ManifestCache:
    """Disk-persisted ``{ref -> pinned manifest}`` map, one JSON file per
    ref under ``root``. Writes are atomic (temp + rename) so a crashed
    pod never leaves a torn entry; reads tolerate garbage (a corrupt
    entry reads as a miss and the next successful fetch rewrites it)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self.stats = {"puts": 0, "hits": 0, "misses": 0, "stale_served": 0}

    def _path(self, registry: str, repository: str, version: str) -> str:
        return os.path.join(
            self.root, _entry_key(registry, repository, version) + ".json"
        )

    def put(self, registry: str, repository: str, version: str,
            manifest, config_yaml: bytes | None = None) -> None:
        """Persist a fetch that just succeeded. ``config_yaml`` (the
        modelx.yaml sidecar) is optional and merged into an existing
        entry when absent — manifest and config fetches happen at
        different call sites."""
        path = self._path(registry, repository, version)
        entry = {
            "registry": registry.rstrip("/"),
            "repository": repository,
            "version": version or "latest",
            "manifest": manifest.to_json(),
            "fetched_at": time.time(),
        }
        # all file I/O runs lock-free: the temp+rename write is atomic on
        # its own, and a racing manifest-put vs config-put for the same
        # ref at worst drops a config sidecar the next fetch rewrites
        if config_yaml is None:
            prev = self._read(path)
            if prev and "config_yaml_b64" in prev:
                entry["config_yaml_b64"] = prev["config_yaml_b64"]
        else:
            entry["config_yaml_b64"] = base64.b64encode(
                config_yaml).decode("ascii")
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".put-")
            with os.fdopen(fd, "w") as f:
                json.dump(entry, f)
            os.replace(tmp, path)
        except OSError as e:
            # a full/read-only disk must not fail the fetch that
            # succeeded — the cache just stays cold for this ref
            logger.warning("manifest cache write for %s/%s failed: %s",
                           repository, version, e)
            return
        with self._lock:
            self.stats["puts"] += 1

    @staticmethod
    def _read(path: str) -> dict | None:
        try:
            with open(path) as f:
                entry = json.load(f)
        except (OSError, ValueError):
            return None
        return entry if isinstance(entry, dict) else None

    def lookup(self, registry: str, repository: str, version: str):
        """The pinned :class:`~modelx_tpu.types.Manifest` for a ref, or
        None. Counts a hit/miss; the caller decides whether serving it is
        a ``stale_served`` event (see :meth:`note_stale_served`)."""
        from modelx_tpu.types import Manifest

        entry = self._read(self._path(registry, repository, version))
        with self._lock:
            if entry is None or "manifest" not in entry:
                self.stats["misses"] += 1
                return None
            self.stats["hits"] += 1
        try:
            return Manifest.from_json(entry["manifest"])
        except (KeyError, TypeError, ValueError):
            return None

    def lookup_config(self, registry: str, repository: str,
                      version: str) -> bytes | None:
        """The cached modelx.yaml bytes for a ref (None when the entry or
        its config sidecar is absent)."""
        entry = self._read(self._path(registry, repository, version))
        if not entry or "config_yaml_b64" not in entry:
            return None
        try:
            return base64.b64decode(entry["config_yaml_b64"])
        except (ValueError, TypeError):
            return None

    def age_s(self, registry: str, repository: str,
              version: str) -> float | None:
        entry = self._read(self._path(registry, repository, version))
        if not entry:
            return None
        return max(0.0, time.time() - float(entry.get("fetched_at", 0)))

    def note_stale_served(self) -> None:
        with self._lock:
            self.stats["stale_served"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


# -- process-wide default (the serving pod's cache) ---------------------------

_default_lock = threading.Lock()
_default: ManifestCache | None = None
_default_configured = False


def configure_default(root: str) -> ManifestCache | None:
    """Set the process-wide manifest cache (``--manifest-cache-dir``).
    Empty root disables it."""
    global _default, _default_configured
    with _default_lock:
        _default = ManifestCache(root) if root else None
        _default_configured = True
        return _default


def default_cache() -> ManifestCache | None:
    """The process default: whatever ``configure_default`` set, else the
    ``MODELX_MANIFEST_CACHE_DIR`` env var, else disabled."""
    global _default, _default_configured
    with _default_lock:
        if not _default_configured:
            root = os.environ.get(_ENV_DIR, "")
            _default = ManifestCache(root) if root else None
            _default_configured = True
        return _default


# -- control-plane health ------------------------------------------------------

OK = "ok"
DEGRADED = "degraded"
OFFLINE = "offline"

# how long after the last failure a clean primary success is still
# "degraded": one blip should read as a brownout for a beat, not flap
# ok/degraded per request
_DEGRADED_WINDOW_S = 30.0


class ControlPlaneHealth:
    """Event-driven registry reachability for one pod.

    - ``ok``: the most recent registry interaction succeeded on the
      primary endpoint, with no failure inside the degraded window;
    - ``degraded``: talking to the control plane, but not cleanly — the
      last success came off a mirror, or a failure happened recently;
    - ``offline``: the most recent interaction failed everywhere (or was
      served from the pinned-manifest cache).

    Readiness never gates on this block; it is an operator/rebalancer
    signal. State transitions land on the pool flight recorder when one
    is attached (``recorder``)."""

    def __init__(self, clock=time.monotonic) -> None:
        self._lock = threading.Lock()
        self._clock = clock
        self.recorder = None
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._last_ok_t = 0.0
            self._last_fail_t = 0.0
            self._last_ok_mirror = False
            self._state = OK
            self.stats = {"ok_total": 0, "mirror_ok_total": 0,
                          "failures_total": 0, "offline_serves_total": 0}

    def _transition(self, state: str) -> None:
        """Caller holds the lock."""
        prev = self._state
        if prev == state:
            return
        self._state = state
        rec = self.recorder
        if rec is not None:
            rec.record("control_plane.transition", state=state, prev=prev)
        logger.info("control plane %s -> %s", prev, state)

    def note_ok(self, mirror: bool = False) -> None:
        with self._lock:
            now = self._clock()
            self._last_ok_t = now
            self._last_ok_mirror = bool(mirror)
            self.stats["ok_total"] += 1
            if mirror:
                self.stats["mirror_ok_total"] += 1
            if mirror or now - self._last_fail_t < _DEGRADED_WINDOW_S:
                self._transition(DEGRADED)
            else:
                self._transition(OK)

    def note_failure(self) -> None:
        with self._lock:
            self._last_fail_t = self._clock()
            self.stats["failures_total"] += 1
            self._transition(OFFLINE)

    def note_offline_serve(self) -> None:
        """A pull/keying was served from the pinned cache because every
        endpoint was down — offline, but the data plane kept going."""
        with self._lock:
            self._last_fail_t = self._clock()
            self.stats["offline_serves_total"] += 1
            self._transition(OFFLINE)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def status(self) -> dict:
        with self._lock:
            out = {"state": self._state}
            out.update(self.stats)
            if self._last_ok_t:
                out["last_ok_age_s"] = round(self._clock() - self._last_ok_t, 3)
            if self._last_fail_t:
                out["last_failure_age_s"] = round(
                    self._clock() - self._last_fail_t, 3)
            return out


_health = ControlPlaneHealth()


def health() -> ControlPlaneHealth:
    """The process-wide tracker (one pod = one control-plane view)."""
    return _health
