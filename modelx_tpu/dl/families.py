"""Model-family registry for the serving path: checkpoint tensor names ->
(config inference, partition rules, forward/generate adapters).

The reference stores models without understanding them; the TPU serving
sidecar has to *execute* them, so each supported family contributes:

- ``infer_config(params)``: recover the architecture from tensor shapes
  (no config.json required — the checkpoint is self-describing);
- ``rules``: GSPMD partition rules (dl/sharding.py);
- ``forward(params, tokens, cfg, mesh)`` -> logits/features;
- ``generate`` (causal families only).

``detect(params)`` picks the family from tensor names, mirroring
dl/sharding.infer_family but over loaded params.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Callable

import jax
import numpy as np

from modelx_tpu.dl.sharding import (
    BERT_RULES,
    GEMMA2_RULES,
    GPT2_RULES,
    PHI3_RULES,
    LLAMA_RULES,
    MIXTRAL_RULES,
    QWEN2_RULES,
    Rules,
    infer_family,
)


@dataclasses.dataclass(frozen=True)
class Family:
    name: str
    rules: Rules
    infer_config: Callable[[dict], Any]
    forward: Callable[..., jax.Array]  # (params, tokens, cfg, mesh) -> logits
    generate: Callable[..., jax.Array] | None = None  # causal LMs only
    # ragged-batch decode (params, prompt, row_lens, cfg, mesh, max_new_tokens)
    # -> generated [B, max_new]; cached-decode families only — the serving
    # batcher uses it to coalesce concurrent generate requests
    generate_ragged: Callable[..., jax.Array] | None = None
    # (cfg, mesh) -> (forward-with-cache, init_kv_cache) for streaming decode
    # (models/decode.ChunkedDecoder); cached-decode families only
    decode_fns: Callable[..., tuple] | None = None
    # (cfg, mesh) -> forward over PAGED kv pools (kv_cache = page pools +
    # a block table; ops/paged_attention.py reads them in place) — the
    # continuous engine's fast paged chunk path; None = the engine falls
    # back to its generic dense-gather chunk for this family
    paged_decode_fns: Callable[..., Callable] | None = None


def _shape(params: dict, name: str) -> tuple[int, ...]:
    return tuple(params[name].shape)


def _act_dtype(params: dict, name: str):
    """Activation dtype for a checkpoint: the (float) dtype of its embedding
    weight. A config whose dtype disagrees with the params breaks the cached
    decode path — the KV cache allocates cfg.dtype while k/v arrive in the
    params' compute dtype, and dynamic_update_slice rejects the mismatch.
    Non-float storage (e.g. int8 weight-only quant) computes in bfloat16."""
    import jax.numpy as jnp

    dt = params[name].dtype
    # jnp.issubdtype understands the extended float types (bfloat16 etc.)
    return dt if jnp.issubdtype(dt, jnp.floating) else jnp.bfloat16


# -- llama --------------------------------------------------------------------


def infer_llama_config(params: dict):
    """Recover the architecture from checkpoint tensor shapes."""
    from modelx_tpu.models import llama

    vocab, hidden = _shape(params, "model.embed_tokens.weight")
    layers = 0
    while f"model.layers.{layers}.self_attn.q_proj.weight" in params:
        layers += 1
    q = _shape(params, "model.layers.0.self_attn.q_proj.weight")[0]
    kv = _shape(params, "model.layers.0.self_attn.k_proj.weight")[0]
    inter = _shape(params, "model.layers.0.mlp.gate_proj.weight")[0]
    # head_dim heuristics: big models use 128 (llama/mistral/qwen2-7B+)
    # unless that would leave fewer than 2 kv heads. kv=128 is genuinely
    # ambiguous — MQA-128 (32 q heads x 1 kv head, e.g. q=4096) vs
    # qwen2-0.5B (14 x 64, 2 kv heads, q=896) — so 128 also wins when the
    # checkpoint is clearly big (q//128 >= 8, the pre-qwen2 rule), which
    # keeps MQA llama checkpoints correct while 0.5B-class models (q//128
    # == 7) fall to 64
    if q % 128 == 0 and kv % 128 == 0 and (kv // 128 >= 2 or q // 128 >= 8):
        head_dim = 128
    elif q % 64 == 0 and kv % 64 == 0 and kv // 64 >= 2:
        head_dim = 64
    else:
        head_dim = max(q // 32, 32)
    if hidden <= 512:  # toy checkpoints
        head_dim = 32
    return llama.LlamaConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=inter,
        num_layers=layers,
        num_heads=q // head_dim,
        num_kv_heads=kv // head_dim,
        head_dim=head_dim,
        tie_embeddings="lm_head.weight" not in params,
        dtype=_act_dtype(params, "model.embed_tokens.weight"),
    )


def _llama_forward(params, tokens, cfg, mesh=None):
    from modelx_tpu.models import llama

    return llama.forward(params, tokens, cfg, mesh=mesh)[0]


def _llama_generate(params, tokens, cfg, mesh=None, max_new_tokens=16):
    from modelx_tpu.models import llama

    return llama.greedy_generate(params, tokens, cfg, max_new_tokens=max_new_tokens, mesh=mesh)


def _llama_generate_ragged(params, tokens, row_lens, cfg, mesh=None,
                            max_new_tokens=16, **sampling):
    from modelx_tpu.models import llama

    return llama.ragged_greedy_generate(
        params, tokens, row_lens, cfg, max_new_tokens=max_new_tokens, mesh=mesh,
        **sampling,
    )


def _llama_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import llama

    def fwd(p, t, kv_cache, cache_offset, mesh=mesh):
        return llama.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset, mesh=mesh
        )

    return fwd, (lambda b, max_len: llama.init_kv_cache(cfg, b, max_len))


def _llama_paged_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import llama

    def fwd(p, t, kv_cache, cache_offset, table, mesh=mesh):
        return llama.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset,
            mesh=mesh, paged_table=table,
        )

    return fwd


# -- mixtral ------------------------------------------------------------------


def infer_mixtral_config(params: dict):
    from modelx_tpu.models import mixtral

    vocab, hidden = _shape(params, "model.embed_tokens.weight")
    layers = 0
    while f"model.layers.{layers}.self_attn.q_proj.weight" in params:
        layers += 1
    q = _shape(params, "model.layers.0.self_attn.q_proj.weight")[0]
    kv = _shape(params, "model.layers.0.self_attn.k_proj.weight")[0]
    w1 = "model.layers.0.block_sparse_moe.experts.w1.weight"
    num_experts, inter, _ = _shape(params, w1)
    head_dim = 128 if q % 128 == 0 and q // 128 >= 8 else max(q // 32, 32)
    if hidden <= 512:
        head_dim = 32
    return mixtral.MixtralConfig(
        vocab_size=vocab,
        hidden_size=hidden,
        intermediate_size=inter,
        num_layers=layers,
        num_heads=q // head_dim,
        num_kv_heads=kv // head_dim,
        head_dim=head_dim,
        num_experts=num_experts,
        dtype=_act_dtype(params, "model.embed_tokens.weight"),
    )


def _mixtral_forward(params, tokens, cfg, mesh=None):
    from modelx_tpu.models import mixtral

    return mixtral.forward(params, tokens, cfg, mesh=mesh)[0]


def _mixtral_generate(params, tokens, cfg, mesh=None, max_new_tokens=16):
    from modelx_tpu.models import mixtral

    return mixtral.greedy_generate(
        params, tokens, cfg, max_new_tokens=max_new_tokens, mesh=mesh
    )


def _mixtral_generate_ragged(params, tokens, row_lens, cfg, mesh=None,
                            max_new_tokens=16, **sampling):
    from modelx_tpu.models import mixtral

    return mixtral.ragged_greedy_generate(
        params, tokens, row_lens, cfg, max_new_tokens=max_new_tokens, mesh=mesh,
        **sampling,
    )


def _mixtral_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import mixtral

    def fwd(p, t, kv_cache, cache_offset, mesh=mesh):
        return mixtral.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset, mesh=mesh
        )

    return fwd, (lambda b, max_len: mixtral.init_kv_cache(cfg, b, max_len))


def _mixtral_paged_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import mixtral

    def fwd(p, t, kv_cache, cache_offset, table, mesh=mesh):
        return mixtral.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset,
            mesh=mesh, paged_table=table,
        )

    return fwd


# -- gpt2 ---------------------------------------------------------------------


def infer_gpt2_config(params: dict):
    from modelx_tpu.models import gpt2

    vocab, hidden = _shape(params, "wte.weight")
    n_pos = _shape(params, "wpe.weight")[0]
    layers = 0
    while f"h.{layers}.attn.c_attn.weight" in params:
        layers += 1
    # head count: standard gpt2 uses hidden/64 heads
    num_heads = max(hidden // 64, 1)
    if hidden <= 128:  # toy checkpoints
        num_heads = 4
    return gpt2.GPT2Config(
        vocab_size=vocab, n_positions=n_pos, hidden_size=hidden,
        num_layers=layers, num_heads=num_heads,
        dtype=_act_dtype(params, "wte.weight"),
    )


def infer_qwen2_config(params: dict):
    """Qwen2 = llama's decoder with qkv input biases; same inference plus
    the bias flag and qwen2's constants (rms eps 1e-6, rope theta 1e6 —
    every released Qwen2/2.5 uses these; shapes can't reveal them)."""
    cfg = infer_llama_config(params)
    return dataclasses.replace(cfg, qkv_bias=True, rms_eps=1e-6,
                               rope_theta=1_000_000.0)


# -- phi3 ---------------------------------------------------------------------


def infer_phi3_config(params: dict):
    """Phi-3 fused shapes: qkv rows = q + 2*kv with q == hidden in every
    released dense variant (mini 32x96, medium 40x128). head_dim: medium's
    GQA (kv != hidden rows) means 128; mini's MHA means hidden/32 = 96.
    Returns a llama.LlamaConfig — the module reuses llama's decoder.
    rope_theta=10000 is the 4k variants' value; the 128k variants need
    longrope scaling shapes can't reveal — apply_sidecar_config checks the
    pulled config.json and refuses those instead of mis-serving them."""
    from modelx_tpu.models import llama

    vocab, hidden = _shape(params, "model.embed_tokens.weight")
    layers = 0
    while f"model.layers.{layers}.self_attn.qkv_proj.weight" in params:
        layers += 1
    qkv_rows = _shape(params, "model.layers.0.self_attn.qkv_proj.weight")[0]
    inter = _shape(params, "model.layers.0.mlp.gate_up_proj.weight")[0] // 2
    kv_rows = (qkv_rows - hidden) // 2
    if hidden <= 512:  # toy checkpoints: 4 q heads by convention
        head_dim = max(hidden // 4, 8)
    elif kv_rows != hidden:  # GQA (phi-3-medium): 128 everywhere released
        head_dim = 128
    else:  # MHA (phi-3-mini): 32 heads of hidden/32
        head_dim = hidden // 32
    return llama.LlamaConfig(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
        num_layers=layers, num_heads=hidden // head_dim,
        num_kv_heads=kv_rows // head_dim, head_dim=head_dim,
        rope_theta=10000.0, rms_eps=1e-5, tie_embeddings=False,
        dtype=_act_dtype(params, "model.embed_tokens.weight"),
    )


def _phi3_forward(params, tokens, cfg, mesh=None):
    from modelx_tpu.models import phi3

    return phi3.forward(params, tokens, cfg, mesh=mesh)[0]


def _phi3_generate(params, tokens, cfg, mesh=None, max_new_tokens=16):
    from modelx_tpu.models import phi3

    return phi3.greedy_generate(params, tokens, cfg, max_new_tokens=max_new_tokens, mesh=mesh)


def _phi3_generate_ragged(params, tokens, row_lens, cfg, mesh=None,
                          max_new_tokens=16, **sampling):
    from modelx_tpu.models import phi3

    return phi3.ragged_greedy_generate(
        params, tokens, row_lens, cfg, max_new_tokens=max_new_tokens, mesh=mesh,
        **sampling,
    )


def _phi3_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import phi3

    def fwd(p, t, kv_cache, cache_offset, mesh=mesh):
        return phi3.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset, mesh=mesh
        )

    return fwd, (lambda b, max_len: phi3.init_kv_cache(cfg, b, max_len))


def _phi3_paged_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import phi3

    def fwd(p, t, kv_cache, cache_offset, table, mesh=mesh):
        return phi3.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset,
            mesh=mesh, paged_table=table,
        )

    return fwd


# -- gemma2 -------------------------------------------------------------------


def infer_gemma2_config(params: dict):
    """Gemma2 shapes are llama-like; head_dim is 256 in every released
    checkpoint except 27b (hidden 4608, head_dim 128, query_pre_attn_scalar
    hidden/heads = 144 instead of head_dim). Softcaps and the 4096 sliding
    window are architecture constants shapes can't reveal."""
    from modelx_tpu.models import gemma2

    vocab, hidden = _shape(params, "model.embed_tokens.weight")
    layers = 0
    while f"model.layers.{layers}.self_attn.q_proj.weight" in params:
        layers += 1
    q = _shape(params, "model.layers.0.self_attn.q_proj.weight")[0]
    kv = _shape(params, "model.layers.0.self_attn.k_proj.weight")[0]
    inter = _shape(params, "model.layers.0.mlp.gate_proj.weight")[0]
    if hidden <= 512:  # toy checkpoints
        head_dim = 32
        qpas = float(head_dim)
        window = 16
    elif hidden >= 4608:  # gemma2-27b
        head_dim = 128
        qpas = float(hidden // (q // head_dim))
        window = 4096
    else:  # 2b / 9b
        head_dim = 256
        qpas = 256.0
        window = 4096
    return gemma2.Gemma2Config(
        vocab_size=vocab, hidden_size=hidden, intermediate_size=inter,
        num_layers=layers, num_heads=q // head_dim,
        num_kv_heads=kv // head_dim, head_dim=head_dim,
        query_pre_attn_scalar=qpas, sliding_window=window,
        dtype=_act_dtype(params, "model.embed_tokens.weight"),
    )


def _gemma2_forward(params, tokens, cfg, mesh=None):
    from modelx_tpu.models import gemma2

    return gemma2.forward(params, tokens, cfg, mesh=mesh)[0]


def _gemma2_generate(params, tokens, cfg, mesh=None, max_new_tokens=16):
    from modelx_tpu.models import gemma2

    return gemma2.greedy_generate(params, tokens, cfg, max_new_tokens=max_new_tokens, mesh=mesh)


def _gemma2_generate_ragged(params, tokens, row_lens, cfg, mesh=None,
                            max_new_tokens=16, **sampling):
    from modelx_tpu.models import gemma2

    return gemma2.ragged_greedy_generate(
        params, tokens, row_lens, cfg, max_new_tokens=max_new_tokens, mesh=mesh,
        **sampling,
    )


def _gemma2_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import gemma2

    def fwd(p, t, kv_cache, cache_offset, mesh=mesh):
        return gemma2.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset, mesh=mesh
        )

    return fwd, (lambda b, max_len: gemma2.init_kv_cache(cfg, b, max_len))


def _gemma2_paged_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import gemma2

    def fwd(p, t, kv_cache, cache_offset, table, mesh=mesh):
        return gemma2.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset,
            mesh=mesh, paged_table=table,
        )

    return fwd


def _gpt2_forward(params, tokens, cfg, mesh=None):
    from modelx_tpu.models import gpt2

    return gpt2.forward(params, tokens, cfg)[0]


def _gpt2_generate(params, tokens, cfg, mesh=None, max_new_tokens=16):
    from modelx_tpu.models import gpt2

    return gpt2.greedy_generate(params, tokens, cfg, max_new_tokens=max_new_tokens, mesh=mesh)


def _gpt2_generate_ragged(params, tokens, row_lens, cfg, mesh=None,
                          max_new_tokens=16, **sampling):
    from modelx_tpu.models import gpt2

    return gpt2.ragged_greedy_generate(
        params, tokens, row_lens, cfg, max_new_tokens=max_new_tokens, mesh=mesh,
        **sampling,
    )


def _gpt2_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import gpt2

    def fwd(p, t, kv_cache, cache_offset, mesh=mesh):
        return gpt2.forward(p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset)

    return fwd, (lambda b, max_len: gpt2.init_kv_cache(cfg, b, max_len))


def _gpt2_paged_decode_fns(cfg, mesh=None):
    from modelx_tpu.models import gpt2

    def fwd(p, t, kv_cache, cache_offset, table, mesh=mesh):
        return gpt2.forward(
            p, t, cfg, kv_cache=kv_cache, cache_offset=cache_offset,
            paged_table=table,
        )

    return fwd


# -- bert ---------------------------------------------------------------------


def infer_bert_config(params: dict):
    from modelx_tpu.models import bert

    vocab, hidden = _shape(params, "bert.embeddings.word_embeddings.weight")
    max_pos = _shape(params, "bert.embeddings.position_embeddings.weight")[0]
    type_vocab = _shape(params, "bert.embeddings.token_type_embeddings.weight")[0]
    layers = 0
    while f"bert.encoder.layer.{layers}.attention.self.query.weight" in params:
        layers += 1
    inter = _shape(params, "bert.encoder.layer.0.intermediate.dense.weight")[0]
    num_heads = max(hidden // 64, 1)
    if hidden <= 128:
        num_heads = 4
    return bert.BertConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_heads=num_heads, intermediate_size=inter,
        max_position_embeddings=max_pos, type_vocab_size=type_vocab,
        dtype=_act_dtype(params, "bert.embeddings.word_embeddings.weight"),
    )


def _bert_forward(params, tokens, cfg, mesh=None):
    """Returns the sequence output [B,S,E] (encoder family: 'logits' are
    features, argmax over E is not meaningful but harmless for probes)."""
    from modelx_tpu.models import bert

    return bert.forward(params, tokens, cfg)[0]


FAMILIES: dict[str, Family] = {
    "llama": Family("llama", LLAMA_RULES, infer_llama_config, _llama_forward,
                    _llama_generate, _llama_generate_ragged, _llama_decode_fns,
                    _llama_paged_decode_fns),
    # same decoder implementation as llama — the bias params flow through
    # the param dict, so every llama entry point serves qwen2 unchanged
    "qwen2": Family("qwen2", QWEN2_RULES, infer_qwen2_config, _llama_forward,
                    _llama_generate, _llama_generate_ragged, _llama_decode_fns,
                    _llama_paged_decode_fns),
    "phi3": Family("phi3", PHI3_RULES, infer_phi3_config, _phi3_forward,
                  _phi3_generate, _phi3_generate_ragged, _phi3_decode_fns,
                  _phi3_paged_decode_fns),
    "gemma2": Family("gemma2", GEMMA2_RULES, infer_gemma2_config,
                     _gemma2_forward, _gemma2_generate,
                     _gemma2_generate_ragged, _gemma2_decode_fns,
                     _gemma2_paged_decode_fns),
    "mixtral": Family("mixtral", MIXTRAL_RULES, infer_mixtral_config, _mixtral_forward,
                      _mixtral_generate, _mixtral_generate_ragged, _mixtral_decode_fns,
                      _mixtral_paged_decode_fns),
    "gpt2": Family("gpt2", GPT2_RULES, infer_gpt2_config, _gpt2_forward,
                   _gpt2_generate, _gpt2_generate_ragged, _gpt2_decode_fns,
                   _gpt2_paged_decode_fns),
    "bert": Family("bert", BERT_RULES, infer_bert_config, _bert_forward, None),
}


logger = logging.getLogger("modelx.serve")


def sidecar_config(model_dir: str) -> dict | None:
    """The checkpoint's pulled ``config.json`` (the HF sidecar), if any.
    Shape inference recovers the architecture but NOT the RoPE parameters —
    rope_theta and rope_scaling leave no trace in tensor shapes."""
    try:
        with open(os.path.join(model_dir, "config.json")) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    return raw if isinstance(raw, dict) else None


# rope_scaling schemes that reshape position encoding at EVERY position:
# serving them with plain RoPE is wrong from token 0, so they refuse.
# Other schemes (llama3 / linear / dynamic-ntk) match plain RoPE inside
# the original context window — those warn (degraded long-context) but
# keep previously-deployable checkpoints loadable.
_ROPE_SCALING_REFUSED = ("longrope", "su", "yarn")


def apply_sidecar_config(cfg, sidecar: dict, family_name: str):
    """Reconcile a shape-inferred config with the checkpoint's config.json.

    ``rope_scaling`` is not implemented by this runtime. Schemes that
    change the encoding at every position (longrope/su/yarn — e.g. the
    phi-3-*-128k family) would decode garbage from the first token, so
    those checkpoints are REFUSED instead of silently mis-served
    (infer_phi3_config assumes the unscaled rope_theta=10000 of every 4k
    dense phi-3); window-extension schemes (llama3, linear, dynamic) warn
    and serve, correct within the pre-scaling window. A differing
    ``rope_theta`` is safe to honor: the sidecar's value replaces the
    inferred default, with a warning so the override is visible in logs."""
    scaling = sidecar.get("rope_scaling")
    if scaling:
        stype = (
            scaling.get("type") or scaling.get("rope_type")
            if isinstance(scaling, dict) else scaling
        )
        if not isinstance(stype, str) or stype.lower() in _ROPE_SCALING_REFUSED:
            raise ValueError(
                f"{family_name} checkpoint's config.json declares "
                f"rope_scaling ({stype!r}); this runtime implements "
                "unscaled RoPE only — refusing to mis-serve a long-context "
                "checkpoint (e.g. phi-3-*-128k)"
            )
        logger.warning(
            "%s config.json declares rope_scaling %r: not implemented — "
            "serving is exact only within the pre-scaling context window",
            family_name, stype,
        )
    theta = sidecar.get("rope_theta")
    if theta is not None and hasattr(cfg, "rope_theta"):
        try:
            theta = float(theta)
        except (TypeError, ValueError):
            logger.warning(
                "%s config.json rope_theta=%r is not numeric; keeping the "
                "inferred %s", family_name, theta, cfg.rope_theta,
            )
            return cfg
        if theta != float(cfg.rope_theta):
            logger.warning(
                "%s config.json rope_theta=%s overrides the shape-inferred %s",
                family_name, theta, cfg.rope_theta,
            )
            cfg = dataclasses.replace(cfg, rope_theta=theta)
    return cfg


def detect(tensor_names) -> Family:
    """Family from tensor names; raises for unrecognized checkpoints."""
    name = infer_family(list(tensor_names))
    if not name or name not in FAMILIES:
        raise ValueError(
            f"cannot determine model family from tensors ({list(tensor_names)[:4]}...); "
            f"supported: {sorted(FAMILIES)}"
        )
    return FAMILIES[name]


def abstract_params(infos: dict, rules: Rules | None = None, mesh=None,
                    quantize: str | None = None) -> dict:
    """ShapeDtypeStructs for a checkpoint known only by its header/manifest
    tensor index — everything config inference and AOT compilation need,
    before a single weight byte arrives. ``infos`` values need ``shape`` and
    either ``np_dtype()`` (st.TensorInfo) or ``dtype``. With rules+mesh the
    structs carry the placement shardings, so the compiled program matches
    the arrays the loader will deliver. ``quantize="int8"`` mirrors the
    loader's weight-only quantization: eligible 2-D weights become QTensor
    pytrees of structs (int8 data + f32 per-channel scale), so quantized
    deploys AOT-compile while their (halved) bytes stream — int8 TTFT pays
    max(load, compile), not the sum."""
    from modelx_tpu.dl.sharding import sharding_for

    if quantize not in (None, "int8"):
        raise ValueError(f"unsupported quantize mode {quantize!r}")
    if quantize:
        import numpy as np

        from modelx_tpu.ops import quant as qt
        from jax.sharding import NamedSharding, PartitionSpec

    out = {}
    for name, info in infos.items():
        dt = info.np_dtype() if hasattr(info, "np_dtype") else info.dtype
        sharding = sharding_for(name, rules, mesh) if rules is not None and mesh is not None else None
        shape = tuple(info.shape)
        if (
            quantize == "int8"
            and getattr(info, "members", None) is None
            and len(shape) == 2
            and qt.DEFAULT_ELIGIBLE.search(name) is not None
        ):
            # must mirror loader._quantized exactly: a mismatch compiles a
            # program the delivered params can't call
            scale_sharding = None
            if sharding is not None:
                spec = sharding.spec
                scale_sharding = NamedSharding(
                    mesh, PartitionSpec(spec[0] if len(spec) else None)
                )
            out[name] = qt.QTensor(
                q=jax.ShapeDtypeStruct(shape, np.int8, sharding=sharding),
                scale=jax.ShapeDtypeStruct((shape[0],), np.float32, sharding=scale_sharding),
            )
        else:
            out[name] = jax.ShapeDtypeStruct(shape, dt, sharding=sharding)
    return out


def forward_program_key(family: Family, cfg, mode: str, token_shape: tuple,
                        mesh, param_sds: dict) -> str:
    """The aot_cache key for one (family, cfg, mode, shape, mesh, params)
    program — the single source of key truth shared by precompile_forward,
    precompile_score and the program-store bundler (dl/program_store.py), so
    a published bundle's artifact names always match what a warm boot asks
    the cache for."""
    from modelx_tpu.dl import aot_cache

    return aot_cache.cache_key(
        family.name, cfg, mode, token_shape,
        tuple(mesh.shape.items()) if mesh is not None else None,
        aot_cache.describe_sds(param_sds),
    )


def precompile_forward(family: Family, cfg, param_sds: dict, token_shape: tuple,
                       mesh=None, mode: str = "forward", cache_dir: str = ""):
    """AOT-compile the prefill forward for one token shape from abstract
    params — the weights do not need to exist yet, so a deploy overlaps this
    with the loader's byte streaming and the first request (or first token)
    meets an already-compiled program. Returns the compiled executable;
    call it with (params, tokens) of exactly these shapes/shardings.
    ``mode``: "forward" (logits), "argmax_all" (per-position argmax — the
    serve forward route), "argmax_last" (first decoded token — TTFT).
    ``cache_dir`` reuses a serialized export across processes (dl/aot_cache)
    so a warm pod start skips tracing+lowering entirely."""
    import jax.numpy as jnp

    if mode == "argmax_all":
        def fn(p, t):
            return jnp.argmax(family.forward(p, t, cfg, mesh=mesh), axis=-1)
    elif mode == "argmax_last":
        def fn(p, t):
            return jnp.argmax(family.forward(p, t, cfg, mesh=mesh)[:, -1, :], axis=-1)
    else:
        def fn(p, t):
            return family.forward(p, t, cfg, mesh=mesh)

    tok = jax.ShapeDtypeStruct(token_shape, jnp.int32)
    if cache_dir:
        from modelx_tpu.dl import aot_cache

        key = forward_program_key(family, cfg, mode, token_shape, mesh, param_sds)
        return aot_cache.load_or_compile(fn, (param_sds, tok), cache_dir, key)
    return jax.jit(fn).lower(param_sds, tok).compile()


def precompile_score(family: Family, cfg, param_sds: dict, token_shape: tuple,
                     top_k: int = 0, mesh=None, cache_dir: str = ""):
    """AOT-compile the scoring program (per-token logprobs of the given
    continuations, optional top-k alternatives) for one padded token shape.
    Body must stay identical to what serve.score_logprobs_rows historically
    jitted inline — routing it through here lets the export ride the aot
    cache and the program-store bundle like the forward ladder does.
    Call the result with (params, tokens) of exactly ``token_shape``."""
    import jax.numpy as jnp

    k = int(top_k)

    def fn(params, toks):
        logits = family.forward(params, toks, cfg, mesh=mesh)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)  # [B, Lb, V]
        nxt = jnp.concatenate(
            [toks[:, 1:], jnp.zeros((toks.shape[0], 1), jnp.int32)],
            axis=1,
        )
        chosen = jnp.take_along_axis(
            lp, nxt[..., None], axis=-1
        )[..., 0]  # position j scores token j+1
        if k:
            top_lp, top_id = jax.lax.top_k(lp, k)
            return chosen, top_id, top_lp
        return chosen, None, None

    tok = jax.ShapeDtypeStruct(token_shape, jnp.int32)
    if cache_dir:
        from modelx_tpu.dl import aot_cache

        key = forward_program_key(
            family, cfg, f"score:{int(top_k)}", token_shape, mesh, param_sds
        )
        return aot_cache.load_or_compile(fn, (param_sds, tok), cache_dir, key)
    return jax.jit(fn).lower(param_sds, tok).compile()
