"""LoRA adapter loading: merge PEFT-style adapters into base weights.

The registry stores adapters as ordinary (small) safetensors blobs — a
fine-tune is a few MB next to a multi-GB base model, and content addressing
dedups the base across adapter versions. At serve time the adapter is
merged into the base weights on load (W <- W + (alpha/r)·B@A), so serving
costs exactly what the base costs: no per-token adapter matmuls, no extra
HBM beyond the merge's transient.

Name mapping follows the PEFT safetensors convention:
``base_model.model.<target>.lora_A.weight`` ([r, in]) and ``...lora_B.weight``
([out, r]) merge into ``<target>.weight``. ``adapter_config.json`` beside the
adapter supplies ``lora_alpha``/``r`` when present (scale alpha/r); absent,
the scale is inferred as alpha=r (scale 1.0).

Reference parity: none — the reference stores adapter files opaquely; this
makes them deployable.
"""

from __future__ import annotations

import json
import os
import re

import numpy as np

_LORA_KEY = re.compile(r"^(?:base_model\.model\.)?(.+)\.lora_(A|B)\.weight$")


def parse_adapter_dir(adapter_dir: str) -> tuple[float, dict[str, dict[str, np.ndarray]]]:
    """Read every *.safetensors under ``adapter_dir``; returns
    (scale, {target tensor name: {"A": [r,in], "B": [out,r]}}).

    Unrecognized tensor names (e.g. PEFT ``modules_to_save`` retrained
    weights) are an ERROR, not a skip: silently serving an adapter with
    parts of the fine-tune dropped is worse than refusing to start."""
    import glob

    from modelx_tpu.dl import safetensors as st

    pairs: dict[str, dict[str, np.ndarray]] = {}
    unrecognized: list[str] = []
    paths = sorted(glob.glob(os.path.join(adapter_dir, "*.safetensors")))
    if not paths:
        raise ValueError(f"no safetensors under adapter dir {adapter_dir}")
    for path in paths:
        for name, arr in st.read_tensors(path).items():
            m = _LORA_KEY.match(name)
            if not m:
                unrecognized.append(name)
                continue
            target = m.group(1) + ".weight"
            pairs.setdefault(target, {})[m.group(2)] = arr
    if unrecognized:
        raise ValueError(
            "adapter has non-LoRA tensors this server cannot merge "
            f"(modules_to_save?): {unrecognized[:3]}"
            + ("..." if len(unrecognized) > 3 else "")
        )
    incomplete = [t for t, ab in pairs.items() if set(ab) != {"A", "B"}]
    if incomplete:
        raise ValueError(f"adapter pairs missing A or B for: {incomplete[:3]}")
    if not pairs:
        raise ValueError(f"no lora_A/lora_B tensors found under {adapter_dir}")

    scale = 1.0
    cfg_path = os.path.join(adapter_dir, "adapter_config.json")
    if os.path.isfile(cfg_path):
        with open(cfg_path) as f:
            cfg = json.load(f)
        # per-module ranks/alphas mean a single global alpha/r scale would
        # silently mis-scale some targets — refuse rather than mis-serve
        # (same stance as modules_to_save / rslora above)
        for key in ("rank_pattern", "alpha_pattern"):
            if cfg.get(key):
                raise ValueError(
                    f"adapter_config.json has {key}: per-module LoRA "
                    "scales are not supported (a single global scale "
                    "would silently mis-merge some targets)"
                )
        r = cfg.get("r") or next(iter(pairs.values()))["A"].shape[0]
        mismatched = {
            t: ab["A"].shape[0] for t, ab in pairs.items() if ab["A"].shape[0] != r
        }
        if mismatched:
            raise ValueError(
                f"adapter ranks differ from adapter_config.json r={r}: "
                f"{dict(list(mismatched.items())[:3])} — refusing to merge "
                "with a wrong global scale"
            )
        alpha = cfg.get("lora_alpha", r)
        if cfg.get("use_rslora"):
            # rank-stabilized LoRA scales by alpha/sqrt(r); using alpha/r
            # would quietly serve a mis-scaled fine-tune
            scale = float(alpha) / float(r) ** 0.5
        else:
            scale = float(alpha) / float(r)
    return scale, pairs


def merge_adapter(params: dict, adapter_dir: str) -> dict:
    """Fold the adapter into ``params`` in place-ish (returns the dict).

    Works on sharded ``jax.Array`` params: the per-target delta is tiny
    host math (B@A), and the addition inherits the base weight's sharding.
    Quantized (QTensor) targets are rejected — merge must happen before
    weight-only quantization, not after the precision was dropped.
    """
    import jax.numpy as jnp

    scale, pairs = parse_adapter_dir(adapter_dir)
    missing = [t for t in pairs if t not in params]
    if missing:
        raise ValueError(
            f"adapter targets not in base model: {missing[:3]}"
            + ("..." if len(missing) > 3 else "")
        )
    from modelx_tpu.ops.quant import QTensor

    for target, ab in pairs.items():
        base = params[target]
        if isinstance(base, QTensor) or not hasattr(base, "dtype"):
            raise ValueError(
                f"cannot merge adapter into non-array weight {target!r} "
                "(quantized? merge adapters before --quantize)"
            )
        a = ab["A"].astype(np.float32)
        b = ab["B"].astype(np.float32)
        if b.shape[1] != a.shape[0] or (b.shape[0], a.shape[1]) != tuple(base.shape):
            raise ValueError(
                f"adapter shapes for {target!r} do not match: "
                f"B{b.shape} @ A{a.shape} vs base {tuple(base.shape)}"
            )
        delta = (scale * (b @ a)).astype(np.dtype(base.dtype))
        # sharded base + replicated delta: the sum keeps the base sharding
        params[target] = base + jnp.asarray(delta)
    return params
