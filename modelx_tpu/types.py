"""Core data model: Index / Manifest / Descriptor / BlobLocation.

Reference parity: pkg/types/types.go:20-66. The schema is wire-compatible with
the reference (same JSON keys, same media types) so existing modelx registries
and clients interoperate. TPU-native extensions ride in ``annotations`` — the
extension point the reference explicitly leaves open (types.go:36,39):

- ``modelx.shard.mesh``   (manifest annotation): device-mesh spec, e.g.
  ``"dp=2,tp=4"`` — axis names and sizes of the `jax.sharding.Mesh` the
  checkpoint was laid out for.
- ``modelx.shard.spec``   (blob annotation): JSON map tensor-name ->
  PartitionSpec (list of axis names / null), for safetensors blobs.
- ``modelx.tensor.index`` (blob annotation): JSON map tensor-name ->
  {dtype, shape, data_offsets} — a mirror of the safetensors header so the
  loader can plan ranged reads without fetching the blob first.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from typing import Any, BinaryIO, Iterator

# --- media types (wire-compatible with pkg/client/push.go:17-23) -------------

MediaTypeModelIndexJson = "application/vnd.modelx.model.index.v1.json"
MediaTypeModelManifestJson = "application/vnd.modelx.model.manifest.v1.json"
MediaTypeModelConfigYaml = "application/vnd.modelx.model.config.v1.yaml"
MediaTypeModelFile = "application/vnd.modelx.model.file.v1"
MediaTypeModelDirectoryTarGz = "application/vnd.modelx.model.directory.v1.tar+gzip"
# compiled-program bundle (dl/program_store.py): a deterministic tar of
# serialized jax.export artifacts, attached to a model version as a real
# blob descriptor — sha256 verification, scrub/quarantine, upload markers
# and GC reference tracking all apply to it unchanged
MediaTypeModelProgram = "application/vnd.modelx.program.v1"
# prefix-KV bundle (dl/kv_store.py): a deterministic tar of a hot
# PrefixKVCache entry's leaves, attached to a model version the same way —
# the registry's verification/scrub/GC machinery applies to derived
# serving state without any kvcache-specific registry code
MediaTypeModelKVCache = "application/vnd.modelx.kvcache.v1"

# --- annotation keys ---------------------------------------------------------

AnnotationFileMode = "filemode"  # types.go:13
# TPU-native extensions (see module docstring):
AnnotationShardMesh = "modelx.shard.mesh"
AnnotationShardSpec = "modelx.shard.spec"
AnnotationTensorIndex = "modelx.tensor.index"
# program-bundle environment stamp (jax version / backend / code digest):
# lets a puller pick the matching bundle from the manifest alone — the
# install path re-verifies against the bundle's own meta.json regardless
AnnotationProgramJax = "modelx.program.jax"
AnnotationProgramBackend = "modelx.program.backend"
AnnotationProgramCode = "modelx.program.code"
AnnotationProgramCount = "modelx.program.artifacts"
# the mesh shape ("dp=2,tp=4") the bundle's programs were compiled under:
# part of the bundle compatibility domain — a dp=1 surface must never
# warm-install on a tp=4 pod
AnnotationProgramMesh = "modelx.program.mesh"
# kv-bundle compatibility stamp: code/mesh mirror the program annotations
# (a KV layout is only loadable under the exact code version + GSPMD mesh
# it was captured under); model is the weight content key, tokens the
# prefix length, prefix the keying hash — enough for a puller to match a
# missed prompt against the manifest without fetching any blob bytes
AnnotationKVCode = "modelx.kv.code"
AnnotationKVMesh = "modelx.kv.mesh"
AnnotationKVModel = "modelx.kv.model"
AnnotationKVTokens = "modelx.kv.tokens"
AnnotationKVPrefix = "modelx.kv.prefix"

# --- blob location purposes (types.go:16-19) ---------------------------------

BlobLocationPurposeUpload = "upload"
BlobLocationPurposeDownload = "download"


# --- digest ------------------------------------------------------------------

_DIGEST_RE = re.compile(r"^[a-z0-9]+(?:[.+_-][a-z0-9]+)*:[0-9a-f]{32,}$")


class Digest(str):
    """A content digest in ``algorithm:hex`` form (go-digest compatible).

    Subclasses ``str`` so digests serialize/compare as plain strings, matching
    the reference's `digest.Digest` (an alias of string).
    """

    __slots__ = ()

    @property
    def algorithm(self) -> str:
        return self.partition(":")[0]

    @property
    def hex(self) -> str:
        return self.partition(":")[2]

    def validate(self) -> None:
        if not _DIGEST_RE.match(self):
            raise ValueError(f"invalid digest: {self!r}")

    @classmethod
    def from_bytes(cls, data: bytes) -> "Digest":
        return cls("sha256:" + hashlib.sha256(data).hexdigest())

    @classmethod
    def from_reader(cls, reader: BinaryIO, chunk_size: int = 4 * 1024 * 1024) -> "Digest":
        """Streaming sha256 (reference: pkg/client/push.go:149-161)."""
        h = hashlib.sha256()
        while chunk := reader.read(chunk_size):
            h.update(chunk)
        return cls("sha256:" + h.hexdigest())

    @classmethod
    def from_file(cls, path: str, chunk_size: int = 4 * 1024 * 1024) -> "Digest":
        try:
            # GIL-free native hashing so concurrent blob pushes/pulls don't
            # serialize on the interpreter (modelx_tpu/native/modelx_io.cc)
            from modelx_tpu import native

            hexdigest = native.sha256_file(path)
            if hexdigest is not None:
                return cls("sha256:" + hexdigest)
        except (OSError, ImportError):
            pass  # engine unavailable/unreadable: surface the python path's error
        with open(path, "rb") as f:
            return cls.from_reader(f, chunk_size)


def _drop_empty(d: dict[str, Any]) -> dict[str, Any]:
    """omitempty semantics: drop None / '' / 0 / {} / [] like Go's json tags."""
    return {k: v for k, v in d.items() if v not in (None, "", 0, {}, [])}


# --- descriptors -------------------------------------------------------------


@dataclasses.dataclass
class Descriptor:
    """types.go:28-37. Describes one blob (or manifest, inside an Index)."""

    name: str = ""
    media_type: str = ""
    digest: str = ""
    size: int = 0
    mode: int = 0  # unix file mode bits (reference stores os.FileMode)
    urls: list[str] = dataclasses.field(default_factory=list)
    modified: str = ""  # RFC3339 timestamp; empty == omitted
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"name": self.name}
        out.update(
            _drop_empty(
                {
                    "mediaType": self.media_type,
                    "digest": self.digest,
                    "size": self.size,
                    "mode": self.mode,
                    "urls": self.urls,
                    "modified": self.modified,
                    "annotations": self.annotations,
                }
            )
        )
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Descriptor":
        if not isinstance(d, dict):
            raise ValueError(f"descriptor must be an object, got {type(d).__name__}")
        return cls(
            name=d.get("name", ""),
            media_type=d.get("mediaType", ""),
            digest=d.get("digest", ""),
            size=int(d.get("size", 0) or 0),
            mode=int(d.get("mode", 0) or 0),
            urls=list(d.get("urls", []) or []),
            modified=d.get("modified", "") or "",
            annotations=dict(d.get("annotations", {}) or {}),
        )


@dataclasses.dataclass
class Index:
    """types.go:53-58. Per-repo index (manifests = versions) or the global
    index (manifests = repositories)."""

    schema_version: int = 1
    media_type: str = MediaTypeModelIndexJson
    manifests: list[Descriptor] = dataclasses.field(default_factory=list)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"schemaVersion": self.schema_version}
        out.update(_drop_empty({"mediaType": self.media_type}))
        out["manifests"] = [m.to_json() for m in self.manifests]
        out.update(_drop_empty({"annotations": self.annotations}))
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Index":
        return cls(
            schema_version=int(d.get("schemaVersion", 1) or 1),
            media_type=d.get("mediaType", "") or "",
            manifests=[Descriptor.from_json(m) for m in d.get("manifests", []) or []],
            annotations=dict(d.get("annotations", {}) or {}),
        )

    def encode(self) -> bytes:
        return canonical_json(self.to_json())

    @classmethod
    def decode(cls, data: bytes) -> "Index":
        return cls.from_json(json.loads(data))


@dataclasses.dataclass
class Manifest:
    """types.go:60-66. One model version: config descriptor + blob list."""

    schema_version: int = 1
    media_type: str = MediaTypeModelManifestJson
    config: Descriptor = dataclasses.field(default_factory=Descriptor)
    blobs: list[Descriptor] = dataclasses.field(default_factory=list)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {"schemaVersion": self.schema_version}
        out.update(_drop_empty({"mediaType": self.media_type}))
        out["config"] = self.config.to_json()
        out["blobs"] = [b.to_json() for b in self.blobs]
        out.update(_drop_empty({"annotations": self.annotations}))
        return out

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "Manifest":
        if not isinstance(d, dict):
            raise ValueError(f"manifest must be an object, got {type(d).__name__}")
        config = d.get("config")
        if config is None:
            config = {}
        blobs = d.get("blobs")
        if blobs is None:
            blobs = []
        if not isinstance(blobs, list):
            raise ValueError("manifest blobs must be a list")
        return cls(
            schema_version=int(d.get("schemaVersion", 1) or 1),
            media_type=d.get("mediaType", "") or "",
            config=Descriptor.from_json(config),
            blobs=[Descriptor.from_json(b) for b in blobs],
            annotations=dict(d.get("annotations", {}) or {}),
        )

    def encode(self) -> bytes:
        return canonical_json(self.to_json())

    @classmethod
    def decode(cls, data: bytes) -> "Manifest":
        return cls.from_json(json.loads(data))

    def all_descriptors(self) -> Iterator[Descriptor]:
        if self.config.name or self.config.digest:
            yield self.config
        yield from self.blobs


@dataclasses.dataclass
class BlobLocation:
    """types.go:20-26. Tells the client *where/how* to move blob bytes:
    provider selects a client-side extension (e.g. ``s3``), properties carry
    presigned URLs etc. The pluggable-protocol seam of the whole design."""

    provider: str = ""
    purpose: str = ""
    properties: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        return _drop_empty(
            {"provider": self.provider, "purpose": self.purpose, "properties": self.properties}
        )

    @classmethod
    def from_json(cls, d: dict[str, Any]) -> "BlobLocation":
        return cls(
            provider=d.get("provider", "") or "",
            purpose=d.get("purpose", "") or "",
            properties=dict(d.get("properties", {}) or {}),
        )


def canonical_json(obj: Any) -> bytes:
    """Deterministic JSON encoding (sorted keys, no whitespace).

    The reference relies on Go's deterministic struct-order marshaling for
    stable index/manifest bytes; we get determinism via sorted keys instead.
    """
    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def sort_descriptors(descs: list[Descriptor]) -> list[Descriptor]:
    """types.go:49-51 SortDescriptorName."""
    return sorted(descs, key=lambda d: d.name)
