"""Deterministic test harnesses shipped with the package (fault injection
lives here so env-gated production chaos drills and the test suite share
one implementation)."""
