"""Deterministic fault injection for the storage and serving paths.

The codebase already treats transient faults as EXPECTED on the storage
path (loader retry x3, governor backoff, extension retries) and — with
engine supervision — on the serving path too. This module makes those
faults reproducible on demand: a seeded ``FaultPlan`` holds per-operation
schedules (error / latency / truncation / short-read), and thin wrappers
apply them to the three seams the framework exposes:

- ``FaultyByteSource`` wraps any loader ``ByteSource`` (dl/loader.py);
- ``wrap_dispatch`` wraps a compiled engine program (the continuous
  engine's chunk/admit dispatches) so a crash lands at an exact call index;
- ``tests/fake_s3.py`` / ``tests/fake_gcs.py`` accept a plan directly
  (server-side 500s and mid-body truncation for blob-store traffic);
- ``FaultyFSProvider`` wraps any registry ``FSProvider`` with crash-point
  injection: abort before ``fs.put`` (nothing written) or mid-put (a TORN
  object commits, then :class:`InjectedCrash`), plus scheduled errors and
  latency on every provider op — the registry torn-write/scrub drills;
- ``FSRegistryStore(fault_plan=...)`` fires ``store.manifest_persisted``
  between manifest persist and index refresh, so stale-index recovery is
  a deterministic test;
- ``PodKillSwitch`` hard-kills a live serving pod's HTTP server (listener
  closed, live connections RST) — the fleet router's pod-death drills:
  mid-stream death must surface typed, failover must cover the rest;
- ``RegistryKillSwitch`` does the same to a RegistryServer and adds
  brownout modes (503 storms, accept-path hangs, mid-body truncation) —
  the control-plane outage drills: pods must keep serving from pinned
  manifests and local blobs while the registry is down.

Determinism: schedules are either explicit call indices (``errors_at``)
or drawn once per op from ``random.Random(seed ^ crc(op))`` at rule-add
time (``error_rate``) — the Nth call to an op always sees the same
verdict, independent of wall clock or thread interleaving (a lock orders
the counter).

Production use is ENV-GATED and default OFF: ``MODELX_FAULT_PLAN`` holds
inline JSON (or ``@/path/to/plan.json``) and ``from_env()`` returns None
unless it is set — the engine and loader consult it at construction, so
an unset env costs one getenv. Example:

    MODELX_FAULT_PLAN='{"seed": 7, "rules": [
        {"op": "engine.dispatch", "errors_at": [100], "error": "chaos"}]}'
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import zlib

ENV_VAR = "MODELX_FAULT_PLAN"


class Action:
    """What one call to ``fire(op)`` must do: sleep ``latency_s``, then
    raise ``error`` (if set); ``keep_bytes`` (when >= 0) tells byte-moving
    wrappers to truncate / short-read the payload instead."""

    __slots__ = ("error", "latency_s", "keep_bytes")

    def __init__(self) -> None:
        self.error: BaseException | None = None
        self.latency_s = 0.0
        self.keep_bytes = -1

    @property
    def clean(self) -> bool:
        return self.error is None and not self.latency_s and self.keep_bytes < 0


class _Rule:
    __slots__ = ("errors_at", "error", "latency_at", "latency_s",
                 "truncate_at", "keep_bytes")

    def __init__(self, errors_at, error, latency_at, latency_s,
                 truncate_at, keep_bytes) -> None:
        self.errors_at = frozenset(errors_at)
        self.error = error
        self.latency_at = frozenset(latency_at)
        self.latency_s = latency_s
        self.truncate_at = frozenset(truncate_at)
        self.keep_bytes = keep_bytes


def _freshen(err: BaseException) -> BaseException:
    """A fresh exception per raise: re-raising one instance accumulates
    tracebacks and couples unrelated call sites."""
    try:
        return type(err)(*err.args)
    except Exception:
        return RuntimeError(f"injected fault: {err}")


class FaultPlan:
    """Seeded, deterministic per-operation fault schedules. Thread-safe:
    ops are counted under a lock, so the Nth call to an op sees the same
    verdict whatever the thread interleaving."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self._rules: dict[str, list[_Rule]] = {}
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    # -- schedule construction ------------------------------------------------

    def add(self, op: str, *, errors_at=(), error: BaseException | None = None,
            error_rate: float = 0.0, horizon: int = 256,
            latency_at=(), latency_s: float = 0.0,
            truncate_at=(), keep_bytes: int = 0) -> "FaultPlan":
        """Add one rule for ``op``. ``errors_at``/``latency_at``/
        ``truncate_at`` are 0-based call indices; ``error_rate`` draws a
        deterministic error schedule over the first ``horizon`` calls from
        the plan's seed (the same (seed, op, rate) always yields the same
        indices). Returns self for chaining."""
        errors_at = set(errors_at)
        if error_rate > 0.0:
            rng = random.Random(self.seed ^ zlib.crc32(op.encode()))
            errors_at |= {i for i in range(horizon) if rng.random() < error_rate}
        rule = _Rule(errors_at, error or OSError(f"injected fault on {op}"),
                     latency_at, float(latency_s), truncate_at, int(keep_bytes))
        with self._lock:
            self._rules.setdefault(op, []).append(rule)
        return self

    def has(self, op: str) -> bool:
        return op in self._rules

    def count(self, op: str) -> int:
        """Calls to ``op`` so far (observability for tests/drills)."""
        with self._lock:
            return self._counts.get(op, 0)

    # -- firing ---------------------------------------------------------------

    def fire(self, op: str) -> Action:
        """Count one call to ``op`` and return its scheduled action."""
        with self._lock:
            i = self._counts.get(op, 0)
            self._counts[op] = i + 1
            act = Action()
            for rule in self._rules.get(op, ()):
                if i in rule.latency_at:
                    act.latency_s = max(act.latency_s, rule.latency_s)
                if i in rule.truncate_at:
                    act.keep_bytes = rule.keep_bytes
                if i in rule.errors_at and act.error is None:
                    act.error = _freshen(rule.error)
            return act

    def maybe_fail(self, op: str) -> None:
        """Apply ``op``'s scheduled latency + error (the wrapper shape for
        call-through seams like engine dispatch)."""
        act = self.fire(op)
        if act.latency_s:
            time.sleep(act.latency_s)
        if act.error is not None:
            raise act.error


# -- seam wrappers -------------------------------------------------------------


class InjectedCrash(RuntimeError):
    """A deterministic 'host died here' stand-in. Raised at a scheduled
    point, it aborts the in-flight operation exactly where a crash would;
    the drill then rebuilds the store over the same underlying provider to
    model a process restart and asserts recovery (torn-write quarantine,
    stale-index rebuild, marker-protected GC)."""


class FaultyFSProvider:
    """Wrap any registry ``FSProvider`` with a seeded :class:`FaultPlan`.

    Ops fired (0-based call indices, per plan semantics): ``fs.put``,
    ``fs.get``, ``fs.stat``, ``fs.remove``, ``fs.exists``, ``fs.list``.
    Special ``fs.put`` behaviors:

    - an error schedule raises BEFORE the inner put — nothing written
      (crash before the write);
    - a truncation schedule (``truncate_at``/``keep_bytes``) COMMITS the
      torn prefix at the destination path and then raises
      :class:`InjectedCrash` — the torn-write shape a non-atomic backend
      (or a crash on a store without fsync-before-rename) produces. This
      is what the scrub/quarantine drills feed on.

    Unlike ``FaultInjectionFSProvider`` (callback-driven), schedules here
    are seeded and index-exact, so crash drills replay byte-identically.
    """

    def __init__(self, inner, plan: FaultPlan, prefix: str = "fs") -> None:
        self.inner = inner
        self.plan = plan
        self.prefix = prefix

    def _op(self, name: str) -> str:
        return f"{self.prefix}.{name}"

    def put(self, path: str, content, size: int = -1, content_type: str = "") -> None:
        act = self.plan.fire(self._op("put"))
        if act.latency_s:
            time.sleep(act.latency_s)
        if act.error is not None:
            raise act.error
        if act.keep_bytes >= 0:
            torn = content.read()[: act.keep_bytes]
            import io as _io

            self.inner.put(path, _io.BytesIO(torn), len(torn), content_type)
            raise InjectedCrash(
                f"torn write: {len(torn)} bytes committed at {path}"
            )
        self.inner.put(path, content, size, content_type)

    def get(self, path: str, offset: int = 0, length: int = -1):
        self.plan.maybe_fail(self._op("get"))
        return self.inner.get(path, offset, length)

    def stat(self, path: str):
        self.plan.maybe_fail(self._op("stat"))
        return self.inner.stat(path)

    def remove(self, path: str) -> None:
        self.plan.maybe_fail(self._op("remove"))
        self.inner.remove(path)

    def exists(self, path: str) -> bool:
        self.plan.maybe_fail(self._op("exists"))
        return self.inner.exists(path)

    def list(self, prefix: str, recursive: bool = False):
        self.plan.maybe_fail(self._op("list"))
        return self.inner.list(prefix, recursive)

    def __getattr__(self, name):
        # pass through provider extras (e.g. LocalFSProvider.local_path)
        return getattr(self.inner, name)


class PodKillSwitch:
    """Abrupt pod death for fleet-router drills (PR 8) — and, with a
    ``sset``, its opposite: coordinated drain (ISSUE 12).

    A clean ``httpd.shutdown()`` lets in-flight handlers FINISH — the
    opposite of a crash. This switch models the real thing: every accepted
    connection socket is tracked, and ``kill()`` closes the listener and
    severs every live connection (``shutdown(SHUT_RDWR)`` — a plain
    ``close()`` would only drop a reference while the handler's
    rfile/wfile keep the fd alive), so a client mid-stream sees a severed
    TCP stream — truncated chunked body, no terminator — not a graceful
    error event, and new connections are refused.

    ``drain()`` is the coordinated path the same drills must also cover:
    what SIGTERM does to a real pod (serve_main's handler), done to an
    in-process pod — ``sset.draining`` flips, ``/healthz`` answers 503
    ``{"status": "draining"}``, admission stops, live streams keep
    flowing so the fleet router can hand them off token-exactly.

    Seeded scheduling composes with :class:`FaultPlan`: drive the kill
    (or drain) from an exact call index by firing an op per relayed
    chunk and calling ``kill()``/``drain()`` when the scheduled error
    lands (see ``fire_kills``/``fire_drain``); the drill replays
    byte-identically.
    """

    def __init__(self, httpd, sset=None) -> None:
        self._httpd = httpd
        self._sset = sset
        self.draining = False
        self._conns: list = []
        self._lock = threading.Lock()
        self.killed = False
        orig_get_request = httpd.get_request

        def get_request():
            sock, addr = orig_get_request()
            with self._lock:
                self._conns.append(sock)
            return sock, addr

        httpd.get_request = get_request

    def kill(self) -> None:
        """Idempotent hard death: refuse new connections, sever live ones
        mid-whatever-they-were-doing."""
        import socket as _socket

        with self._lock:
            if self.killed:
                return
            self.killed = True
            conns = list(self._conns)
        try:
            self._httpd.socket.close()
        except OSError:
            pass  # already closed: the death is what matters
        for sock in conns:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass  # connection already gone
            try:
                sock.close()
            except OSError:
                pass

    def fire_kills(self, plan: FaultPlan, op: str = "pod.kill"):
        """A per-event hook: call the returned function once per relayed
        chunk/request; when the plan schedules an error at that index the
        pod dies THERE. Returns True when the kill fired."""

        def hook() -> bool:
            act = plan.fire(op)
            if act.latency_s:
                time.sleep(act.latency_s)
            if act.error is not None:
                self.kill()
                return True
            return False

        return hook

    def drain(self) -> None:
        """Coordinated drain: what serve_main's SIGTERM handler does,
        applied to an in-process pod. Idempotent; requires the switch to
        have been built with the pod's ServerSet."""
        if self._sset is None:
            raise RuntimeError("PodKillSwitch needs sset= to drain")
        self.draining = True
        self._sset.draining = True

    def fire_drain(self, plan: FaultPlan, op: str = "pod.drain"):
        """Like ``fire_kills`` but the scheduled event DRAINS the pod
        instead of killing it — drills cover both the crash and the
        coordinated hand-off path. Returns True when the drain fired."""

        def hook() -> bool:
            act = plan.fire(op)
            if act.latency_s:
                time.sleep(act.latency_s)
            if act.error is not None:
                self.drain()
                return True
            return False

        return hook


class RegistryKillSwitch:
    """Registry death and brownout for control-plane drills (PR 19).

    Hard-down is the PodKillSwitch move applied to a RegistryServer:
    ``kill()`` closes the listener and severs every live connection, so
    in-flight manifest/blob requests die mid-stream and new connections
    are refused. A *restart* is modeled by constructing a fresh
    RegistryServer over the SAME store on the SAME port (the HTTP server
    sets ``allow_reuse_address``) — what the chaos soak does to assert
    the publish outbox drains after recovery.

    Brownout rides a seeded :class:`FaultPlan` fired once per ACCEPTED
    connection (op ``registry.accept``, 0-based indices):

    - an error schedule answers the connection with a raw ``503`` +
      ``Retry-After`` and closes it — the 50x storm a dying control
      plane emits (clients must back off per endpoint, then fail over);
    - a latency schedule sleeps in the accept path — the hang shape,
      surfaced to clients at their connect/read timeout
      (``--request-timeout``) granularity;
    - a truncation schedule lets the handler start responding, then
      severs the connection ``truncate_delay_s`` later — mid-body
      truncation, the torn blob stream digest verification must catch.

    Schedules replay byte-identically (the plan counts accepts under its
    lock); a switch with no plan is inert until ``kill()``.
    """

    OP = "registry.accept"

    def __init__(self, server, plan: FaultPlan | None = None,
                 truncate_delay_s: float = 0.01) -> None:
        self._httpd = server.httpd if hasattr(server, "httpd") else server
        self.plan = plan
        self.truncate_delay_s = float(truncate_delay_s)
        self._conns: list = []
        self._lock = threading.Lock()
        self.killed = False
        self.storms = 0  # connections answered with the injected 503
        orig_get_request = self._httpd.get_request

        def get_request():
            sock, addr = orig_get_request()
            with self._lock:
                self._conns.append(sock)
            if self.plan is not None:
                act = self.plan.fire(self.OP)
                if act.latency_s:
                    # brownout hang: the accept loop stalls, clients wait
                    # out their own timeouts
                    time.sleep(act.latency_s)
                if act.error is not None:
                    with self._lock:
                        self.storms += 1
                    try:
                        sock.sendall(
                            b"HTTP/1.1 503 Service Unavailable\r\n"
                            b"Retry-After: 1\r\nContent-Length: 0\r\n"
                            b"Connection: close\r\n\r\n"
                        )
                    except OSError:
                        pass  # client already gone; the refusal stands
                    self._sever(sock)
                    # swallowed by BaseServer._handle_request_noblock: the
                    # serve loop continues, this connection never reaches
                    # a handler
                    raise OSError("injected 503 storm")
                if act.keep_bytes >= 0:
                    t = threading.Timer(self.truncate_delay_s,
                                        self._sever, args=(sock,))
                    t.daemon = True
                    t.start()
            return sock, addr

        self._httpd.get_request = get_request

    @staticmethod
    def _sever(sock) -> None:
        import socket as _socket

        try:
            sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass  # connection already gone
        try:
            sock.close()
        except OSError:
            pass

    def kill(self) -> None:
        """Idempotent hard death: refuse new connections, sever live
        ones mid-stream."""
        with self._lock:
            if self.killed:
                return
            self.killed = True
            conns = list(self._conns)
        try:
            self._httpd.socket.close()
        except OSError:
            pass  # already closed: the death is what matters
        for sock in conns:
            self._sever(sock)


def wrap_dispatch(fn, plan: FaultPlan, op: str = "engine.dispatch"):
    """Wrap a compiled dispatch callable (e.g. the continuous engine's
    chunk program): scheduled latency/errors fire BEFORE the real call, so
    a crash at call index N never half-applies device state."""

    def wrapped(*args, **kwargs):
        plan.maybe_fail(op)
        return fn(*args, **kwargs)

    wrapped.__wrapped__ = fn
    return wrapped


class FaultyByteSource:
    """A loader ``ByteSource`` with scheduled faults. Errors surface as
    OSError (what the loader's ``_read_with_retry`` treats as transient);
    a truncation schedule performs a SHORT READ — the head of the range
    lands in the caller's buffer, then the read fails like a dropped
    connection, exercising partial-spool recovery paths."""

    def __init__(self, source, plan: FaultPlan, op: str = "loader.read") -> None:
        self._source = source
        self.plan = plan
        self.op = op

    def read_range(self, offset: int, length: int, out=None):
        act = self.plan.fire(self.op)
        if act.latency_s:
            time.sleep(act.latency_s)
        if act.error is not None:
            raise act.error
        if 0 <= act.keep_bytes < length:
            if act.keep_bytes and out is not None:
                self._source.read_range(offset, act.keep_bytes,
                                        memoryview(out)[: act.keep_bytes])
            raise OSError(
                f"injected short read: {act.keep_bytes}/{length} bytes at {offset}"
            )
        return self._source.read_range(offset, length, out)

    def size(self) -> int:
        return self._source.size()

    def close(self) -> None:
        close = getattr(self._source, "close", None)
        if close is not None:
            close()


# -- env gating ----------------------------------------------------------------


def from_env(env_var: str = ENV_VAR) -> FaultPlan | None:
    """Build a plan from ``MODELX_FAULT_PLAN`` (inline JSON or ``@path``);
    None when unset — the default-off gate every production seam uses."""
    spec = os.environ.get(env_var, "")
    if not spec:
        return None
    if spec.startswith("@"):
        with open(spec[1:], encoding="utf-8") as f:
            spec = f.read()
    d = json.loads(spec)
    plan = FaultPlan(seed=int(d.get("seed", 0)))
    for r in d.get("rules", ()):
        err: BaseException | None = None
        if r.get("crash"):
            err = InjectedCrash(r.get("error", "injected crash"))
        elif r.get("error"):
            err = OSError(r["error"])
        plan.add(
            r["op"],
            errors_at=r.get("errors_at", ()),
            error=err,
            error_rate=float(r.get("error_rate", 0.0)),
            horizon=int(r.get("horizon", 256)),
            latency_at=r.get("latency_at", ()),
            latency_s=float(r.get("latency_s", 0.0)),
            truncate_at=r.get("truncate_at", ()),
            keep_bytes=int(r.get("keep_bytes", 0)),
        )
    return plan
