"""Pipeline parallelism over the ``pp`` mesh axis.

The GPipe-style collective recipe, written the TPU/JAX way rather than as a
torch scheduler: per-layer params are *stacked* along a leading L axis and
sharded over ``pp`` (each rank holds its contiguous block of layers); the
pipeline itself is a ``shard_map`` over ``pp`` in which every step each rank
applies its stage (a ``lax.scan`` over its local layers) and rotates
activations one hop around the ring with ``ppermute`` — neighbor-only ICI
traffic, static shapes, no host scheduler. Microbatches enter at rank 0 and
results drain from the last rank; the loop runs M + P - 1 steps (the
classic bubble). ``lax.fori_loop`` with static bounds lowers to ``scan`` so
the whole pipeline is reverse-differentiable and a pipelined *training*
step works with plain ``jax.grad``.

The reference registry has no model execution at all (SURVEY §2.2); this
module is part of the TPU serve/train path the build brief makes
first-class ("real tp/pp/dp/sp/ep shardings").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from modelx_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from modelx_tpu.models import llama


_ALL_SUFFIXES = llama.LAYER_PARAM_SUFFIXES + llama.BIAS_SUFFIXES


def stack_layer_params(params: dict[str, jax.Array], num_layers: int) -> dict[str, jax.Array]:
    """Fold "model.layers.N.<suffix>" params into stacked [L, ...] arrays
    keyed by suffix (qwen2's optional qkv biases included when present).
    Non-layer params pass through under their own names."""
    out: dict[str, jax.Array] = {
        name: v for name, v in params.items() if not name.startswith("model.layers.")
    }
    for suffix in _ALL_SUFFIXES:
        if f"model.layers.0.{suffix}" not in params:
            continue
        out[suffix] = jnp.stack(
            [params[f"model.layers.{i}.{suffix}"] for i in range(num_layers)]
        )
    return out


def unstack_layer_params(stacked: dict[str, jax.Array], num_layers: int) -> dict[str, jax.Array]:
    """Inverse of stack_layer_params."""
    out = {k: v for k, v in stacked.items() if k not in _ALL_SUFFIXES}
    for suffix in _ALL_SUFFIXES:
        if suffix not in stacked:
            continue
        for i in range(num_layers):
            out[f"model.layers.{i}.{suffix}"] = stacked[suffix][i]
    return out


def stacked_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """Shardings for a stacked param dict: layers over pp, per-layer specs
    derived from the canonical rules (so tp layout can't drift). QWEN2_RULES
    is LLAMA_RULES plus the qkv-bias specs; extra entries for params a dict
    doesn't have are simply unused."""
    from modelx_tpu.dl.sharding import QWEN2_RULES, clean_spec, spec_for

    sh = {}
    for name in ("model.embed_tokens.weight", "model.norm.weight", "lm_head.weight"):
        sh[name] = NamedSharding(mesh, clean_spec(spec_for(name, QWEN2_RULES), mesh))
    for suffix in _ALL_SUFFIXES:
        spec = P("pp", *spec_for(suffix, QWEN2_RULES))
        sh[suffix] = NamedSharding(mesh, clean_spec(spec, mesh))
    return sh


def pipeline_forward(
    stacked: dict[str, jax.Array],
    tokens: jax.Array,
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    num_microbatches: int | None = None,
) -> jax.Array:
    """Pipelined llama forward. ``stacked`` from :func:`stack_layer_params`
    (layer arrays sharded over ``pp``). tokens: [B, S]; B must divide by
    num_microbatches (default: pp size). Returns logits [B, S, V]."""
    pp = mesh.shape["pp"]
    m = num_microbatches or pp
    b, s = tokens.shape
    if b % m:
        raise ValueError(f"batch {b} not divisible into {m} microbatches")
    mb = b // m

    positions = jnp.arange(s)[None, :]  # [1, S]; broadcasts inside _rope
    ctx = llama.ShardingCtx(None)  # inside shard_map: no GSPMD constraints

    x = jnp.take(stacked["model.embed_tokens.weight"], tokens, axis=0).astype(cfg.dtype)
    x_mb = x.reshape(m, mb, s, cfg.hidden_size)

    layer_stack = {k: stacked[k] for k in _ALL_SUFFIXES if k in stacked}

    def stage_scan(local_layers, h):
        def body(h, lp):
            h, _ = llama.decoder_layer(lp, h, positions, cfg, ctx, attention_impl="reference")
            return h, None

        h, _ = jax.lax.scan(body, h, local_layers)
        return h

    def pipelined(local_layers, x_mb):
        rank = jax.lax.axis_index("pp")
        steps = m + pp - 1
        state = jnp.zeros_like(x_mb[0])
        outputs = jnp.zeros_like(x_mb)

        def step(t, carry):
            state, outputs = carry
            feed = x_mb[jnp.minimum(t, m - 1)]
            inp = jnp.where(rank == 0, feed, state)
            out = stage_scan(local_layers, inp)
            # the last rank drains microbatch t-(pp-1) once the fill ends
            idx = t - (pp - 1)
            upd = jax.lax.dynamic_update_slice(
                outputs, out[None], (jnp.maximum(idx, 0), 0, 0, 0)
            )
            take = (idx >= 0) & (rank == pp - 1)
            outputs = jnp.where(take, upd, outputs)
            state = jax.lax.ppermute(out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return state, outputs

        _state, outputs = jax.lax.fori_loop(0, steps, step, (state, outputs))
        # results live on the last rank; broadcast around the ring
        return jax.lax.psum(
            jnp.where(rank == pp - 1, outputs, jnp.zeros_like(outputs)), "pp"
        )

    # layers shard over pp; the microbatch's batch dim shards over dp (tp
    # inside the stage would need manual psum in shard_map — the pipelined
    # path composes pp×dp and leaves tp to the GSPMD forward).
    layer_spec = jax.tree.map(lambda _: P("pp"), layer_stack)
    batch_spec = P(None, "dp" if "dp" in mesh.axis_names else None)
    x_mb = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(layer_spec, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(layer_stack, x_mb)

    x = x_mb.reshape(b, s, cfg.hidden_size)
    x = llama._rms_norm(x, stacked["model.norm.weight"], cfg.rms_eps)
    head = stacked.get("lm_head.weight", stacked["model.embed_tokens.weight"])
    from modelx_tpu.ops.nn import linear as _linear

    return _linear(x, head)


def make_pipeline_train_step(cfg: llama.LlamaConfig, optimizer, mesh: Mesh, num_microbatches: int | None = None):
    """train_step(stacked_params, opt_state, batch) -> (params, opt_state, loss)
    where the forward is the pp pipeline above and grads flow back through
    the ppermute ring (fori_loop lowers to scan, so reverse-mode works)."""
    from modelx_tpu.models.train import make_train_step

    return make_train_step(
        cfg,
        optimizer,
        mesh=mesh,
        forward_fn=lambda stacked, tokens: pipeline_forward(
            stacked, tokens, cfg, mesh, num_microbatches
        ),
    )
