"""Device-mesh construction and sharding conventions.

The registry side of the framework stores mesh/layout *metadata*
(SURVEY.md §2.2: DP/TP/... become mesh-axis metadata the registry stores and
the loader honors); this package is where that metadata becomes a live
`jax.sharding.Mesh` and `NamedSharding`s.
"""

from modelx_tpu.parallel.mesh import (
    AXIS_BATCH,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_SEQUENCE,
    AXIS_STAGE,
    MeshSpec,
    make_mesh,
    parse_mesh_spec,
)

__all__ = [
    "AXIS_BATCH",
    "AXIS_EXPERT",
    "AXIS_MODEL",
    "AXIS_SEQUENCE",
    "AXIS_STAGE",
    "MeshSpec",
    "make_mesh",
    "parse_mesh_spec",
]
