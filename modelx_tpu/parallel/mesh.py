"""Mesh spec parsing and `jax.sharding.Mesh` construction.

Mesh specs are the string form stored in manifests / modelx.yaml
(``modelx.shard.mesh`` annotation), e.g. ``"dp=2,tp=4"`` or
``"dp=1,sp=2,tp=4"``. Axis-name conventions (scaling-book vocabulary):

    dp — data parallel (batch)           ep — expert parallel (MoE)
    tp — tensor/model parallel           pp — pipeline stage parallel
    sp — sequence/context parallel       fsdp — fully-sharded data parallel

A size of -1 means "absorb the remaining devices" (like a reshape).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_BATCH = "dp"
AXIS_MODEL = "tp"
AXIS_SEQUENCE = "sp"
AXIS_EXPERT = "ep"
AXIS_STAGE = "pp"
AXIS_FSDP = "fsdp"

KNOWN_AXES = (AXIS_BATCH, AXIS_FSDP, AXIS_STAGE, AXIS_EXPERT, AXIS_SEQUENCE, AXIS_MODEL)


@dataclasses.dataclass
class MeshSpec:
    axes: dict[str, int]  # ordered: outermost (DCN-ish) first, tp innermost

    def __str__(self) -> str:
        return ",".join(f"{k}={v}" for k, v in self.axes.items())

    @property
    def size(self) -> int:
        return math.prod(self.axes.values())


def parse_mesh_spec(spec: str) -> MeshSpec:
    """``"dp=2,tp=4"`` -> MeshSpec. Order in the string is mesh order; put
    the most communication-hungry axis (tp) last so it lands on the
    fastest/nearest ICI neighbors."""
    axes: dict[str, int] = {}
    if not spec.strip():
        raise ValueError("empty mesh spec")
    for part in spec.split(","):
        name, _, size = part.strip().partition("=")
        if not name or not size:
            raise ValueError(f"bad mesh spec segment {part!r} (want name=size)")
        try:
            n = int(size)
        except ValueError:
            raise ValueError(f"bad mesh axis size {size!r}") from None
        if n == 0 or n < -1:
            raise ValueError(f"bad mesh axis size {n} for {name}")
        if name in axes:
            raise ValueError(f"duplicate mesh axis {name!r}")
        axes[name] = n
    if sum(1 for v in axes.values() if v == -1) > 1:
        raise ValueError("at most one axis may be -1")
    return MeshSpec(axes=axes)


def make_mesh(spec: str | MeshSpec, devices=None) -> Mesh:
    """Build a Mesh over the given (default: all) devices.

    Axes with -1 absorb remaining devices; total must divide evenly.
    """
    if isinstance(spec, str):
        spec = parse_mesh_spec(spec)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(spec.axes)
    fixed = math.prod(v for v in axes.values() if v != -1)
    for name, v in axes.items():
        if v == -1:
            if n % fixed:
                raise ValueError(f"{n} devices not divisible by {fixed} for axis {name!r}")
            axes[name] = n // fixed
            fixed = math.prod(axes.values())
    total = math.prod(axes.values())
    if total > n:
        raise ValueError(f"mesh {spec} needs {total} devices, have {n}")
    if total < n:
        devices = devices[:total]  # smaller meshes use a device prefix
    arr = np.array(devices).reshape(tuple(axes.values()))
    return Mesh(arr, axis_names=tuple(axes.keys()))


def single_device_mesh() -> Mesh:
    """A 1×... mesh over one device (CPU tests / single-chip serve)."""
    return Mesh(np.array(jax.devices()[:1]).reshape((1,)), axis_names=(AXIS_BATCH,))


def mesh_str(mesh: Mesh) -> str:
    """Canonical ``"dp=2,tp=4"`` form of a live Mesh — the annotation /
    env-key spelling, round-trippable through :func:`parse_mesh_spec`."""
    return ",".join(f"{k}={v}" for k, v in dict(mesh.shape).items())


# axes whose size divides each device's WEIGHT bytes: tensor/expert/stage
# parallelism and ZeRO-3 all shard the parameters themselves. dp and sp
# replicate parameters (they shard batch/sequence), so they never reduce
# the per-device footprint.
WEIGHT_SHARDING_AXES = (AXIS_FSDP, AXIS_STAGE, AXIS_EXPERT, AXIS_MODEL)


def weight_shard_factor(mesh: Mesh) -> int:
    """How many ways the mesh divides a model's weight bytes — the
    per-device footprint divisor the HBM budget uses. A dp-only mesh
    returns 1: every device holds the full replica."""
    return math.prod(
        int(size) for name, size in dict(mesh.shape).items()
        if name in WEIGHT_SHARDING_AXES
    )
