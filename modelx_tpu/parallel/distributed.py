"""Multi-host (multi-process) initialization and host-local helpers.

The reference's only "distributed" machinery is HTTP + S3 multipart
(SURVEY.md §2.2); the TPU build's multi-host story is jax.distributed +
GSPMD: every host runs the same program, `jax.distributed.initialize`
wires the hosts into one runtime, meshes span *all* devices, and the
collectives ride ICI within a slice / DCN across slices. The registry side
needs no changes — each host's loader fetches only the byte ranges of the
shards it can address (loader.py plans from
``sharding.addressable_devices_indices_map``), which is exactly the
"each host fetches its bytes once" contract of SURVEY §7.

On GKE/TPU-pod deployments the coordinator/process-count/process-id come
from the environment (jax.distributed autodetects on Cloud TPU); explicit
arguments or MODELX_* env vars cover everything else (e.g. CPU fleets).
"""

from __future__ import annotations

import logging
import os

import jax

logger = logging.getLogger("modelx.distributed")

_initialized = False
_failed = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Idempotent `jax.distributed.initialize` with env fallbacks.

    Resolution order per argument: explicit > MODELX_COORDINATOR /
    MODELX_NUM_PROCESSES / MODELX_PROCESS_ID env > jax autodetection
    (Cloud TPU pods need no configuration at all). Single-process runs
    (nothing configured, no TPU pod env) are a no-op.
    """
    global _initialized, _failed
    if _initialized or _failed:
        return
    coordinator_address = coordinator_address or os.environ.get("MODELX_COORDINATOR")
    if num_processes is None and os.environ.get("MODELX_NUM_PROCESSES"):
        num_processes = int(os.environ["MODELX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("MODELX_PROCESS_ID"):
        process_id = int(os.environ["MODELX_PROCESS_ID"])

    if coordinator_address is None and num_processes is None and not _on_tpu_pod():
        logger.debug("single-process run; skipping jax.distributed")
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        # pod-ish env vars without a resolvable coordinator (e.g. a single
        # tunneled chip): stay single-process rather than crash the entrypoint
        logger.warning("jax.distributed unavailable (%s); continuing single-process", e)
        _failed = True
        return
    _initialized = True
    logger.info(
        "distributed: process %d/%d, %d local of %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def _on_tpu_pod() -> bool:
    """Cloud TPU pod environments announce themselves; jax autodetects there."""
    return any(
        os.environ.get(k)
        for k in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID")
    )


def process_span() -> tuple[int, int]:
    """(process_index, process_count).

    Calls :func:`initialize` first (idempotent, no-op when single-process):
    querying jax.process_count() before distributed init would silently boot
    a single-process backend and break the later initialize on a pod.
    """
    initialize()
    return jax.process_index(), jax.process_count()


def host_local_slice(total: int) -> tuple[int, int]:
    """Even [start, stop) split of ``total`` items for this process — the
    pattern for sharding host-side work (e.g. which files of a multi-file
    checkpoint this host reads) before device shardings take over."""
    idx, count = process_span()
    per = (total + count - 1) // count
    start = min(idx * per, total)
    return start, min(start + per, total)
