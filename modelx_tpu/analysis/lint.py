"""Core of the AST lint: file walking, rule dispatch, baseline, reporting.

Stdlib-only (ast + os): the gate must run in any environment the repo
builds in, including containers without jax on the path.

A rule is a callable ``rule(ctx) -> Iterable[Finding]`` registered in
``rules/__init__.py``; ``ctx`` is a :class:`ModuleContext` giving it the
parsed tree with parent links, the source, and scope helpers. Findings
are suppressed by ``baseline.toml`` entries keyed on (rule, file, scope)
— scope, not line number, so routine edits above a vetted site don't
resurrect it — and every entry must carry a written justification.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from dataclasses import dataclass, field

DEFAULT_TARGETS = ("modelx_tpu", "bench.py", "scripts")
_SKIP_DIRS = {"__pycache__", ".git", "_build", "node_modules", ".venv"}


@dataclass
class Finding:
    """One violation: where, what rule, and how to fix it."""

    rule: str
    file: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    hint: str = ""
    scope: str = ""  # dotted qualname of the enclosing def/class ("" = module)

    def key(self) -> tuple:
        return (self.file, self.line, self.col, self.rule)

    def render(self, show_hint: bool = True) -> str:
        where = f"{self.file}:{self.line}"
        scope = f" [{self.scope}]" if self.scope else ""
        out = f"{where}: {self.rule}: {self.message}{scope}"
        if show_hint and self.hint:
            out += f"\n    hint: {self.hint}"
        return out


@dataclass
class Suppression:
    rule: str
    file: str
    scope: str = ""
    reason: str = ""
    used: int = field(default=0, compare=False)

    def matches(self, f: Finding) -> bool:
        if self.rule != f.rule or self.file != f.file:
            return False
        if not self.scope:
            return True
        return f.scope == self.scope or f.scope.startswith(self.scope + ".")


class BaselineError(Exception):
    """baseline.toml is malformed (bad syntax, missing reason, ...)."""


def _parse_baseline_toml(text: str, path: str) -> list[Suppression]:
    """Minimal TOML-subset parser for the baseline file (py3.10 has no
    tomllib, and the gate must stay dependency-free). Supported: comments,
    ``[[suppression]]`` table headers, and ``key = "string"`` pairs."""
    sups: list[Suppression] = []
    current: dict[str, str] | None = None

    def flush() -> None:
        nonlocal current
        if current is None:
            return
        missing = {"rule", "file", "reason"} - set(current)
        if missing:
            raise BaselineError(
                f"{path}: suppression {current} is missing {sorted(missing)} "
                "(every baseline entry must name its rule + file and carry a "
                "written justification in `reason`)"
            )
        if not current["reason"].strip():
            raise BaselineError(
                f"{path}: suppression for {current['rule']} at "
                f"{current['file']} has an empty reason; baseline entries "
                "require a written justification"
            )
        sups.append(Suppression(rule=current["rule"], file=current["file"],
                                scope=current.get("scope", ""),
                                reason=current["reason"]))
        current = None

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[suppression]]":
            flush()
            current = {}
            continue
        if "=" in line and current is not None:
            key, _, val = line.partition("=")
            key = key.strip()
            val = val.strip()
            if val.startswith('"') and val.endswith('"') and len(val) >= 2:
                val = val[1:-1]
            else:
                raise BaselineError(
                    f"{path}:{lineno}: value for {key!r} must be a "
                    f'double-quoted string, got {val!r}'
                )
            current[key] = val
            continue
        raise BaselineError(f"{path}:{lineno}: cannot parse line {raw!r}")
    flush()
    return sups


def load_baseline(path: str) -> list[Suppression]:
    with open(path, encoding="utf-8") as f:
        return _parse_baseline_toml(f.read(), path)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.toml")


class ModuleContext:
    """One parsed module handed to every rule: tree with parent links,
    source lines, and scope helpers."""

    def __init__(self, path: str, rel: str, tree: ast.Module, source: str) -> None:
        self.path = path
        self.rel = rel
        self.tree = tree
        self.source = source
        self.lines = source.splitlines()
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the innermost enclosing def/class chain."""
        parts: list[str] = []
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(anc.name)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def finding(self, rule: str, node: ast.AST, message: str, hint: str = "") -> Finding:
        return Finding(rule=rule, file=self.rel, line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0), message=message,
                       hint=hint, scope=self.scope_of(node))


def iter_python_files(targets, root: str):
    """Yield (abs_path, repo_relative_path) for every .py under targets."""
    for target in targets:
        top = target if os.path.isabs(target) else os.path.join(root, target)
        if os.path.isfile(top):
            yield top, os.path.relpath(top, root).replace(os.sep, "/")
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    p = os.path.join(dirpath, name)
                    yield p, os.path.relpath(p, root).replace(os.sep, "/")


def analyze_paths(targets, root: str = ".", rules=None) -> tuple[list[Finding], list[str]]:
    """Run every rule over every file. Returns (findings, errors) where
    errors are files that failed to parse (reported, non-fatal: a syntax
    error is the compiler's job, not the linter's)."""
    from modelx_tpu.analysis.rules import all_rules

    active = rules if rules is not None else all_rules()
    findings: list[Finding] = []
    errors: list[str] = []
    for path, rel in iter_python_files(targets, root):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            errors.append(f"{rel}: {e}")
            continue
        ctx = ModuleContext(path, rel, tree, source)
        for rule in active:
            findings.extend(rule(ctx))
    findings.sort(key=Finding.key)
    return findings, errors


def apply_baseline(findings: list[Finding], sups: list[Suppression]):
    """Split findings into (new, suppressed); marks suppressions used."""
    new: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        for s in sups:
            if s.matches(f):
                s.used += 1
                suppressed.append(f)
                break
        else:
            new.append(f)
    return new, suppressed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m modelx_tpu.analysis",
        description="modelx-tpu concurrency/purity lint (see docs/analysis.md)",
    )
    parser.add_argument("targets", nargs="*", default=[],
                        help=f"files/dirs to scan (default: {', '.join(DEFAULT_TARGETS)})")
    parser.add_argument("--root", default=os.getcwd(),
                        help="repo root findings are reported relative to")
    parser.add_argument("--baseline", default="",
                        help="baseline.toml path (default: the checked-in one)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--rule", action="append", default=[],
                        help="run only these rule ids (repeatable)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="summary line only")
    args = parser.parse_args(argv)

    from modelx_tpu.analysis.rules import all_rules, rule_catalog

    if args.list_rules:
        for rid, doc in rule_catalog().items():
            print(f"{rid}: {doc}")
        return 0

    rules = all_rules()
    if args.rule:
        unknown = set(args.rule) - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in args.rule]

    if args.targets:
        # a typo'd explicit target must not silently turn the gate green
        missing = [t for t in args.targets
                   if not os.path.exists(t)
                   and not os.path.exists(os.path.join(args.root, t))]
        if missing:
            print(f"error: target(s) not found: {', '.join(missing)}",
                  file=sys.stderr)
            return 2
        targets = args.targets
    else:
        targets = [
            t for t in DEFAULT_TARGETS if os.path.exists(os.path.join(args.root, t))
        ]
    findings, errors = analyze_paths(targets, root=args.root, rules=rules)

    sups: list[Suppression] = []
    if not args.no_baseline:
        baseline_path = args.baseline or default_baseline_path()
        if os.path.exists(baseline_path):
            try:
                sups = load_baseline(baseline_path)
            except BaselineError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
    new, suppressed = apply_baseline(findings, sups)

    for err in errors:
        print(f"parse error: {err}", file=sys.stderr)
    if not args.quiet:
        for f in new:
            print(f.render())
        unused = [s for s in sups if not s.used]
        for s in unused:
            print(f"warning: unused baseline suppression {s.rule} @ "
                  f"{s.file}" + (f" [{s.scope}]" if s.scope else "") +
                  " — remove it", file=sys.stderr)
    print(f"modelx-analysis: {len(new)} new finding(s), "
          f"{len(suppressed)} baseline-suppressed, "
          f"{len(findings)} total across {len(set(f.file for f in findings)) or 0} file(s)")
    return 1 if new else 0
