"""Project-native static analysis + runtime lock-order checking.

Five PRs of threaded serving work (engine supervisor, lifecycle pool,
crash-safe GC, blob-cache LRU) rest on invariants that were, until now,
prose: "heavy teardown runs outside the pool lock", "every handler error
is typed", "acquire is always pinned by try/finally". This package turns
those rules into machine checks so the GSPMD-mesh refactor (ROADMAP top
item) cannot silently reintroduce the hazards we already paid to remove.

Two halves:

- **AST lint** (`lint.py` + `rules/`): ``python -m modelx_tpu.analysis``
  walks the tree and enforces six rules written against this codebase's
  real hazards (blocking-under-lock, lock-leak, untyped-handler-error,
  bare-thread, swallowed-exception, jax-impurity). Findings carry
  ``file:line``, a rule id, and a fix hint; ``baseline.toml`` suppresses
  individually vetted sites (justification required) so the gate starts
  green and only NEW violations fail CI.

- **Runtime lockdep** (`lockdep.py` + `pytest_lockdep.py`): a TSan-lite
  instrumented Lock/RLock (env-gated ``MODELX_LOCKDEP=1``, zero overhead
  when off) that records per-thread acquisition order into a global
  lock-order graph, reports cycles (potential deadlocks) and
  over-threshold holds with both stacks, and rides the chaos/lifecycle
  pytest drills as a plugin.

See docs/analysis.md for the rule catalog and workflow.
"""

from modelx_tpu.analysis.lint import Finding, analyze_paths, main  # noqa: F401
