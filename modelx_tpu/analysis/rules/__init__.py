"""Rule registry + shared AST helpers for the modelx-tpu lint.

Each rule module registers callables with :func:`register`; a rule is
``rule(ctx: ModuleContext) -> Iterable[Finding]`` with a ``rule_id``
attribute. The ids are stable (baseline entries reference them):

- ``blocking-under-lock``  network/file I/O, sleeps, device transfers,
  future waits, or subprocesses while holding a lock
- ``lock-leak``            ``acquire()`` not pinned by try/finally
- ``untyped-handler-error`` raise reaching an HTTP handler that is not a
  typed serving/registry error
- ``bare-thread``          ``threading.Thread`` without a daemon flag or
  a supervised join
- ``swallowed-exception``  silent ``except: pass`` on server paths
- ``jax-impurity``         wall-clock/RNG calls inside jitted program
  builders (they freeze at trace time)
"""

from __future__ import annotations

import ast
import re

_REGISTRY: list = []


def register(rule_id: str, doc: str):
    """Decorator: register ``fn`` as a lint rule under ``rule_id``."""

    def deco(fn):
        fn.rule_id = rule_id
        fn.rule_doc = doc
        _REGISTRY.append(fn)
        return fn

    return deco


def all_rules() -> list:
    _load()
    return list(_REGISTRY)


def rule_catalog() -> dict[str, str]:
    _load()
    return {r.rule_id: r.rule_doc for r in _REGISTRY}


_loaded = False


def _load() -> None:
    global _loaded
    if _loaded:
        return
    # import for registration side effects
    from modelx_tpu.analysis.rules import handlers, locks, purity, threads  # noqa: F401

    _loaded = True


# -- shared AST helpers ---------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``time.sleep`` for
    ``time.sleep(...)``, ``.result`` for ``fut.result`` (unknown
    receiver), ``open`` for a bare name."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        if base and not base.startswith("."):
            return f"{base}.{node.attr}"
        return f".{node.attr}"
    if isinstance(node, ast.Call):
        return dotted_name(node.func)
    return ""


def terminal_name(node: ast.AST) -> str:
    """The last path component of an expression: ``_lock`` for
    ``self._lock``, ``lock`` for ``lock``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return terminal_name(node.func)
    return ""


_LOCK_NAME_RE = re.compile(r"(^|_)(lock|locks|rlock|mutex|mtx|cv|cond|guard)s?($|_)",
                           re.IGNORECASE)


def lock_named(name: str) -> bool:
    return bool(name) and bool(_LOCK_NAME_RE.search(name))


_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
    # lockdep's instrumented wrappers are locks too
    "lockdep.Lock", "lockdep.RLock",
}


def module_lock_names(tree: ast.Module) -> set[str]:
    """Names/attributes assigned from ``threading.Lock()`` & co anywhere
    in the module — catches locks whose names don't look lock-ish
    (``self._profiling = threading.Lock()``)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and dotted_name(node.value.func) in _LOCK_FACTORIES):
            continue
        for tgt in node.targets:
            t = terminal_name(tgt)
            if t:
                names.add(t)
    return names


def is_lock_expr(node: ast.AST, known_locks: set[str]) -> bool:
    """Heuristic: does this with-item / receiver look like a lock? Either
    its terminal name matches the lock-naming convention, it was assigned
    from a lock factory in this module, or it's a ``_repo_lock(...)``-style
    accessor call whose name says lock."""
    t = terminal_name(node)
    return lock_named(t) or t in known_locks


def body_nodes_outside_nested_defs(stmts) -> list[ast.AST]:
    """Every node lexically inside ``stmts`` that actually EXECUTES there:
    nested function/class bodies are skipped (they run later, not under
    the enclosing with/lock), but their decorators/defaults do execute."""
    out: list[ast.AST] = []
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(node.args.defaults)
            stack.extend(d for d in (node.args.kw_defaults or []) if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.ClassDef):
            stack.extend(node.decorator_list)
            stack.extend(node.bases)
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out
