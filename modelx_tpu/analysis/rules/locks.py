"""Lock-discipline rules: blocking-under-lock and lock-leak.

The invariants these enforce were written in prose across PRs 1-5:

- "heavy teardown runs OUTSIDE the pool lock" (dl/lifecycle.py
  ``_finish_free``), "one tenant's teardown must not stall admission";
- "the engine loop never sleeps holding ``_close_lock``";
- every manual ``acquire()`` is released on every path, including the
  exception ones.

``blocking-under-lock`` flags calls that block on the network, disk,
device, a future, a subprocess, or the wall clock while a lock is
lexically held (a ``with <lock>:`` body, or a ``try`` immediately
following a bare ``x.acquire()``). ``Condition.wait`` is exempt — it
releases the lock while waiting. Nested ``def``/``lambda`` bodies are
exempt — they run later, not under the lock.

``lock-leak`` flags statement-form ``x.acquire()`` whose release is not
pinned by a ``finally`` in the same function.
"""

from __future__ import annotations

import ast

from modelx_tpu.analysis.rules import (
    body_nodes_outside_nested_defs,
    dotted_name,
    is_lock_expr,
    module_lock_names,
    register,
    terminal_name,
)

# dotted-name prefixes/exacts that block. ``.name`` entries match any
# receiver (attribute calls); bare entries match exact dotted paths.
_BLOCKING_EXACT = {
    "time.sleep",
    "open", "os.replace", "os.rename", "os.renames", "os.unlink", "os.remove",
    "os.stat", "os.lstat", "os.listdir", "os.scandir", "os.makedirs",
    "os.mkdir", "os.rmdir", "os.removedirs", "os.fsync", "os.ftruncate",
    "os.truncate", "os.pwrite", "os.pread", "os.utime", "os.kill",
    "os.path.getsize", "os.path.getmtime", "os.path.exists", "os.path.isfile",
    "os.path.isdir",
    "shutil.rmtree", "shutil.copy", "shutil.copy2", "shutil.copyfile",
    "shutil.copytree", "shutil.move",
    "jax.device_put", "device_put", "jax.block_until_ready",
    "socket.create_connection",
}
_BLOCKING_PREFIX = (
    "requests.", "urllib.", "subprocess.", "http.client.",
)
_BLOCKING_METHOD = {
    # attribute calls on any receiver
    "result",            # Future.result() — waits for another thread
    "block_until_ready",  # device sync
    "urlopen",
    "device_put",
}
# the registry's FSProvider seam (registry/fs.py): `self.fs.put(...)` is
# local-disk OR S3/GCS network I/O depending on deployment — under a lock
# it must be a deliberate, documented serialization (baseline it), never
# an accident
_PROVIDER_RECEIVER = "fs"
_PROVIDER_METHODS = {"put", "get", "stat", "remove", "exists", "list"}

# methods that look blocking but must NOT count
_EXEMPT_METHOD = {
    "wait",       # Condition.wait / Event.wait: Condition RELEASES the lock;
                  # Event.wait under a lock would still be a hazard, but the
                  # repo convention is Conditions — keep the rule precise
    "notify", "notify_all",
}

_RULE_BLOCK = "blocking-under-lock"
_RULE_LEAK = "lock-leak"


def _is_blocking_call(call: ast.Call) -> str | None:
    """The matched blocking-name, or None."""
    name = dotted_name(call.func)
    if not name:
        return None
    if name in _BLOCKING_EXACT:
        return name
    for p in _BLOCKING_PREFIX:
        if name.startswith(p):
            return name
    if isinstance(call.func, ast.Attribute):
        meth = call.func.attr
        if meth in _EXEMPT_METHOD:
            return None
        if meth in _BLOCKING_METHOD:
            return name
        if (meth in _PROVIDER_METHODS
                and terminal_name(call.func.value) == _PROVIDER_RECEIVER):
            return name
    return None


def _held_regions(ctx, known_locks):
    """Yield (lock_label, stmts, witness_node) for every lexical region
    that runs with a lock held."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.With):
            lock_items = [i.context_expr for i in node.items
                          if is_lock_expr(i.context_expr, known_locks)]
            if lock_items:
                yield dotted_name(lock_items[0]) or terminal_name(lock_items[0]), node.body, node
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # bare `x.acquire()` statement followed by a try whose finally
            # releases: the try body is the held region
            yield from _manual_regions(node, known_locks)


def _manual_regions(fn, known_locks):
    for stmts in _stmt_blocks(fn):
        for i, stmt in enumerate(stmts):
            recv = _acquire_receiver(stmt)
            if recv is None:
                # conditional probe: `if not x.acquire(blocking=False): ...`
                # followed by the pinned try — the try body holds the lock
                recv = _conditional_acquire_receiver(stmt)
            if recv is None or not is_lock_expr(recv, known_locks):
                continue
            if i + 1 < len(stmts) and isinstance(stmts[i + 1], ast.Try):
                yield dotted_name(recv) or terminal_name(recv), stmts[i + 1].body, stmts[i + 1]


def _stmt_blocks(fn):
    """Every statement list inside ``fn`` (body, orelse, finalbody, ...),
    not descending into nested defs."""
    blocks = [fn.body]
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                             ast.ClassDef)):
            continue
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(node, attr, None)
            if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                blocks.append(sub)
                stack.extend(sub)
        for h in getattr(node, "handlers", []) or []:
            blocks.append(h.body)
            stack.extend(h.body)
    return blocks


def _acquire_receiver(stmt):
    """The receiver expr of a statement-form ``x.acquire(...)``, else None."""
    if (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "acquire"):
        return stmt.value.func.value
    return None


def _conditional_acquire_receiver(stmt):
    """The receiver of an ``.acquire(...)`` appearing in an If test (the
    non-blocking probe shape: ``if not x.acquire(blocking=False):``)."""
    if not isinstance(stmt, ast.If):
        return None
    for n in ast.walk(stmt.test):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "acquire"):
            return n.func.value
    return None


@register(_RULE_BLOCK, "network/file I/O, sleeps, device transfers, future "
                       "waits, or subprocesses while holding a lock")
def blocking_under_lock(ctx):
    known_locks = module_lock_names(ctx.tree)
    findings = []
    seen = set()
    for label, stmts, _witness in _held_regions(ctx, known_locks):
        for node in body_nodes_outside_nested_defs(stmts):
            # a nested `with <other lock>` region is reported once, for
            # the innermost lock it blocks under — dedup on position
            if not isinstance(node, ast.Call):
                continue
            matched = _is_blocking_call(node)
            if matched is None:
                continue
            pos = (node.lineno, node.col_offset)
            if pos in seen:
                continue
            seen.add(pos)
            findings.append(ctx.finding(
                _RULE_BLOCK, node,
                f"{matched}() while holding {label!r}",
                hint="move the blocking call outside the lock (collect work "
                     "under the lock, perform it after release — see "
                     "ModelPool._free_entry_locked/_finish_free for the "
                     "split pattern)",
            ))
    return findings


@register(_RULE_LEAK, "acquire() not pinned by try/finally")
def lock_leak(ctx):
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for stmts in _stmt_blocks(node):
            for i, stmt in enumerate(stmts):
                recv = _acquire_receiver(stmt)
                if recv is None:
                    continue
                if _release_pinned(ctx, stmts, i, stmt, recv):
                    continue
                label = dotted_name(recv) or terminal_name(recv)
                findings.append(ctx.finding(
                    _RULE_LEAK, stmt,
                    f"{label}.acquire() is not pinned by try/finally",
                    hint="follow acquire() immediately with `try: ... "
                         f"finally: {label}.release()` (or use `with "
                         f"{label}:`) so an exception cannot leak the lock",
                ))
    return findings


def _release_pinned(ctx, stmts, i, stmt, recv) -> bool:
    """Is the acquire at stmts[i] released in a finally? Accepts the
    canonical shape (next statement is a Try with release in finalbody)
    and the acquire-inside-a-try-whose-finally-releases shape."""
    target = ast.dump(recv)

    def releases(block) -> bool:
        for s in block:
            for n in ast.walk(s):
                if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "release"
                        and ast.dump(n.func.value) == target):
                    return True
        return False

    nxt = stmts[i + 1] if i + 1 < len(stmts) else None
    if isinstance(nxt, ast.Try) and releases(nxt.finalbody):
        return True
    for anc in ctx.ancestors(stmt):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(anc, ast.Try) and releases(anc.finalbody):
            return True
    return False
